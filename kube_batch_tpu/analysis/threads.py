"""A7 — concurrency sanitizer: thread lifecycle & shared-state escape
(KBT-T001/T002/T003, its own CLI:
``python -m kube_batch_tpu.analysis.threads``).

PRs 15-18 multiplied the live threads per process — the backend watch
pump, the shard-lease renewer/prober, the backpressure tick path, the
fleet scrape loop, the kb-write pool, the pipeline dispatch fence — and
the existing analyzers only prove *lock ordering* (KBT-D) and declared
*lock discipline* (KBT-L). This module closes the remaining gap three
ways, all stdlib-AST so the bare container runs it:

- **KBT-T001 thread lifecycle**: every ``threading.Thread`` /
  ``ThreadPoolExecutor`` construction must have a reachable *bounded*
  ``join(timeout=...)``/``shutdown()`` path or an explicit
  ``daemon=True`` annotation — tracked interprocedurally across the
  binding (self attribute: class-wide; local: function-wide; module
  global: module-wide; collection appends and loop/alias joins
  resolve), the way KBT-C tracks Statement lifecycles. A ``with``
  executor and an ownership transfer (returning the thread, passing it
  to a call) end the obligation.
- **KBT-T002 shared-state escape**: two-phase. Phase one infers each
  class's *thread roots* — methods reached from ``Thread(target=...)``
  / ``*.submit(...)`` call sites (plus the seed-root map below for
  dynamic dispatch the AST cannot see, e.g. the admission gate's HTTP
  handler threads), plus a synthetic ``(callers)`` root for everything
  invoked from the owning thread. Phase two walks each root's
  self-call closure charging ``self.<field>`` reads/writes (subscript
  stores and mutating container calls count as writes), and flags any
  field written from ≥2 roots — or written in one root and read in
  another, or written from a *multi* root (a pool callable / a thread
  started in a loop) — that carries no guard under the KBT-L
  declaration surface (the seed map or ``#: guarded_by``). Declared
  fields are KBT-L's domain and stay silent here: the two analyzers
  share one declaration surface.
- **KBT-T003 atomicity**: a guarded field read under its lock in one
  ``with`` region and written back under a *different* region of the
  same lock in the same function, with no re-read before the write —
  the split read-modify-write another thread can interleave.

Findings triage like every other family: fix, or reason-baseline in
``hack/lint-baseline.toml`` (this CLI applies/prunes only the KBT-T
slice of the shared file). The seeded fixtures at the bottom are the
self-check: the CLI fails unless every code fires on its positive
fixture and stays silent on the negative twin, and unless the runtime
:class:`~kube_batch_tpu.utils.race.RaceWitness` drills pass
(ordered-by-lock clean, ordered-by-join clean, true race caught with a
deterministic trace id). ``--witness-drive`` additionally drives the
witness over the live streaming-federation bind path (the absorb-mode
``StreamTrigger`` under concurrent peer churn + drain).
"""

from __future__ import annotations

import ast
from typing import Optional

from kube_batch_tpu.analysis import Finding, SourceFile
from kube_batch_tpu.analysis.lock_discipline import (
    SEED_GUARDED,
    _annotated_guards,
    _class_locks,
    _is_assume_locked,
)

__all__ = [
    "SEED_ROOTS",
    "analyze",
    "selfcheck",
    "witness_selfcheck",
    "witness_drive",
    "main",
]

_THREAD_CTORS = {"Thread"}
_POOL_CTORS = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
_CTORS = _THREAD_CTORS | _POOL_CTORS

# Method names whose call mutates the receiver in place — a
# ``self.F.append(...)`` is a write to F for escape purposes even
# though the AST only shows a Load of F.
_MUTATORS = {
    "append", "add", "update", "pop", "popitem", "clear", "remove",
    "discard", "extend", "insert", "setdefault", "appendleft",
    "popleft", "difference_update", "intersection_update",
    "symmetric_difference_update",
}

# Pool-submission entry points that make their callable argument a
# thread root (the kb-write pool wrappers on top of plain submit).
_SUBMITTERS = {"submit", "_submit_write", "submit_dispatch"}

# Field types that are themselves synchronization/thread-safe objects:
# calls on them are their own discipline, not shared-state escape.
_ATOMIC_TYPES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue", "LifoQueue",
    "PriorityQueue", "deque", "local", "ThreadPoolExecutor",
    "RateLimitingQueue",
}

# (path, class) -> {root name: (entry methods, multi)} for thread roots
# the AST cannot infer because the dispatch is dynamic: the admission
# gate's methods run on the lease server's HTTP handler threads (many
# at once), and the dispatch fence's record_join callback runs on
# kb-write pool threads while arm/wait run on the cycle thread.
SEED_ROOTS: dict[tuple[str, str], dict[str, tuple[tuple[str, ...], bool]]] = {
    ("kube_batch_tpu/admission.py", "AdmissionGate"): {
        "http-handlers": (("decide", "note_done"), True),
    },
    ("kube_batch_tpu/pipeline.py", "DispatchFence"): {
        "kb-write-pool": (("record_join",), True),
        "cycle": (("arm", "wait", "reset", "degrade"), False),
    },
    ("kube_batch_tpu/obs/fleet.py", "FleetAggregator"): {
        "kb-fleet-scrape": (("_scrape_one",), True),
    },
}


def _last_name(fn: ast.expr) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_ctor(call: ast.Call) -> Optional[str]:
    """'thread' | 'pool' | None for a Call node."""
    name = _last_name(call.func)
    if name in _THREAD_CTORS:
        return "thread"
    if name in _POOL_CTORS:
        return "pool"
    return None


def _noqa(sf: SourceFile, lineno: int) -> bool:
    lines = sf.lines
    return 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]


# -- shared context plumbing --------------------------------------------------


def _contexts(tree: ast.AST):
    """id(node) -> (class name | None, function node | None,
    frozenset of names the function declared ``global``)."""
    ctx_of: dict[int, tuple] = {}

    def assign(node, ctx):
        ctx_of[id(node)] = ctx
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                assign(child, (child.name, ctx[1], ctx[2]))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                gl = frozenset(
                    n
                    for s in ast.walk(child)
                    if isinstance(s, ast.Global)
                    for n in s.names
                )
                assign(child, (ctx[0], child, gl))
            else:
                assign(child, ctx)

    assign(tree, (None, None, frozenset()))
    return ctx_of


def _parents(tree: ast.AST) -> dict[int, ast.AST]:
    out: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[id(child)] = node
    return out


# -- KBT-T001: thread lifecycle ----------------------------------------------
#
# Binding keys: ("self", cls, attr) | ("local", id(fn), name) |
# ("global", name). Evidence kinds: "daemon", "join_b" (bounded),
# "join_u" (no timeout), "shutdown".


def _unwrap_iter(e: ast.expr) -> ast.expr:
    """list(xs)/sorted(xs)/tuple(xs)/reversed(xs) -> xs."""
    if (
        isinstance(e, ast.Call)
        and isinstance(e.func, ast.Name)
        and e.func.id in ("list", "sorted", "tuple", "reversed")
        and e.args
    ):
        return e.args[0]
    return e


def _expr_key(e: ast.expr, cls, fn, gl) -> Optional[tuple]:
    if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name):
        if e.value.id == "self" and cls is not None:
            return ("self", cls, e.attr)
        return None
    if isinstance(e, ast.Name):
        if fn is None or e.id in gl:
            return ("global", e.id)
        return ("local", id(fn), e.id)
    return None


def _aliases(fn: Optional[ast.AST], tree: ast.AST, cls, gl) -> dict:
    """name -> binding key, from ``x = self.attr`` and ``for t in xs``
    (including comprehension generators) within one function scope."""
    scope = fn if fn is not None else tree
    out: dict[str, tuple] = {}
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                key = _expr_key(node.value, cls, fn, gl)
                if key is not None and key != ("local", id(fn), t.id):
                    out[t.id] = key
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            if isinstance(node.target, ast.Name):
                key = _expr_key(_unwrap_iter(node.iter), cls, fn, gl)
                if key is not None:
                    out[node.target.id] = key
        elif isinstance(node, ast.comprehension):
            if isinstance(node.target, ast.Name):
                key = _expr_key(_unwrap_iter(node.iter), cls, fn, gl)
                if key is not None:
                    out[node.target.id] = key
    return out


def _resolve(e: ast.expr, cls, fn, gl, alias: dict) -> Optional[tuple]:
    key = _expr_key(e, cls, fn, gl)
    for _ in range(2):  # x = self._threads; for t in x: ...
        if key is not None and key[0] == "local" and key[2] in alias:
            nxt = alias[key[2]]
            if nxt == key:
                break
            key = nxt
        else:
            break
    return key


def _has_daemon_kwarg(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and bool(kw.value.value)
    return False


def _t001(sf: SourceFile, ctx_of, parents, findings: list[Finding]) -> None:
    alias_cache: dict[int, dict] = {}

    def alias_for(fn, cls, gl) -> dict:
        k = id(fn) if fn is not None else 0
        if k not in alias_cache:
            alias_cache[k] = _aliases(fn, sf.tree, cls, gl)
        return alias_cache[k]

    # evidence maps
    self_ev: dict[tuple, set] = {}
    local_ev: dict[tuple, set] = {}
    global_ev: dict[str, set] = {}

    def record(key: Optional[tuple], kind: str) -> None:
        if key is None:
            return
        if key[0] == "self":
            self_ev.setdefault((key[1], key[2]), set()).add(kind)
        elif key[0] == "local":
            local_ev.setdefault((key[1], key[2]), set()).add(kind)
        else:
            global_ev.setdefault(key[1], set()).add(kind)

    for node in ast.walk(sf.tree):
        cls, fn, gl = ctx_of.get(id(node), (None, None, frozenset()))
        alias = alias_for(fn, cls, gl)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr == "join":
                key = _resolve(node.func.value, cls, fn, gl, alias)
                bounded = bool(node.args or node.keywords)
                record(key, "join_b" if bounded else "join_u")
            elif node.func.attr == "shutdown":
                record(_resolve(node.func.value, cls, fn, gl, alias), "shutdown")
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon":
                    if (
                        isinstance(node.value, ast.Constant)
                        and bool(node.value.value)
                    ):
                        record(_resolve(t.value, cls, fn, gl, alias), "daemon")

    # ctor sites
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _is_ctor(node)
        if kind is None:
            continue
        cls, fn, gl = ctx_of.get(id(node), (None, None, frozenset()))
        alias = alias_for(fn, cls, gl)
        if _noqa(sf, node.lineno):
            continue
        if kind == "thread" and _has_daemon_kwarg(node):
            continue
        parent = parents.get(id(node))
        key: Optional[tuple] = None
        anonymous_start = False
        if isinstance(parent, ast.withitem):
            continue  # `with ThreadPoolExecutor() as x:` shuts down
        if isinstance(parent, ast.Assign):
            key = _resolve(parent.targets[0], cls, fn, gl, alias)
        elif (
            isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Attribute)
            and parent.func.attr == "append"
        ):
            key = _resolve(parent.func.value, cls, fn, gl, alias)
        elif isinstance(parent, ast.Attribute) and parent.attr == "start":
            anonymous_start = True
        elif isinstance(parent, (ast.Return, ast.Call, ast.Dict, ast.Tuple,
                                 ast.List, ast.Set)):
            continue  # ownership transferred to the caller / a collection
        elif isinstance(parent, ast.Expr):
            pass  # bare discarded ctor: key stays None -> finding
        else:
            continue  # unrecognized binding shape: stay quiet

        if key is not None:
            if key[0] == "self":
                ev = self_ev.get((key[1], key[2]), set())
                desc = f"self.{key[2]}"
                sym = f"{key[1]}.{key[2]}"
            elif key[0] == "local":
                ev = local_ev.get((key[1], key[2]), set())
                desc = f"local {key[2]!r}"
                sym = f"{cls + '.' if cls else ''}{fn.name if fn else '<module>'}.{key[2]}"
            else:
                ev = global_ev.get(key[1], set())
                desc = f"module global {key[1]!r}"
                sym = f"<module>.{key[1]}"
        else:
            ev = set()
            desc = "an anonymous handle" if anonymous_start else "no handle"
            scope = f"{cls + '.' if cls else ''}{fn.name if fn else '<module>'}"
            sym = f"{scope}.<anonymous>"

        what = "Thread" if kind == "thread" else "executor pool"
        if "daemon" in ev or "join_b" in ev or "shutdown" in ev:
            continue
        if "join_u" in ev:
            findings.append(
                Finding(
                    sf.path, node.lineno, "KBT-T001",
                    f"{what} bound to {desc} is only ever joined without a "
                    "timeout — a wedged worker hangs shutdown forever; pass "
                    "join(timeout=...) and escalate on leak",
                    symbol=sym,
                )
            )
        else:
            findings.append(
                Finding(
                    sf.path, node.lineno, "KBT-T001",
                    f"{what} bound to {desc} has no reachable bounded "
                    "join/shutdown path and no daemon annotation — the "
                    "worker outlives its owner and hangs process teardown "
                    "(add stop()+join(timeout=...)/shutdown(), or mark "
                    "daemon=True where a supervisor polls it)",
                    symbol=sym,
                )
            )


# -- KBT-T002: shared-state escape -------------------------------------------


def _methods_of(cls: ast.ClassDef) -> dict[str, ast.AST]:
    return {
        m.name: m
        for m in cls.body
        if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _atomic_fields(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _last_name(node.value.func) in _ATOMIC_TYPES:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out.add(t.attr)
    return out


def _field_accesses(fn_node: ast.AST, skip_nested: bool = True):
    """[(field, 'r'|'w', lineno, attr node)] for every ``self.<F>``
    touch in one function body. Subscript stores/deletes and mutating
    container calls on ``self.F`` count as writes; nested function
    bodies are skipped (they run on whichever thread invokes the
    callback, so they are charged as their own root or not at all)."""
    consumed: set[int] = set()
    out = []

    def is_self_attr(e) -> bool:
        return (
            isinstance(e, ast.Attribute)
            and isinstance(e.value, ast.Name)
            and e.value.id == "self"
        )

    def walk(node, top: bool) -> None:
        if not top and skip_nested and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv = node.func.value
            if node.func.attr in _MUTATORS and is_self_attr(recv):
                out.append((recv.attr, "w", recv.lineno, recv))
                consumed.add(id(recv))
        elif isinstance(node, (ast.Subscript,)) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            if is_self_attr(node.value):
                out.append((node.value.attr, "w", node.value.lineno, node.value))
                consumed.add(id(node.value))
        elif isinstance(node, ast.AugAssign) and is_self_attr(node.target):
            out.append((node.target.attr, "w", node.target.lineno, node.target))
            consumed.add(id(node.target))
        elif isinstance(node, ast.Attribute) and is_self_attr(node):
            if id(node) not in consumed:
                kind = "r" if isinstance(node.ctx, ast.Load) else "w"
                out.append((node.attr, kind, node.lineno, node))
                consumed.add(id(node))
        for child in ast.iter_child_nodes(node):
            walk(child, False)

    walk(fn_node, True)
    # a Store target's inner Attribute is visited before we know the
    # ctx on some shapes; dedupe identical (node) entries keeping 'w'
    best: dict[int, tuple] = {}
    for field, kind, line, node in out:
        cur = best.get(id(node))
        if cur is None or (cur[1] == "r" and kind == "w"):
            best[id(node)] = (field, kind, line, node)
    return sorted(best.values(), key=lambda a: (a[2], a[0]))


def _self_calls(fn_node: ast.AST, methods: dict) -> set[str]:
    out: set[str] = set()

    def walk(node, top: bool) -> None:
        if not top and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            return
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            f = node.func
            if (
                isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and f.attr in methods
            ):
                out.add(f.attr)
        for child in ast.iter_child_nodes(node):
            walk(child, False)

    walk(fn_node, True)
    return out


def _infer_roots(sf: SourceFile, cls: ast.ClassDef, methods: dict):
    """root name -> (entries, multi). An entry is a method name or a
    nested FunctionDef node (a closure passed as Thread target)."""
    roots: dict[str, tuple[list, bool]] = {}

    def add(name: str, entry, multi: bool) -> None:
        entries, m = roots.get(name, ([], False))
        if entry not in entries:
            entries.append(entry)
        roots[name] = (entries, m or multi)

    for mname, mnode in methods.items():
        nested = {
            n.name: n
            for n in ast.walk(mnode)
            if isinstance(n, ast.FunctionDef) and n is not mnode
        }
        loop_depth_of = {}

        def tag(node, depth, loop_depth_of=loop_depth_of):
            loop_depth_of[id(node)] = depth
            for child in ast.iter_child_nodes(node):
                tag(
                    child,
                    depth
                    + int(isinstance(node, (ast.For, ast.While, ast.AsyncFor))),
                )

        tag(mnode, 0)
        for node in ast.walk(mnode):
            if not isinstance(node, ast.Call):
                continue
            in_loop = loop_depth_of.get(id(node), 0) > 0
            if _is_ctor(node) == "thread":
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    v = kw.value
                    if (
                        isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id == "self"
                        and v.attr in methods
                    ):
                        add(v.attr, v.attr, in_loop)
                    elif isinstance(v, ast.Name) and v.id in nested:
                        add(f"{mname}:{v.id}", nested[v.id], in_loop)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SUBMITTERS
            ) or (
                isinstance(node.func, ast.Name)
                and node.func.id in _SUBMITTERS
            ):
                for a in node.args:
                    if (
                        isinstance(a, ast.Attribute)
                        and isinstance(a.value, ast.Name)
                        and a.value.id == "self"
                        and a.attr in methods
                    ):
                        add(a.attr, a.attr, True)

    for root, (entries, multi) in SEED_ROOTS.get((sf.path, cls.name), {}).items():
        for e in entries:
            if e in methods:
                add(root, e, multi)
    return roots


def _closure(entries: list, methods: dict, blocked: set) -> list:
    """Transitive self-call closure from ``entries`` (method names or
    nested function nodes), never descending into ``blocked`` methods
    (another root's entry runs on that root's thread)."""
    seen: set[str] = set()
    out: list = []
    frontier = list(entries)
    while frontier:
        e = frontier.pop()
        if isinstance(e, str):
            if e in seen or e in ("__init__", "__del__"):
                continue
            seen.add(e)
            node = methods.get(e)
            if node is None:
                continue
        else:
            node = e  # nested def root entry
        out.append(node)
        for callee in sorted(_self_calls(node, methods)):
            if callee not in blocked and callee not in seen:
                frontier.append(callee)
    return out


def _t002(
    sf: SourceFile,
    cls: ast.ClassDef,
    guards: dict[str, str],
    findings: list[Finding],
) -> None:
    methods = _methods_of(cls)
    roots = _infer_roots(sf, cls, methods)
    if not roots:
        return
    root_entry_methods = {
        e for entries, _ in roots.values() for e in entries if isinstance(e, str)
    }
    caller_entries = [
        m
        for m in methods
        if m not in root_entry_methods and m not in ("__init__", "__del__")
    ]
    if caller_entries:
        roots["(callers)"] = (caller_entries, False)

    skip = set(guards) | _class_locks(cls) | _atomic_fields(cls) | set(methods)
    # field -> root -> {'r','w'}; field -> first write (line) for anchor
    touched: dict[str, dict[str, set]] = {}
    first_write: dict[str, tuple[int, str]] = {}
    for root, (entries, _multi) in sorted(roots.items()):
        blocked = root_entry_methods - {
            e for e in entries if isinstance(e, str)
        }
        for node in _closure(entries, methods, blocked):
            for field, kind, line, _n in _field_accesses(node):
                if field in skip:
                    continue
                touched.setdefault(field, {}).setdefault(root, set()).add(kind)
                if kind == "w":
                    cur = first_write.get(field)
                    if cur is None or line < cur[0]:
                        first_write[field] = (line, root)

    for field, by_root in sorted(touched.items()):
        writers = [r for r, kinds in by_root.items() if "w" in kinds]
        if not writers:
            continue
        multi_writer = any(roots[r][1] for r in writers)
        if len(by_root) < 2 and not multi_writer:
            continue
        line, _ = first_write[field]
        if _noqa(sf, line):
            continue
        readers = sorted(r for r in by_root if r not in writers)
        detail = "written from " + ", ".join(
            f"{r}{' (xN)' if roots.get(r, (None, False))[1] else ''}"
            for r in sorted(writers)
        )
        if readers:
            detail += "; read from " + ", ".join(readers)
        findings.append(
            Finding(
                sf.path, line, "KBT-T002",
                f"self.{field} escapes to multiple thread roots with no "
                f"declared guard ({detail}) — annotate `#: guarded_by "
                "<lock>` on its __init__ line (KBT-L then enforces the "
                "discipline) or confine it to one thread",
                symbol=f"{cls.name}.{field}",
            )
        )


# -- KBT-T003: split read-modify-write ---------------------------------------


def _t003(
    sf: SourceFile,
    cls: ast.ClassDef,
    guards: dict[str, str],
    findings: list[Finding],
) -> None:
    lock_names = set(guards.values())

    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if meth.name in ("__init__", "__del__") or meth.name.endswith("_locked"):
            continue
        if _is_assume_locked(meth):
            continue
        region_of: dict[int, dict] = {}
        # region id -> {if-node id: branch index} — two regions in
        # sibling branches of one If are mutually exclusive paths and
        # never pair up
        branch_of: dict[int, dict] = {}
        counter = [0]

        def tag(node, current, branches, region_of=region_of, counter=counter):
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) and region_of:
                return  # nested defs run elsewhere
            region_of[id(node)] = current
            if isinstance(node, ast.With):
                acquired = []
                for item in node.items:
                    e = item.context_expr
                    for sub in ast.walk(e):
                        region_of[id(sub)] = current
                    if (
                        isinstance(e, ast.Attribute)
                        and isinstance(e.value, ast.Name)
                        and e.value.id == "self"
                        and e.attr in lock_names
                    ):
                        acquired.append(e.attr)
                inner = dict(current)
                for a in acquired:
                    counter[0] += 1
                    inner[a] = counter[0]
                    branch_of[counter[0]] = dict(branches)
                for stmt in node.body:
                    tag(stmt, inner, branches)
                return
            if isinstance(node, ast.If):
                for sub in ast.walk(node.test):
                    region_of[id(sub)] = current
                for stmt in node.body:
                    tag(stmt, current, {**branches, id(node): 0})
                for stmt in node.orelse:
                    tag(stmt, current, {**branches, id(node): 1})
                return
            for child in ast.iter_child_nodes(node):
                tag(child, current, branches)

        tag(meth, {}, {})

        def same_path(ra: int, rb: int) -> bool:
            ba, bb = branch_of.get(ra, {}), branch_of.get(rb, {})
            return all(bb[k] == v for k, v in ba.items() if k in bb)
        # (field) -> [(region, kind, line)] in source order
        per_field: dict[str, list] = {}
        for field, kind, line, node in _field_accesses(meth):
            lock = guards.get(field)
            if lock is None:
                continue
            region = region_of.get(id(node), {}).get(lock)
            if region is None:
                continue  # unlocked access: KBT-L001's finding, not ours
            per_field.setdefault(field, []).append((region, kind, line))

        for field, accesses in sorted(per_field.items()):
            regions: dict[int, list] = {}
            for region, kind, line in accesses:
                regions.setdefault(region, []).append((kind, line))
            read_regions = [
                r for r, acc in regions.items() if any(k == "r" for k, _ in acc)
            ]
            if not read_regions:
                continue
            for r in sorted(regions):
                earlier = [x for x in read_regions if x < r and same_path(x, r)]
                if not earlier:
                    continue
                acc = regions[r]
                # a region that also READS the field under the writing
                # lock (validate/merge/max()) is a re-read region, even
                # when the read sits on the RHS of the writing statement
                if any(k == "w" for k, _ in acc) and not any(
                    k == "r" for k, _ in acc
                ):
                    line = min(ln for k, ln in acc if k == "w")
                    if _noqa(sf, line):
                        continue
                    read_line = min(
                        ln
                        for k, ln in regions[min(earlier)]
                        if k == "r"
                    )
                    findings.append(
                        Finding(
                            sf.path, line, "KBT-T003",
                            f"self.{field} is read under self.{guards[field]} "
                            f"(line {read_line}) and written back under a "
                            f"separate self.{guards[field]} region in "
                            f"{cls.name}.{meth.name} — the read-modify-write "
                            "is not atomic (another thread interleaves "
                            "between the regions); merge the regions or "
                            "re-read/validate under the writing lock",
                            symbol=f"{cls.name}.{meth.name}.{field}",
                        )
                    )
                    break  # one finding per field per method


# -- entry point --------------------------------------------------------------


def analyze(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        ctx_of = _contexts(sf.tree)
        parents = _parents(sf.tree)
        _t001(sf, ctx_of, parents, findings)
        seed = SEED_GUARDED.get(sf.path, {})
        annotated = _annotated_guards(sf)
        for cls in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = dict(seed.get(cls.name, {}))
            guards.update(annotated.get(cls.name, {}))
            _t002(sf, cls, guards, findings)
            if guards:
                _t003(sf, cls, guards, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return findings


# -- seeded fixtures + self-check --------------------------------------------
#
# Each positive fixture marks its expected finding lines with a
# `# VIOLATION: <code>` comment; the negative twin must stay silent.
# selfcheck() fails the CLI if a code ever stops firing (or starts
# over-firing) — the analyzer cannot silently rot.

_FIX_T001_POS = '''
import threading
from concurrent.futures import ThreadPoolExecutor

class Leaky:
    def start(self):
        self._worker = threading.Thread(target=self._run)  # VIOLATION: KBT-T001
        self._worker.start()

    def launch_pool(self):
        self._pool = ThreadPoolExecutor(max_workers=2)  # VIOLATION: KBT-T001
        self._pool.submit(self._run)

    def wait_forever(self):
        t = threading.Thread(target=self._run)  # VIOLATION: KBT-T001
        t.start()
        t.join()

    def _run(self):
        pass
'''

_FIX_T001_NEG = '''
import threading
from concurrent.futures import ThreadPoolExecutor

class Clean:
    def start(self):
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def stop(self):
        self._worker.join(timeout=5.0)

    def pooled(self):
        with ThreadPoolExecutor(max_workers=2) as pool:
            pool.submit(self._run)

    def fan_out(self):
        ts = []
        for _ in range(4):
            ts.append(threading.Thread(target=self._run))
        for t in ts:
            t.daemon = True
            t.start()
        for t in ts:
            t.join(timeout=1.0)

    def factory(self):
        return threading.Thread(target=self._run)

    def _run(self):
        pass
'''

_FIX_T002_POS = '''
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0
        self._status = ""

    def start(self):
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        while True:
            self._count += 1  # VIOLATION: KBT-T002
            self._status = "live"  # VIOLATION: KBT-T002

    def snapshot(self):
        return self._count, self._status
'''

_FIX_T002_NEG = '''
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0  #: guarded_by _lock
        self._status = ""  #: guarded_by _lock

    def start(self):
        self._t = threading.Thread(target=self._pump, daemon=True)
        self._t.start()

    def _pump(self):
        while True:
            with self._lock:
                self._count += 1
                self._status = "live"

    def snapshot(self):
        with self._lock:
            return self._count, self._status
'''

_FIX_T003_POS = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  #: guarded_by _lock

    def bump(self):
        with self._lock:
            n = self._n
        n += 1
        with self._lock:
            self._n = n  # VIOLATION: KBT-T003
'''

_FIX_T003_NEG = '''
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0  #: guarded_by _lock

    def bump_atomic(self):
        with self._lock:
            self._n += 1

    def bump_revalidated(self):
        with self._lock:
            n = self._n
        with self._lock:
            if self._n == n:
                self._n = n + 1
'''

FIXTURES: dict[str, str] = {
    "t001_pos": _FIX_T001_POS,
    "t001_neg": _FIX_T001_NEG,
    "t002_pos": _FIX_T002_POS,
    "t002_neg": _FIX_T002_NEG,
    "t003_pos": _FIX_T003_POS,
    "t003_neg": _FIX_T003_NEG,
}


def _expected(source: str) -> set[tuple[str, int]]:
    out = set()
    for i, line in enumerate(source.splitlines(), 1):
        if "# VIOLATION:" in line:
            out.add((line.split("# VIOLATION:")[1].strip(), i))
    return out


def selfcheck() -> list[str]:
    """Prove every KBT-T code fires on its seeded fixture and stays
    silent on the negative twin. Returns problem strings (empty=ok)."""
    problems: list[str] = []
    for name, source in sorted(FIXTURES.items()):
        sf = SourceFile(f"fixture:{name}", source, ast.parse(source))
        got = {(f.code, f.line) for f in analyze([sf])}
        want = _expected(source)
        if got != want:
            problems.append(
                f"fixture {name}: expected {sorted(want)} got {sorted(got)}"
            )
    return problems


# -- runtime RaceWitness self-check ------------------------------------------


def witness_selfcheck() -> list[str]:
    """Deterministic drills of utils.race.RaceWitness: a true race is
    caught with a stable trace id; lock- and join-ordered accesses stay
    clean. Returns problem strings (empty=ok)."""
    import threading

    from kube_batch_tpu.utils.race import RaceWitness

    problems: list[str] = []

    class Box:
        def __init__(self) -> None:
            self.field = 0

    def race_once() -> list[str]:
        w = RaceWitness()
        box = w.watch(Box(), ["field"])
        first_done = threading.Event()

        def writer_a() -> None:
            box.field = 1
            first_done.set()

        def writer_b() -> None:
            first_done.wait(5.0)  # Event is not a happens-before edge
            box.field = 2

        ta = w.spawn(writer_a, name="drill-a")
        tb = w.spawn(writer_b, name="drill-b")
        ta.start()
        tb.start()
        ta.join(5.0)
        tb.join(5.0)
        return list(w.reports)

    r1, r2 = race_once(), race_once()
    if not r1:
        problems.append("true-race drill: witness reported nothing")
    elif "[trace Box.field:0-1]" not in r1[0]:
        problems.append(f"true-race drill: unexpected trace id in {r1[0]!r}")
    if r1 != r2:
        problems.append(
            f"true-race drill not deterministic: {r1!r} vs {r2!r}"
        )

    # ordered by lock: the release->acquire edge orders the writes
    w = RaceWitness()
    box = w.watch(Box(), ["field"])
    mu = w.wrap("box.mu", threading.Lock())
    first_done = threading.Event()

    def locked_a() -> None:
        with mu:
            box.field = 1
        first_done.set()

    def locked_b() -> None:
        first_done.wait(5.0)
        with mu:
            box.field = 2

    ta, tb = w.spawn(locked_a), w.spawn(locked_b)
    ta.start(), tb.start()
    ta.join(5.0), tb.join(5.0)
    if w.reports:
        problems.append(f"lock-ordered drill flagged: {w.reports!r}")

    # ordered by join: parent writes after joining the child
    w = RaceWitness()
    box = w.watch(Box(), ["field"])

    def child() -> None:
        box.field = 1

    t = w.spawn(child)
    t.start()
    t.join(5.0)
    box.field = 2  # happens-after via the join edge
    if w.reports:
        problems.append(f"join-ordered drill flagged: {w.reports!r}")
    return problems


# -- live witness drive: streaming-federation bind path -----------------------


def witness_drive(writers: int = 2, events_per_writer: int = 40) -> dict:
    """Drive the RaceWitness over the live absorb-mode StreamTrigger +
    StreamState — the federated streaming bind path: concurrent peer
    bind/release churn and pending arrivals against one trigger, a
    drain loop absorbing occupancy patches into the resident table.
    Expect clean: every hot-field access is ordered by trigger._lock
    or confined to the drain thread."""
    import threading

    from kube_batch_tpu.cache.store import PODS
    from kube_batch_tpu.streaming import StreamState, StreamTrigger
    from kube_batch_tpu.testing import build_node, build_pod, build_resource_list
    from kube_batch_tpu.utils.race import RaceWitness

    w = RaceWitness()
    trigger = StreamTrigger(absorb_external=True)
    trigger._lock = w.wrap("trigger._lock", trigger._lock)
    w.watch(
        trigger,
        {
            "_gangs": "touch",
            "_bound_patches": "touch",
            "_node_patches": "touch",
            "_arrivals": "touch",
            "_queues": "touch",
            "_stale": "rw",
            "_stale_reason": "rw",
        },
    )
    state = StreamState()
    from kube_batch_tpu.api.node_info import NodeInfo

    state.nodes = {
        f"n{i}": NodeInfo(
            build_node(f"n{i}", build_resource_list(cpu=64, memory="64Gi", pods=256))
        )
        for i in range(4)
    }
    state.valid = True
    state.reason = ""
    w.watch(state, {"nodes": "touch", "valid": "rw", "reason": "rw"})

    stop = threading.Event()
    accesses = {"n": 0}
    w.on_access = lambda _name: accesses.__setitem__("n", accesses["n"] + 1)

    def peer(idx: int) -> None:
        for i in range(events_per_writer):
            name = f"peer{idx}-p{i}"
            bound = build_pod(
                name=name, group_name=f"g{idx}",
                req=build_resource_list(cpu=1, memory="256Mi"),
                node_name=f"n{i % 4}",
            )
            trigger._on_event(PODS, f"default/{name}", bound, None)  # peer bind
            if i % 3 == 0:
                trigger._on_event(PODS, f"default/{name}", None, bound)  # release
            pending = build_pod(
                name=f"own{idx}-p{i}", group_name=f"own{idx}",
                req=build_resource_list(cpu=1, memory="256Mi"),
            )
            trigger._on_event(PODS, f"default/own{idx}-p{i}", pending, None)

    def drain_loop() -> None:
        while not stop.is_set():
            trigger.wait(0.01)
            work = trigger.drain()
            if work.bound_patches:
                state.apply_bound_patches(work.bound_patches)
            trigger.prune(set(list(work.gangs)[:2]))

    threads = [w.spawn(peer, args=(i,), name=f"kbt-drive-peer{i}") for i in range(writers)]
    drainer = w.spawn(drain_loop, name="kbt-drive-drain")
    for t in threads:
        t.start()
    drainer.start()
    for t in threads:
        t.join(timeout=30.0)
    stop.set()
    drainer.join(timeout=30.0)
    # final absorb on the main thread — ordered by the join edges
    work = trigger.drain()
    if work.bound_patches:
        state.apply_bound_patches(work.bound_patches)
    leaked = [t.name for t in [*threads, drainer] if t.is_alive()]
    return {
        "ok": not w.reports and not leaked,
        "accesses": accesses["n"],
        "backlog": trigger.backlog_pods(),
        "reports": list(w.reports),
        "leaked": leaked,
    }


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json
    import os
    import textwrap

    from kube_batch_tpu.analysis import (
        CODES,
        Baseline,
        apply_baseline,
        load_baseline,
        load_tree,
        render_baseline,
        repo_root,
    )

    p = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.analysis.threads",
        description="thread-lifecycle / shared-state-escape / atomicity "
        "analyzer (KBT-T) + RaceWitness self-check (stdlib-only)",
    )
    p.add_argument("--json", action="store_true", help="machine-readable summary")
    p.add_argument("--strict", action="store_true",
                   help="also fail on stale KBT-T baseline entries")
    p.add_argument("--baseline", default=None,
                   help="suppression file (default: <repo>/hack/lint-baseline.toml)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, apply no suppressions")
    p.add_argument("--repo", default=None, help="tree to analyze (default: auto)")
    p.add_argument("--explain", metavar="CODE", default=None,
                   help="describe a finding code and exit")
    p.add_argument("--prune", action="store_true",
                   help="rewrite the shared baseline dropping stale KBT-T "
                   "entries (other code families untouched)")
    p.add_argument("--witness-drive", action="store_true",
                   help="also drive the RaceWitness over the live "
                   "streaming-federation bind path (imports the package)")
    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code not in (0, None) else 0

    if args.explain:
        code = args.explain.upper()
        if code not in CODES:
            print(f"unknown code {code!r}; known: {', '.join(sorted(CODES))}")
            return 2
        title, body = CODES[code]
        print(f"{code}: {title}\n")
        print(textwrap.fill(body, width=78))
        return 0

    repo = os.path.abspath(args.repo) if args.repo else repo_root()
    findings = analyze(load_tree(repo))

    if args.no_baseline:
        bl = None
        kept, suppressed, stale, baseline_errors = findings, [], [], []
        bl_path = None
    else:
        bl_path = args.baseline or os.path.join(repo, "hack", "lint-baseline.toml")
        bl = load_baseline(bl_path, repo)
        # this CLI owns only the KBT-T slice of the shared baseline:
        # other families neither suppress here nor read as stale
        sub = Baseline(
            path=bl.path,
            suppressions=[
                s for s in bl.suppressions if s.code.startswith("KBT-T")
            ],
            errors=[f for f in bl.errors if f.symbol.startswith("KBT-T")],
            preamble=bl.preamble,
        )
        kept, suppressed, stale = apply_baseline(findings, sub)
        baseline_errors = sub.errors

    if args.prune:
        if bl is None:
            print("--prune is meaningless with --no-baseline")
            return 2
        keep = [
            s for s in bl.suppressions
            if not s.code.startswith("KBT-T")
            or s.hits > 0
            or not (s.code and s.path)
        ]
        dropped = [s for s in bl.suppressions if s not in keep]
        if dropped:
            with open(bl_path, "w", encoding="utf-8") as fh:
                fh.write(render_baseline(bl, keep))
        for s in dropped:
            print(f"pruned: {s.code} at {s.path}"
                  + (f" ({s.symbol})" if s.symbol else ""))
        print(f"prune: {len(dropped)} stale KBT-T entr"
              f"{'y' if len(dropped) == 1 else 'ies'} dropped")
        stale = []

    static_problems = selfcheck()
    witness_problems = witness_selfcheck()
    drive = witness_drive() if args.witness_drive else None

    failing = list(kept) + list(baseline_errors)
    if args.strict:
        failing += stale
    ok = (
        not failing
        and not static_problems
        and not witness_problems
        and (drive is None or drive["ok"])
    )

    if args.json:
        print(json.dumps({
            "ok": ok,
            "repo": repo,
            "findings": [f.__dict__ for f in kept],
            "baseline_errors": [f.__dict__ for f in baseline_errors],
            "stale": [f.__dict__ for f in stale],
            "suppressed": len(suppressed),
            "counts": _counts(kept),
            "selfcheck": {
                "static": static_problems,
                "witness": witness_problems,
            },
            "witness_drive": drive,
        }, sort_keys=True))
    else:
        for f in sorted(failing, key=lambda f: (f.path, f.line, f.code)):
            print(f.render())
        if stale and not args.strict:
            for f in stale:
                print(f"note: {f.render()}")
        for prob in static_problems:
            print(f"selfcheck: {prob}")
        for prob in witness_problems:
            print(f"witness: {prob}")
        if drive is not None and not drive["ok"]:
            for r in drive["reports"]:
                print(f"drive: {r}")
            for name in drive["leaked"]:
                print(f"drive: leaked thread {name}")
        print(
            f"threads: {len(kept)} finding(s), {len(stale)} stale, "
            f"{len(suppressed)} suppressed, selfcheck "
            f"{'ok' if not (static_problems or witness_problems) else 'FAILED'}"
            + (
                f", witness drive {'ok' if drive['ok'] else 'FAILED'} "
                f"({drive['accesses']} accesses)"
                if drive is not None
                else ""
            )
        )
    if ok:
        return 0
    return 1


def _counts(findings) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.code] = out.get(f.code, 0) + 1
    return out


if __name__ == "__main__":
    import sys

    # re-enter through the canonical module so module-level state is
    # shared with normal imports
    from kube_batch_tpu.analysis.threads import main as _canonical_main

    sys.exit(_canonical_main())
