"""Domain-aware static analysis suite (the third rail of ``make verify``).

The generic half of the verify gate (hack/verify.py: compileall,
tabnanny, the stdlib F401/E722/E711/B006/F541 linter, ruff+mypy when
present) knows nothing about the invariants this codebase actually
lives or dies by. This package encodes them as four analyzers, each
stdlib-only (ast-based) so the bare container runs the full gate:

- **A1 lock-discipline** (:mod:`.lock_discipline`, KBT-L0xx):
  attributes declared guarded — via the seed map for the threaded
  cache/store/workqueue/journal/watch-hub layers or a
  ``#: guarded_by <lock>`` annotation — must only be touched lexically
  inside ``with self.<lock>`` or in a method marked lock-held
  (``_locked`` suffix / ``@assume_locked``). Catches the cross-thread
  races the runtime mutation detector only sees if a test happens to
  interleave.
- **A2 JAX hazards** (:mod:`.jax_hazards`, KBT-J0xx): inside
  jit/pjit/shard_map/pallas-reachable functions of ``ops/`` and
  ``parallel/``, flag host syncs (``.item()``, ``.tolist()``,
  ``np.asarray``, ``jax.device_get``, ``float()/int()`` on traced
  values), Python truth tests on traced values, and bare ``print``;
  plus raw ``float32/float64`` dtype literals in ``plugins/``/``api/``
  that bypass the ``api/numerics.py`` comparison-dtype policy.
- **A3 registry consistency** (:mod:`.registry_consistency`, KBT-R0xx):
  every fault point fired exists in ``faults.POINTS`` and vice versa;
  every ``metrics.<name>`` touched is declared in
  ``metrics/__init__.py``; every ``KBT_*`` env var read appears in the
  deployment runbook's env table, and no documented knob is dead.
- **A4 snapshot escape** (:mod:`.snapshot_escape`, KBT-S0xx):
  plugins/actions that mutate objects reached from a session snapshot
  without going through the Statement / session APIs.
- **A5 lock order** (:mod:`.lock_order`, KBT-D0xx): the interprocedural
  lock-acquisition graph over the threaded layers (built on A1's
  guarded-by seed map) — ABBA cycles and blocking calls (fsync, sleep,
  subprocess, device sync) inside lock-held regions.
- **A6 protocol lifecycles** (:mod:`.protocol`, KBT-C0xx): the five
  declared lifecycle state machines (Session open->close, Statement
  operate->commit|discard, journal append->dispatch->confirm, circuit-
  breaker tier transitions, StreamState harvest->patch->invalidate->
  re-harvest) checked path-structurally per function, plus listener
  register/remove pairing on teardown paths.
- **A7 concurrency sanitizer** (:mod:`.threads`, KBT-T0xx, also its
  own CLI ``python -m kube_batch_tpu.analysis.threads``): thread/pool
  lifecycle discipline (every construction needs a reachable bounded
  join/shutdown or a daemon annotation), shared-state escape (an
  unguarded ``self.<field>`` written in one inferred thread root's
  call closure and touched in another's), and split read-modify-write
  across two regions of one lock. Shares A1's ``#: guarded_by``
  declaration surface; its runtime sibling is the vector-clock
  :class:`~kube_batch_tpu.utils.race.RaceWitness`.

A jax-dependent sibling, the **trace-time auditor**
(:mod:`kube_batch_tpu.analysis.trace`, KBT-P0xx, its own CLI
``python -m kube_batch_tpu.analysis.trace``), traces the real solver
entry points on abstract inputs and audits the resulting jaxprs /
lowered programs: host callbacks and warm-cycle transfers, f64 upcast
leaks, large captured constants, un-honored donation, and cross-tier
program-signature drift. A second sibling, the **interleaving model
checker** (:mod:`kube_batch_tpu.analysis.interleave`, KBT-I0xx, CLI
``python -m kube_batch_tpu.analysis.interleave``), drives fixed
streaming/takeover scenarios through every distinguishable thread
schedule (DPOR-lite over declared step footprints, checked against a
:class:`~kube_batch_tpu.utils.locking.LockOrderWitness`) and asserts
bind-for-bind parity, zero lost/duplicate binds, and journal
consistency per schedule; counterexamples replay by trace id. Both
share this package's Finding/CODES/baseline machinery; this module
stays stdlib-only.

Findings print as ``file:line: CODE message``. Intentional deviations
live in a committed suppression file (``hack/lint-baseline.toml``);
every entry requires a ``reason`` — a reason-less entry is itself a
finding (KBT-B001), and under ``--strict`` so is a stale one
(KBT-B002). CLI: ``python -m kube_batch_tpu.analysis``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Optional

__all__ = [
    "Finding",
    "SourceFile",
    "Suppression",
    "Baseline",
    "CODES",
    "load_tree",
    "load_baseline",
    "apply_baseline",
    "render_baseline",
    "run_suite",
    "repo_root",
]


@dataclass(frozen=True)
class Finding:
    """One analyzer hit: ``{path}:{line}: {code} {message}``.

    ``symbol`` is the stable suppression key (qualified name + detail)
    — baseline entries match on it instead of line numbers, which
    drift."""

    path: str  # repo-relative, forward slashes
    line: int
    code: str
    message: str
    symbol: str = ""

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


@dataclass
class SourceFile:
    path: str  # repo-relative, forward slashes
    source: str
    tree: ast.AST

    @property
    def lines(self) -> list[str]:
        return self.source.splitlines()


# code -> (one-line title, what it protects / how to fix) — the
# ``--explain`` text and the runbook table's source of truth.
CODES: dict[str, tuple[str, str]] = {
    "KBT-L001": (
        "guarded attribute touched without its lock",
        "The attribute is declared guarded (seed map or `#: guarded_by "
        "<lock>` annotation) but is read/written outside a lexical `with "
        "self.<lock>` block, in a method not marked lock-held (`_locked` "
        "suffix or @assume_locked). This is the cross-thread race class "
        "the runtime mutation detector only catches if a test interleaves "
        "— the resync workers, write pool, watch hub and HTTP handlers "
        "all share these structures. Fix: take the lock, move the access "
        "inside an existing critical section, or mark the helper "
        "@assume_locked if every caller already holds it.",
    ),
    "KBT-L002": (
        "guarded_by annotation names no known lock",
        "A `#: guarded_by <lock>` annotation refers to an attribute that "
        "is never assigned a threading.Lock/RLock/Condition in this "
        "class. The guard would never be enforceable. Fix the annotation "
        "or add the lock.",
    ),
    "KBT-J001": (
        "host sync inside a jit-reachable function",
        "`.item()`, `.tolist()`, `np.asarray`/`np.array`, "
        "`jax.device_get`, or `float()/int()/bool()` on a traced value "
        "forces a device->host transfer and blocks dispatch inside "
        "traced code — on TPU this serializes the solve pipeline (and "
        "under tracing it raises ConcretizationTypeError at runtime on "
        "some paths the tests never walk). Fix: stay in jnp, or hoist "
        "the host conversion outside the jitted entry.",
    ),
    "KBT-J002": (
        "Python truth test on a traced value",
        "`if`/`while`/`assert` on a traced array needs a concrete bool, "
        "so it either host-syncs or raises TracerBoolConversionError "
        "depending on the path. Use `jax.lax.cond`/`jnp.where`, or make "
        "the flag a static argument.",
    ),
    "KBT-J003": (
        "bare print inside a jit-reachable function",
        "`print` runs at trace time (once per compile, not per step) "
        "and silently prints tracers. Use `jax.debug.print` for runtime "
        "values, or move the print outside the jitted entry.",
    ),
    "KBT-J004": (
        "raw dtype literal bypasses the comparison-dtype policy",
        "Comparison-feeding derived quantities (shares, fractions, "
        "scores) must be computed in api/numerics.comparison_dtype() — "
        "f32 when the kernels solve f32 — or the serial oracle disagrees "
        "with the device kernels on sub-ulp ties (~0.5% of placements at "
        "scale). A hard-coded np.float64/np.float32 in plugins/ or api/ "
        "pins one side. Identity checks (`x is np.float64`) are exempt — "
        "they consult the policy, they don't bypass it. Fix: use "
        "comparison_dtype(); on-grid integral quantities that are exact "
        "in every dtype may keep a literal with a baseline reason.",
    ),
    "KBT-R001": (
        "fault point fired but not registered",
        "faults.should_fire()/arm() is called with a point name missing "
        "from faults.POINTS — the drill spec parser would reject it, so "
        "the injection can never be armed and the degraded branch is "
        "dead code. Add the point to POINTS (with its ladder/runbook "
        "entry) or fix the typo.",
    ),
    "KBT-R002": (
        "registered fault point never fired",
        "A faults.POINTS entry has no should_fire() call site — drills "
        "arming it silently inject nothing, which is exactly the "
        "false-confidence failure chaos tooling exists to prevent. Wire "
        "the point at the boundary it names or remove it.",
    ),
    "KBT-R003": (
        "metric not declared in metrics/__init__.py",
        "Code touches metrics.<name> but the metrics module defines no "
        "such collector/helper — an AttributeError on a path that only "
        "fires under failure (most metering sits in except blocks). "
        "Declare the metric (with HELP text, and add it to "
        "render_prometheus_text) or fix the name.",
    ),
    "KBT-R004": (
        "KBT_* env var read but not documented in the runbook",
        "An os.environ read of a KBT_* knob has no row in the deployment "
        "runbook's environment table (deployment/README.md) — operators "
        "cannot discover it, and drills/runbooks drift from reality. Add "
        "the row (name, default, one-line semantics).",
    ),
    "KBT-R005": (
        "documented KBT_* env knob is dead",
        "The deployment runbook documents a KBT_* variable no code "
        "reads — operators will set it and observe nothing. Remove the "
        "row or restore the read.",
    ),
    "KBT-S001": (
        "snapshot object mutated outside Statement/session APIs",
        "A plugin/action assigns attributes on an object reached from "
        "the session snapshot (ssn.jobs/nodes/queues) directly. Session "
        "state must change through ssn.allocate/evict or a Statement so "
        "the operation log can undo it on discard and the event handlers "
        "(DRF/proportion shares) observe it; a silent direct write "
        "desyncs shares and survives gang rollback. Route through the "
        "session API, or baseline with the parity evidence if the "
        "mutation is a vetted bulk-replay equivalent.",
    ),
    "KBT-S002": (
        "snapshot object mutator called outside Statement/session APIs",
        "A plugin/action calls a mutating method (add_task, remove_task, "
        "update_task_status, ...) on a snapshot-derived job/node/task "
        "directly instead of through ssn.allocate/evict or a Statement. "
        "Same failure class as KBT-S001: no undo log, no events, shares "
        "desync.",
    ),
    "KBT-D001": (
        "lock-order cycle (ABBA) in the static acquisition graph",
        "Two locks are acquired in opposite orders on different code "
        "paths (A then B here, B then A elsewhere) in the threaded "
        "cache/store/workqueue/journal/watch-hub layers. Under load the "
        "two paths interleave and deadlock — the failover takeover path "
        "is exactly where both orders tend to meet. Fix: pick one global "
        "order (document it where the locks are declared) and re-nest the "
        "inner acquisition, or split the critical section so the second "
        "lock is taken after the first is released.",
    ),
    "KBT-D002": (
        "blocking call while holding a lock",
        "A lock-held region calls into a blocking API (journal fsync, "
        "time.sleep, subprocess, future .result(), device sync like "
        "block_until_ready/device_get, network send/recv). Every other "
        "thread needing that lock — watch emitters, resync workers, the "
        "HTTP handlers — stalls for the full blocking latency, and a "
        "hung fsync or RPC turns into a scheduler-wide freeze. Fix: move "
        "the blocking work outside the critical section (snapshot under "
        "the lock, block after), or baseline with the ordering argument "
        "when the blocking is the point (e.g. WAL fsync ordered with seq "
        "assignment). `Condition.wait` on the held condition is exempt — "
        "it releases the lock while blocking.",
    ),
    "KBT-P001": (
        "host callback / transfer inside a traced solver program",
        "The traced program for a solver entry point contains a host "
        "callback primitive (pure_callback/io_callback/debug_callback) "
        "or fails the warm-cycle transfer guard (an implicit host<->device "
        "transfer on a steady-state cycle). On TPU each one serializes "
        "the solve pipeline per iteration — the exact per-decision cost "
        "the resident-state design exists to avoid. Fix: keep the data "
        "device-resident (arena), hoist host work outside the jitted "
        "entry, or make the value a static argument.",
    ),
    "KBT-P002": (
        "f64 upcast leaked into a traced solver program",
        "An intermediate value in the traced program carries float64 "
        "while the entry point's inputs are float32 — a silent upcast "
        "(Python float promotion, np.float64 constant, dtype-less "
        "jnp.asarray) that doubles VMEM pressure and splits numerics "
        "from the f32 kernels the parity suites pin. The source-level "
        "KBT-J004 only sees literal spellings; this check sees the "
        "traced truth. Fix: pin the constant/cast to the array's dtype.",
    ),
    "KBT-P003": (
        "large host constant captured into a traced program",
        "The traced program closes over a host constant bigger than the "
        "audit threshold — an embedded table re-uploaded and re-hashed "
        "on every compile (the 400k-row-table footgun). Large data must "
        "enter as a traced argument (cacheable, arena-resident), not a "
        "captured constant. Fix: pass it as an argument or pre-place it "
        "on device.",
    ),
    "KBT-P004": (
        "declared buffer donation is not honored",
        "An entry point declares donate_argnums but the lowered program "
        "carries no input-output alias for the donated buffer (no "
        "shape/dtype-matching output, or XLA dropped the alias) — the "
        "arena's in-place row scatter silently becomes a full copy and "
        "device memory doubles at the biggest buffer. Fix: make the "
        "donated input's aval match an output aval exactly, or drop the "
        "donation declaration so the copy is at least explicit.",
    ),
    "KBT-P005": (
        "cross-tier program signature drift",
        "The solver tiers (XLA twin, GSPMD sharded rung, mesh-Pallas "
        "rung) disagree on an input/output aval (shape or dtype) of the "
        "shared SolveState protocol at some mesh size. The degradation "
        "ladder hands state between tiers mid-session — a drifted field "
        "means resume-after-failover reinterprets bits or retraces, and "
        "selection numerics diverge structurally between tiers. Fix: "
        "restore the drifted field's shape/dtype in the offending tier.",
    ),
    "KBT-C001": (
        "session/Statement left open on an exit path",
        "A session (open_session/open_micro_session) or Statement "
        "(statement_factory/ssn.statement()/Statement(ssn)) created in "
        "this function can reach a function exit, a loop-iteration end, "
        "or a rebinding without close_session() / commit() / discard() "
        "on that path. An open statement's operations neither replay to "
        "the cache nor roll back — the gang-atomicity hole the "
        "Statement exists to close; a dropped session loses the cycle's "
        "status write-back. The check is path-structural: a branch your "
        "invariants make impossible still needs the close, because the "
        "next refactor makes it possible. Escaping the resource "
        "(return/alias/store on an object) transfers ownership and "
        "ends the check; passing it as a call argument does not.",
    ),
    "KBT-C002": (
        "protocol operation outside its owning scope",
        "Either a raw cache dispatch (cache.bind/bind_many/evict) "
        "outside the Statement/session layer (framework/session.py, "
        "framework/statement.py, cache/cache.py) — the write skips the "
        "operation log and the share event handlers — or a circuit-"
        "breaker _transition() outside faults/ladder.py / outside the "
        "declared closed/open/half_open alphabet. Route the bind "
        "through ssn/Statement (or baseline with parity evidence for a "
        "vetted bulk-replay), and keep tier transitions inside the "
        "ladder where the lock/backoff discipline lives.",
    ),
    "KBT-C003": (
        "journal append/dispatch/confirm pairing broken",
        "A write-intent append (append_intents/_journal_intents) can "
        "exit its function on a path with no dispatch (_submit_write/"
        "_do_*) or confirm — an orphan intent every takeover will "
        "re-litigate — or a module appends but never confirms/"
        "dispatches (or confirms what it never appends, outside "
        "recovery/ where takeover confirms a dead leader's intents). "
        "Dispatch or confirm on every path, or return the seqs to the "
        "caller who does.",
    ),
    "KBT-C004": (
        "resident-table read after invalidate without re-harvest",
        "On the same path, a StreamState-like object is invalidate()d "
        "and then its resident node table is read (.nodes / "
        "apply_node_patches) with no adopt_full_cycle re-harvest in "
        "between — a micro-cycle solving against capacity that no "
        "longer exists. Degrade to the full cycle first (it re-adopts "
        "the table), or reorder the read before the invalidation.",
    ),
    "KBT-C005": (
        "listener registered without a remove on the teardown path",
        "add_store_listener()/attach() has no matching remove reachable "
        "from the registration: neither a finally whose try starts at "
        "or immediately after the registration, nor a paired teardown "
        "method (detach/stop/close/...) on the class. The leaked "
        "listener keeps firing into a stopped loop — every store event "
        "pays for a consumer that no longer exists, and a re-started "
        "loop double-registers. Even one statement between the "
        "registration and the protecting try is one exception away "
        "from the leak.",
    ),
    "KBT-T001": (
        "thread/pool without a reachable bounded shutdown path",
        "A threading.Thread or executor pool is constructed with no "
        "reachable bounded join(timeout=...)/shutdown() on its binding "
        "and no daemon=True annotation — or is only ever joined without "
        "a timeout. A wedged worker then outlives its owner and hangs "
        "process teardown (the watch pump, lease renewer and scrape "
        "loops all shut down under deadline budgets). Fix: add a "
        "stop()+join(timeout=...) path (idempotent on double-stop), use "
        "`with ThreadPoolExecutor(...)`, or mark daemon=True where a "
        "supervisor polls liveness. Ownership transfers (returning the "
        "thread, passing it to a call) end the obligation at the "
        "construction site.",
    ),
    "KBT-T002": (
        "unguarded field escapes to multiple thread roots",
        "A self.<field> with no declared guard (KBT-L seed map or "
        "`#: guarded_by` annotation) is written in one inferred thread "
        "root's call closure and touched in another's — or written from "
        "a multi-instance root (a pool callable, a thread started in a "
        "loop). Thread roots are inferred from Thread(target=...)/"
        "submit(...) sites plus the seed-root map for dynamic dispatch "
        "(HTTP handler threads, write-pool callbacks); everything else "
        "is the owning `(callers)` root. Unordered cross-root access is "
        "a data race: torn reads, lost updates, stale decisions. Fix: "
        "annotate `#: guarded_by <lock>` on the field's __init__ line "
        "(KBT-L then enforces every touch) and take the lock, or "
        "confine the field to one thread and baseline with the "
        "confinement argument.",
    ),
    "KBT-T003": (
        "read-modify-write split across two lock regions",
        "A guarded field is read under its lock in one `with` region "
        "and written back under a *different* region of the same lock "
        "in the same function, with no re-read before the write. Both "
        "accesses hold the lock, so KBT-L is satisfied — but the "
        "modify step between the regions runs unlocked, and another "
        "thread's update in the window is silently overwritten "
        "(check-then-act on stale state). Fix: merge the two regions "
        "into one critical section, or re-read/validate the field "
        "under the writing lock before storing.",
    ),
    "KBT-I001": (
        "interleaving counterexample",
        "The interleaving model checker "
        "(kube_batch_tpu.analysis.interleave) found a thread schedule "
        "under which a scenario invariant breaks: an arrival lost or "
        "never bound, a bind landing twice, the journal left with "
        "orphan intents, a lock-order reversal, or placements diverging "
        "from what every other schedule of the same scenario produced. "
        "The finding names the trace id — replay it step by step with "
        "`python -m kube_batch_tpu.analysis.interleave --replay "
        "<scenario>:<digits>`, fix the race, and re-explore.",
    ),
    "KBT-I002": (
        "interleaving model error",
        "The scenario model itself is unsound, not the code under test: "
        "a step acquired a lock outside its declared footprint (so the "
        "partial-order pruning could have skipped a distinguishable "
        "schedule), or a scenario build precondition failed. Fix the "
        "step's declared footprint or the scenario builder before "
        "trusting any clean result from that scenario.",
    ),
    "KBT-B001": (
        "baseline entry missing a reason",
        "Every hack/lint-baseline.toml entry must say WHY the finding is "
        "intentionally kept — a reason-less suppression is "
        "indistinguishable from a silent skip and fails the gate.",
    ),
    "KBT-B002": (
        "stale baseline entry",
        "A suppression matches no current finding — the code it excused "
        "changed. Delete the entry (strict mode fails on it so the "
        "baseline can only shrink, never rot).",
    ),
}


def repo_root() -> str:
    """The tree to analyze: cwd when it holds the package (the normal
    checkout / image layout), else the checkout containing this file."""
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "kube_batch_tpu")):
        return cwd
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_tree(repo: str, package: str = "kube_batch_tpu") -> list[SourceFile]:
    """Parse every package .py (tests and this meta-layer excluded —
    the generic hack/verify.py lint still covers both)."""
    out: list[SourceFile] = []
    pkg_dir = os.path.join(repo, package)
    for root, dirs, files in os.walk(pkg_dir):
        dirs[:] = sorted(d for d in dirs if d not in ("__pycache__", "analysis"))
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            abspath = os.path.join(root, f)
            rel = os.path.relpath(abspath, repo).replace(os.sep, "/")
            with open(abspath, encoding="utf-8") as fh:
                source = fh.read()
            try:
                tree = ast.parse(source, rel)
            except SyntaxError:
                continue  # compileall's problem, not ours
            out.append(SourceFile(rel, source, tree))
    return out


# -- baseline (hack/lint-baseline.toml) --------------------------------------
#
# Parsed with a deliberately tiny TOML-subset reader: this image is
# py3.10 (no tomllib) and installs are off. Grammar accepted: comments,
# [[suppress]] table headers, and `key = "string"` pairs. Anything else
# is a parse error (loud, so the file cannot quietly rot into a dialect
# tomllib would later reject).

_HEADER_RE = re.compile(r"^\[\[suppress\]\]\s*$")
_PAIR_RE = re.compile(r'^(?P<key>[A-Za-z_][A-Za-z0-9_]*)\s*=\s*"(?P<val>(?:[^"\\]|\\.)*)"\s*$')
_KEYS = {"code", "path", "symbol", "reason"}


@dataclass
class Suppression:
    code: str = ""
    path: str = ""
    symbol: str = ""  # empty = any symbol at (code, path)
    reason: str = ""
    line: int = 0  # line of the [[suppress]] header in the baseline
    hits: int = 0  # findings matched this run
    # the entry's verbatim lines (header + pairs + trailing comments), so
    # --prune can rewrite the file preserving formatting and reasons
    raw: list[str] = field(default_factory=list)

    def matches(self, f: Finding) -> bool:
        return (
            self.code == f.code
            and self.path == f.path
            and (not self.symbol or self.symbol == f.symbol)
        )


@dataclass
class Baseline:
    path: str
    suppressions: list[Suppression] = field(default_factory=list)
    errors: list[Finding] = field(default_factory=list)  # KBT-B001 + parse errors
    preamble: list[str] = field(default_factory=list)  # verbatim lines before the first entry


def _strip_comment(line: str) -> str:
    out = []
    in_str = False
    i = 0
    while i < len(line):
        c = line[i]
        if c == '"' and (i == 0 or line[i - 1] != "\\"):
            in_str = not in_str
        if c == "#" and not in_str:
            break
        out.append(c)
        i += 1
    return "".join(out).strip()


def load_baseline(path: str, repo: str) -> Baseline:
    rel = os.path.relpath(path, repo).replace(os.sep, "/")
    bl = Baseline(path=rel)
    if not os.path.exists(path):
        return bl
    cur: Optional[Suppression] = None
    with open(path, encoding="utf-8") as fh:
        for lineno, raw in enumerate(fh, 1):
            line = _strip_comment(raw)
            if not line:
                if cur is None:
                    bl.preamble.append(raw.rstrip("\n"))
                continue
            if _HEADER_RE.match(line):
                cur = Suppression(line=lineno)
                cur.raw.append(raw.rstrip("\n"))
                bl.suppressions.append(cur)
                continue
            m = _PAIR_RE.match(line)
            if m and cur is not None and m.group("key") in _KEYS:
                val = m.group("val").replace('\\"', '"').replace("\\\\", "\\")
                setattr(cur, m.group("key"), val)
                cur.raw.append(raw.rstrip("\n"))
                continue
            bl.errors.append(
                Finding(
                    rel, lineno, "KBT-B001",
                    f"unparseable baseline line {raw.strip()!r} (grammar: "
                    '[[suppress]] tables of key = "value" pairs)',
                    symbol=f"parse:{lineno}",
                )
            )
    for s in bl.suppressions:
        if not s.reason.strip():
            bl.errors.append(
                Finding(
                    rel, s.line, "KBT-B001",
                    f"suppression of {s.code or '<no code>'} at "
                    f"{s.path or '<no path>'} has no reason — every entry "
                    "must say why the finding is intentionally kept",
                    symbol=f"{s.code}:{s.path}:{s.symbol}",
                )
            )
        if not s.code or not s.path:
            bl.errors.append(
                Finding(
                    rel, s.line, "KBT-B001",
                    "suppression must name both `code` and `path`",
                    symbol=f"incomplete:{s.line}",
                )
            )
    return bl


def apply_baseline(
    findings: list[Finding], bl: Baseline
) -> tuple[list[Finding], list[Finding], list[Finding]]:
    """-> (kept, suppressed, stale) where stale are KBT-B002 findings
    for suppressions that matched nothing."""
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for f in findings:
        hit = None
        for s in bl.suppressions:
            if s.matches(f):
                hit = s
                break
        if hit is not None:
            hit.hits += 1
            suppressed.append(f)
        else:
            kept.append(f)
    stale = [
        Finding(
            bl.path, s.line, "KBT-B002",
            f"suppression of {s.code} at {s.path}"
            + (f" ({s.symbol})" if s.symbol else "")
            + " matches no current finding — delete it",
            symbol=f"{s.code}:{s.path}:{s.symbol}",
        )
        for s in bl.suppressions
        if s.hits == 0 and s.code and s.path
    ]
    return kept, suppressed, stale


def render_baseline(bl: Baseline, keep: list[Suppression]) -> str:
    """The baseline file's text with only ``keep`` entries, preserving
    the preamble comment block and each entry's verbatim lines/order
    (the --prune rewrite)."""
    parts: list[str] = []
    preamble = list(bl.preamble)
    while preamble and not preamble[-1].strip():
        preamble.pop()
    if preamble:
        parts.append("\n".join(preamble))
    for s in keep:
        parts.append("\n".join(s.raw))
    return "\n\n".join(parts) + "\n" if parts else ""


# -- suite -------------------------------------------------------------------


def run_suite(
    repo: Optional[str] = None,
    files: Optional[list[SourceFile]] = None,
    runbook: Optional[str] = None,
) -> list[Finding]:
    """Run all four analyzers over the tree; findings sorted by
    (path, line, code). Baseline application is the caller's business
    (the CLI and hack/verify.py both go through it)."""
    from kube_batch_tpu.analysis import (
        jax_hazards,
        lock_discipline,
        lock_order,
        protocol,
        registry_consistency,
        snapshot_escape,
        threads,
    )

    repo = repo or repo_root()
    if files is None:
        files = load_tree(repo)
    findings: list[Finding] = []
    analyzers: list[Callable[..., list[Finding]]] = [
        lock_discipline.analyze,
        lock_order.analyze,
        protocol.analyze,
        jax_hazards.analyze,
        snapshot_escape.analyze,
        threads.analyze,
    ]
    for analyze in analyzers:
        findings.extend(analyze(files))
    findings.extend(registry_consistency.analyze(files, repo=repo, runbook=runbook))
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return findings
