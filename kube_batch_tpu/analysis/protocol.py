"""A6 — protocol-lifecycle analyzer (KBT-C001..C005).

The suite's other analyzers check *where* state is touched (locks,
snapshots, registries); this one checks *in what order*. Five core
protocols run through this codebase, each a small lifecycle state
machine declared in :data:`MACHINES` below (the runbook table renders
from the same structure):

- **Session**: ``open_session``/``open_micro_session`` must reach
  ``close_session`` — close is where status write-back and the
  mutation-detector hand-off happen, so a dropped session silently
  swallows a whole cycle's decisions.
- **Statement**: ``statement_factory(ssn)`` / ``ssn.statement()`` /
  ``Statement(ssn)`` must reach ``commit()`` or ``discard()`` on every
  exit path — an open statement's operations neither replay to the
  cache nor roll back, which is exactly the gang-atomicity hole the
  Statement exists to close.
- **Journal**: an ``append_intents``/``_journal_intents`` call must be
  followed by a dispatch (``_submit_write``/``_do_*``) or a confirm on
  every path, and a module that appends must also confirm somewhere
  (``recovery/`` is exempt in the confirm-only direction: takeover
  confirms orphans it did not append).
- **Circuit breaker**: tier transitions happen only through
  ``CircuitBreaker._transition`` inside ``faults/ladder.py``, and only
  between the declared states (closed/half_open/open).
- **StreamState**: after ``invalidate()`` the resident node table must
  not be read (``.nodes`` / ``apply_node_patches``) until
  ``adopt_full_cycle`` re-harvests it — a stale read is a solve
  against capacity that no longer exists.

The path engine is branch-sensitive and structural, not symbolic: it
walks every structurally distinguishable path through a function
(``if`` both ways, loop bodies once with an explicit iteration-end
check, ``try``/``finally`` threaded through every exit, ``return``/
``raise``/``break``/``continue`` as path exits). Conditions are not
evaluated — a path that your invariants make impossible still needs
the commit/discard on it, because the next refactor will make it
possible. Resources that *escape* (returned, aliased, stored on an
object) transfer ownership and stop being checked; passing a resource
as a call argument does **not** escape it (helpers operate on a
statement, the creator still owns the close).

Listener hygiene (KBT-C005) is lexical: a registration call
(``add_store_listener`` / ``.attach()``) is safe only when the paired
remove sits in a ``finally`` whose ``try`` starts at or immediately
after the registration, or when the enclosing class pairs it in a
teardown method (``detach``/``stop``/``close``/...). "Immediately"
is the point: one statement between register and ``try`` is one
exception away from a leaked listener that keeps waking a dead loop.
"""

from __future__ import annotations

import ast
from typing import Optional

from kube_batch_tpu.analysis import Finding, SourceFile

__all__ = ["MACHINES", "analyze"]

# The five declared lifecycle machines. ``states``/``edges`` document
# the protocol (and feed the runbook table); the remaining keys are the
# call-name alphabets the checker drives off, so the declaration *is*
# the configuration.
MACHINES: dict[str, dict] = {
    "session": {
        "title": "Session: open -> ... -> close_session",
        "states": ("open", "closed"),
        "edges": (("open", "close_session", "closed"),),
        "create": ("open_session", "open_micro_session"),
        "close_fn": ("close_session",),
        "code": "KBT-C001",
    },
    "statement": {
        "title": "Statement: operate -> commit | discard",
        "states": ("open", "committed", "discarded"),
        "edges": (("open", "commit", "committed"), ("open", "discard", "discarded")),
        "create": ("statement_factory",),
        "create_method": ("statement",),
        "create_class_suffix": "Statement",
        "close": ("commit", "discard"),
        "code": "KBT-C001",
    },
    "journal": {
        "title": "Write-intent journal: append -> dispatch -> confirm",
        "states": ("appended", "dispatched", "confirmed"),
        "edges": (
            ("appended", "dispatch", "dispatched"),
            ("dispatched", "confirm", "confirmed"),
            ("appended", "confirm", "confirmed"),  # landed-before-takeover
        ),
        "append": ("append_intents", "_journal_intents"),
        "dispatch": ("_submit_write", "_do_bind", "_do_bind_many", "_do_evict"),
        "confirm": ("confirm", "_journal_confirm"),
        "code": "KBT-C003",
    },
    "breaker": {
        "title": "Circuit breaker: closed -> open -> half_open -> closed",
        "states": ("closed", "open", "half_open"),
        "edges": (
            ("closed", "trip", "open"),
            ("open", "probe", "half_open"),
            ("half_open", "success", "closed"),
            ("half_open", "failure", "open"),
            ("open", "reset", "closed"),
            ("half_open", "reset", "closed"),
        ),
        "state_names": ("CLOSED", "OPEN", "HALF_OPEN"),
        "owner": "kube_batch_tpu/faults/ladder.py",
        "transition": "_transition",
        "code": "KBT-C002",
    },
    "stream_state": {
        "title": "StreamState: harvest -> patch -> invalidate -> re-harvest",
        "states": ("valid", "invalid"),
        "edges": (
            ("valid", "apply_node_patches", "valid"),
            ("valid", "invalidate", "invalid"),
            ("invalid", "adopt_full_cycle", "valid"),
        ),
        "invalidate": ("invalidate",),
        "reharvest": ("adopt_full_cycle",),
        "read_attrs": ("nodes",),
        "read_methods": ("apply_node_patches",),
        "code": "KBT-C004",
    },
}

# Cache dispatch (KBT-C002, Statement side): .bind/.bind_many/.evict on
# a receiver spelled `cache`/`_cache` is the raw mirror write the
# Statement/session layer exists to mediate. Only these files own it.
_DISPATCH_METHODS = ("bind", "bind_many", "evict")
_DISPATCH_RECEIVERS = ("cache", "_cache")
_DISPATCH_OWNERS = frozenset(
    {
        "kube_batch_tpu/framework/session.py",
        "kube_batch_tpu/framework/statement.py",
        "kube_batch_tpu/cache/cache.py",
    }
)

# Listener hygiene (KBT-C005).
_LISTENER_PAIRS = {"add_store_listener": "remove_store_listener", "attach": "detach"}
_TEARDOWN_METHODS = ("detach", "stop", "close", "shutdown", "unsubscribe", "__exit__")

# Modules exempt from the confirm-without-append direction of KBT-C003:
# takeover reconciliation confirms intents a dead leader appended.
_CONFIRM_EXEMPT_PREFIX = "kube_batch_tpu/recovery/"


def _terminal_name(fn: ast.expr) -> str:
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


# -- path engine --------------------------------------------------------------

_FALL, _RETURN, _RAISE, _BREAK, _CONTINUE = "fall", "return", "raise", "break", "continue"
_FN_EXITS = (_FALL, _RETURN, _RAISE)


class _PathEngine:
    """Walk one function body over every structurally distinguishable
    path. Semantics objects supply the transfer functions; the engine
    owns branching, loops (body once + iteration-end hook), try/finally
    threading, and path dedup (capped, so pathological functions
    degrade to fewer paths instead of exploding)."""

    MAX_PATHS = 256

    def __init__(self, sem: "_Semantics") -> None:
        self.sem = sem
        sem.engine = self
        self.loop_stack: list[int] = []

    def run(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        for kind, st in self._block(fn.body, self.sem.initial()):
            if kind in _FN_EXITS:
                self.sem.at_exit(kind, st)

    def _block(self, stmts: list[ast.stmt], state: dict) -> list[tuple[str, dict]]:
        paths = [(_FALL, state)]
        for stmt in stmts:
            nxt: list[tuple[str, dict]] = []
            for kind, st in paths:
                if kind != _FALL:
                    nxt.append((kind, st))
                else:
                    nxt.extend(self._stmt(stmt, st))
            paths = self._dedupe(nxt)
        return paths

    def _dedupe(self, paths: list[tuple[str, dict]]) -> list[tuple[str, dict]]:
        seen: set = set()
        out: list[tuple[str, dict]] = []
        for kind, st in paths:
            key = (kind, tuple(sorted(st.items())))
            if key not in seen:
                seen.add(key)
                out.append((kind, st))
        return out[: self.MAX_PATHS]

    def _stmt(self, stmt: ast.stmt, st: dict) -> list[tuple[str, dict]]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return [(_FALL, st)]  # nested defs run later, elsewhere
        if isinstance(stmt, ast.If):
            s2 = self.sem.visit_expr(stmt.test, st)
            return self._dedupe(
                self._block(stmt.body, dict(s2)) + self._block(stmt.orelse, dict(s2))
            )
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._loop(stmt, st)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, st)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                st = self.sem.visit_expr(item.context_expr, st)
            return self._block(stmt.body, st)
        if isinstance(stmt, ast.Return):
            return [(_RETURN, self.sem.on_return(stmt.value, st))]
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                st = self.sem.visit_expr(stmt.exc, st)
            return [(_RAISE, st)]
        if isinstance(stmt, ast.Break):
            return [(_BREAK, st)]
        if isinstance(stmt, ast.Continue):
            return [(_CONTINUE, st)]
        return [(_FALL, self.sem.visit_stmt(stmt, st))]

    def _loop(self, stmt, st: dict) -> list[tuple[str, dict]]:
        head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
        st = self.sem.visit_expr(head, st)
        out: list[tuple[str, dict]] = [(_FALL, dict(st))]  # zero iterations
        self.loop_stack.append(id(stmt))
        body = self._block(stmt.body, dict(st))
        self.loop_stack.pop()
        for kind, s in body:
            if kind in (_FALL, _CONTINUE):
                # the next iteration (or loop end) is about to rebind /
                # drop everything created in this body
                out.append((_FALL, self.sem.iteration_end(stmt, s)))
            elif kind == _BREAK:
                out.append((_FALL, s))
            else:
                out.append((kind, s))
        if stmt.orelse:
            nxt: list[tuple[str, dict]] = []
            for kind, s in out:
                if kind == _FALL:
                    nxt.extend(self._block(stmt.orelse, dict(s)))
                else:
                    nxt.append((kind, s))
            out = nxt
        return self._dedupe(out)

    def _try(self, stmt: ast.Try, st: dict) -> list[tuple[str, dict]]:
        entry = dict(st)
        body = self._block(stmt.body, dict(st))
        outs: list[tuple[str, dict]] = []
        if stmt.handlers:
            # a RAISE inside the body lands in a handler instead of
            # escaping; the handler may fire before any body effect, so
            # it runs from the entry state (conservative)
            outs.extend(e for e in body if e[0] != _RAISE)
            for h in stmt.handlers:
                outs.extend(self._block(h.body, dict(entry)))
        else:
            outs.extend(body)
        if stmt.orelse:
            nxt: list[tuple[str, dict]] = []
            for kind, s in outs:
                if kind == _FALL:
                    nxt.extend(self._block(stmt.orelse, dict(s)))
                else:
                    nxt.append((kind, s))
            outs = nxt
        if stmt.finalbody:
            nxt = []
            for kind, s in outs:
                for fk, fs in self._block(stmt.finalbody, dict(s)):
                    nxt.append((fk if fk != _FALL else kind, fs))
            # an exception part-way through the body still runs finally:
            # model it as one raising path from the entry state
            for fk, fs in self._block(stmt.finalbody, dict(entry)):
                nxt.append((fk if fk != _FALL else _RAISE, fs))
            outs = nxt
        return self._dedupe(outs)


# -- semantics ----------------------------------------------------------------

_OPEN, _CLOSED, _ESCAPED = "open", "closed", "escaped"


class _Semantics:
    def __init__(self, sf: SourceFile, qual: str, findings: list[Finding]) -> None:
        self.sf = sf
        self.qual = qual
        self.findings = findings
        self.engine: Optional[_PathEngine] = None
        self.reported: set = set()

    def emit(self, line: int, code: str, message: str, symbol: str) -> None:
        key = (line, code, symbol)
        if key not in self.reported:
            self.reported.add(key)
            self.findings.append(Finding(self.sf.path, line, code, message, symbol))

    def initial(self) -> dict:
        return {}

    def visit_stmt(self, stmt: ast.stmt, st: dict) -> dict:
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                st = self.visit_expr(child, st)
        return st

    def visit_expr(self, expr: Optional[ast.expr], st: dict) -> dict:
        return st

    def on_return(self, value: Optional[ast.expr], st: dict) -> dict:
        return self.visit_expr(value, st) if value is not None else st

    def iteration_end(self, loop: ast.stmt, st: dict) -> dict:
        return st

    def at_exit(self, kind: str, st: dict) -> None:
        pass


class _ResourceSem(_Semantics):
    """C001 (sessions + statements): track locals bound to a created
    resource until every path closes, escapes, or leaks it."""

    _SESSION_CREATE = MACHINES["session"]["create"]
    _SESSION_CLOSE_FN = MACHINES["session"]["close_fn"]
    _STMT_CREATE = MACHINES["statement"]["create"]
    _STMT_CREATE_METHOD = MACHINES["statement"]["create_method"]
    _STMT_SUFFIX = MACHINES["statement"]["create_class_suffix"]
    _STMT_CLOSE = MACHINES["statement"]["close"]

    def _creation_kind(self, call: ast.Call) -> Optional[str]:
        name = _terminal_name(call.func)
        if name in self._SESSION_CREATE:
            return "session"
        if name in self._STMT_CREATE:
            return "statement"
        if isinstance(call.func, ast.Attribute) and name in self._STMT_CREATE_METHOD:
            return "statement"
        if name.endswith(self._STMT_SUFFIX) and not name.startswith("_"):
            # public Statement classes (Statement, ScanStatement, ...);
            # underscore variants (e.g. recovery's _GangStatement) follow
            # the journal machine's eager-idempotent protocol instead
            return "statement"
        return None

    def visit_stmt(self, stmt: ast.stmt, st: dict) -> dict:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            kind = self._creation_kind(stmt.value)
            if kind is not None and len(stmt.targets) == 1 and isinstance(
                stmt.targets[0], ast.Name
            ):
                st = dict(st)
                for a in stmt.value.args:
                    st = self.visit_expr(a, st)
                for k in stmt.value.keywords:
                    st = self.visit_expr(k.value, st)
                var = stmt.targets[0].id
                prev = st.get(var)
                if prev is not None and prev[0] == _OPEN:
                    self._leak(var, prev, "re-assigned")
                loop = self.engine.loop_stack[-1] if self.engine.loop_stack else 0
                st[var] = (_OPEN, kind, stmt.lineno, loop)
                return st
        if isinstance(stmt, ast.Assign):
            st = self.visit_expr(stmt.value, st)
            st = dict(st)
            for t in stmt.targets:
                if isinstance(t, ast.Name) and t.id in st:
                    prev = st.pop(t.id)
                    if prev[0] == _OPEN:
                        self._leak(t.id, prev, "overwritten")
                else:
                    st = self.visit_expr(t, st)
            return st
        return super().visit_stmt(stmt, st)

    def visit_expr(self, expr: Optional[ast.expr], st: dict) -> dict:
        if expr is None:
            return st
        st = dict(st)
        self._walk(expr, st, escape_args=False)
        return st

    def on_return(self, value: Optional[ast.expr], st: dict) -> dict:
        if value is None:
            return st
        st = dict(st)
        # returning hands the resource (or anything holding it) out:
        # ownership transfers, the caller closes
        self._walk(value, st, escape_args=True)
        return st

    def _walk(self, node: ast.expr, st: dict, escape_args: bool) -> None:
        if isinstance(node, ast.Lambda):
            return  # deferred body, not this path
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            # close_session(var, ...) closes its first argument
            if (
                name in self._SESSION_CLOSE_FN
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in st
            ):
                v = node.args[0].id
                if st[v][0] == _OPEN:
                    st[v] = (_CLOSED,) + st[v][1:]
                rest = node.args[1:]
            else:
                rest = node.args
                fn = node.func
                if (
                    isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in st
                ):
                    # a method call on the resource: commit/discard close
                    # it, anything else (operate/pipeline/evict) is the
                    # protocol's operate phase — receiver stays owned
                    if fn.attr in self._STMT_CLOSE and st[fn.value.id][0] == _OPEN:
                        st[fn.value.id] = (_CLOSED,) + st[fn.value.id][1:]
                else:
                    self._walk(node.func, st, escape_args)
            for a in rest:
                if isinstance(a, ast.Name) and a.id in st and not escape_args:
                    continue  # pass-by-arg: the helper borrows, caller owns
                self._walk(a, st, escape_args)
            for k in node.keywords:
                if (
                    isinstance(k.value, ast.Name)
                    and k.value.id in st
                    and not escape_args
                ):
                    continue
                self._walk(k.value, st, escape_args)
            return
        if isinstance(node, ast.Name):
            if node.id in st and isinstance(node.ctx, ast.Load):
                st[node.id] = (_ESCAPED,) + st[node.id][1:]
            return
        if isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id in st:
                return  # plain attribute read on the resource: neutral
            self._walk(node.value, st, escape_args)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._walk(child, st, escape_args)
            elif isinstance(child, ast.keyword):
                self._walk(child.value, st, escape_args)
            elif isinstance(child, ast.comprehension):
                self._walk(child.iter, st, escape_args)
                for c in child.ifs:
                    self._walk(c, st, escape_args)

    def _leak(self, var: str, rec: tuple, how: str) -> None:
        _, kind, line, _ = rec
        if kind == "session":
            msg = (
                f"session opened into `{var}` here is {how} before "
                "close_session() — status write-back and resident-table "
                "hand-off are silently dropped"
            )
        else:
            msg = (
                f"Statement created into `{var}` here is {how} before "
                "commit()/discard() — its operations neither replay to "
                "the cache nor roll back"
            )
        self.emit(line, "KBT-C001", msg, f"{self.qual}.{var}")

    def iteration_end(self, loop: ast.stmt, st: dict) -> dict:
        st = dict(st)
        lid = id(loop)
        for var, rec in list(st.items()):
            if rec[0] == _OPEN and rec[3] == lid:
                self._leak(var, rec, "dropped at the end of the loop iteration")
                st[var] = (_CLOSED,) + rec[1:]  # report once
        return st

    def at_exit(self, kind: str, st: dict) -> None:
        how = {
            _FALL: "can reach the end of the function",
            _RETURN: "can reach a return",
            _RAISE: "can reach a raise",
        }[kind]
        for var, rec in sorted(st.items()):
            if rec[0] != _OPEN:
                continue
            _, rkind, line, _ = rec
            if rkind == "session":
                msg = (
                    f"session opened into `{var}` here {how} without "
                    "close_session() on that path"
                )
            else:
                msg = (
                    f"Statement created into `{var}` here {how} without "
                    "commit()/discard() on that path"
                )
            self.emit(line, "KBT-C001", msg, f"{self.qual}.{var}")


class _JournalSem(_Semantics):
    """C003 path direction: an append must reach a dispatch or confirm
    on every path out of the appending function (returning the seqs
    hands them to the caller and transfers the obligation)."""

    _APPEND = MACHINES["journal"]["append"]
    _CLOSERS = MACHINES["journal"]["dispatch"] + MACHINES["journal"]["confirm"]

    def visit_stmt(self, stmt: ast.stmt, st: dict) -> dict:
        call = None
        var = None
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
                var = stmt.targets[0].id
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        if call is not None and _terminal_name(call.func) in self._APPEND:
            st = dict(st)
            key = var if var is not None else f"@{stmt.lineno}"
            st[key] = (_OPEN, stmt.lineno)
            return st
        return super().visit_stmt(stmt, st)

    def visit_expr(self, expr: Optional[ast.expr], st: dict) -> dict:
        if expr is None:
            return st
        st = dict(st)
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and _terminal_name(node.func) in self._CLOSERS:
                for k, rec in list(st.items()):
                    if rec[0] == _OPEN:
                        st[k] = (_CLOSED, rec[1])
        return st

    def on_return(self, value: Optional[ast.expr], st: dict) -> dict:
        st = self.visit_expr(value, st) if value is not None else dict(st)
        if value is not None:
            names = {
                n.id for n in ast.walk(value) if isinstance(n, ast.Name)
            }
            for k, rec in list(st.items()):
                if k in names and rec[0] == _OPEN:
                    st[k] = (_ESCAPED, rec[1])
        return st

    def at_exit(self, kind: str, st: dict) -> None:
        for k, rec in sorted(st.items()):
            if rec[0] != _OPEN:
                continue
            self.emit(
                rec[1],
                "KBT-C003",
                "journal intent appended here can exit the function "
                "without a dispatch (_submit_write/_do_*) or confirm on "
                "that path — an orphan the next takeover re-litigates",
                f"{self.qual}.append",
            )


class _StreamStateSem(_Semantics):
    """C004: a receiver that was invalidate()d on this path must not
    serve .nodes / apply_node_patches until adopt_full_cycle."""

    _INVALIDATE = MACHINES["stream_state"]["invalidate"]
    _REHARVEST = MACHINES["stream_state"]["reharvest"]
    _READ_ATTRS = MACHINES["stream_state"]["read_attrs"]
    _READ_METHODS = MACHINES["stream_state"]["read_methods"]

    @staticmethod
    def _receiver_key(obj: ast.expr) -> Optional[str]:
        # Names and self-attributes only: deeper chains churn too much
        # to track soundly and never appear in the streaming layer.
        if isinstance(obj, ast.Name):
            return obj.id
        if (
            isinstance(obj, ast.Attribute)
            and isinstance(obj.value, ast.Name)
            and obj.value.id == "self"
        ):
            return f"self.{obj.attr}"
        return None

    def visit_expr(self, expr: Optional[ast.expr], st: dict) -> dict:
        if expr is None:
            return st
        st = dict(st)
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                key = self._receiver_key(node.func.value)
                if key is None:
                    continue
                if node.func.attr in self._INVALIDATE:
                    st[key] = ("stale", node.lineno)
                elif node.func.attr in self._REHARVEST:
                    st.pop(key, None)
                elif node.func.attr in self._READ_METHODS and key in st:
                    self._stale_read(node, key, st[key], node.func.attr + "()")
            elif (
                isinstance(node, ast.Attribute)
                and node.attr in self._READ_ATTRS
                and isinstance(node.ctx, ast.Load)
            ):
                key = self._receiver_key(node.value)
                if key is not None and key in st:
                    self._stale_read(node, key, st[key], "." + node.attr)
        return st

    def _stale_read(self, node: ast.expr, key: str, rec: tuple, what: str) -> None:
        self.emit(
            node.lineno,
            "KBT-C004",
            f"resident table of `{key}` read via {what} after "
            f"invalidate() on line {rec[1]} with no adopt_full_cycle "
            "re-harvest in between — a solve against capacity that no "
            "longer exists",
            f"{self.qual}.{key}",
        )


# -- non-path checks ----------------------------------------------------------


def _check_dispatch_scope(sf: SourceFile, findings: list[Finding]) -> None:
    """C002, cache side: raw mirror writes outside the owning layer."""
    if sf.path in _DISPATCH_OWNERS:
        return
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in _DISPATCH_METHODS:
            continue
        recv = node.func.value
        recv_name = (
            recv.id if isinstance(recv, ast.Name)
            else recv.attr if isinstance(recv, ast.Attribute)
            else ""
        )
        if recv_name in _DISPATCH_RECEIVERS:
            findings.append(
                Finding(
                    sf.path,
                    node.lineno,
                    "KBT-C002",
                    f"cache.{node.func.attr}() called outside the "
                    "Statement/session layer — the write skips the "
                    "operation log (no gang rollback) and the share "
                    "event handlers",
                    symbol=f"cache.{node.func.attr}",
                )
            )


def _check_breaker_scope(sf: SourceFile, findings: list[Finding]) -> None:
    """C002, breaker side: transitions only inside the owner module and
    only between declared states."""
    m = MACHINES["breaker"]
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr != m["transition"]:
            continue
        if sf.path != m["owner"]:
            findings.append(
                Finding(
                    sf.path,
                    node.lineno,
                    "KBT-C002",
                    f"breaker {m['transition']}() called outside "
                    f"{m['owner']} — tier state changes bypass the "
                    "ladder's lock/backoff discipline",
                    symbol=f"breaker.{m['transition']}",
                )
            )
            continue
        arg = node.args[0] if node.args else None
        bad = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if arg.value not in m["states"]:
                bad = f"state literal {arg.value!r}"
        elif isinstance(arg, ast.Name) and arg.id not in m["state_names"]:
            bad = f"state name `{arg.id}`"
        if bad is not None:
            findings.append(
                Finding(
                    sf.path,
                    node.lineno,
                    "KBT-C002",
                    f"breaker transition to {bad} is outside the "
                    f"declared alphabet {m['states']}",
                    symbol="breaker.alphabet",
                )
            )


def _calls_in(node: ast.AST) -> set[str]:
    return {
        _terminal_name(c.func)
        for c in ast.walk(node)
        if isinstance(c, ast.Call)
    }


def _check_listeners(sf: SourceFile, findings: list[Finding]) -> None:
    """C005: every registration needs its remove on the teardown path —
    a finally whose try starts at or immediately after the
    registration, or a paired class teardown method."""
    for holder, cls in _functions(sf.tree):
        for fn in holder:
            regs: list[tuple[ast.Call, str]] = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = _terminal_name(node.func)
                    if name in _LISTENER_PAIRS:
                        regs.append((node, _LISTENER_PAIRS[name]))
            if not regs:
                continue
            protected: dict[str, set[int]] = {}
            _protected_lines(fn.body, protected)
            teardown_removes: set[str] = set()
            if cls is not None:
                for meth in cls.body:
                    if (
                        isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and meth.name in _TEARDOWN_METHODS
                    ):
                        teardown_removes |= _calls_in(meth)
            for call, remove in regs:
                if call.lineno in protected.get(remove, set()):
                    continue
                if remove in teardown_removes:
                    continue
                reg = _terminal_name(call.func)
                findings.append(
                    Finding(
                        sf.path,
                        call.lineno,
                        "KBT-C005",
                        f"{reg}() registered with no {remove}() on the "
                        "teardown path (needs a finally starting at or "
                        "immediately after the registration, or a paired "
                        f"{'/'.join(_TEARDOWN_METHODS[:3])} method on the "
                        "class) — the dead listener keeps firing into a "
                        "stopped loop",
                        symbol=f"{_qual(cls, fn)}.{reg}",
                    )
                )


def _protected_lines(stmts: list[ast.stmt], out: dict[str, set[int]]) -> None:
    """remove-name -> line numbers whose registration is covered by a
    finally containing that remove: the try body plus the single
    statement immediately preceding the try."""
    for i, s in enumerate(stmts):
        if isinstance(s, ast.Try) and s.finalbody:
            removes = set()
            for fb in s.finalbody:
                removes |= _calls_in(fb)
            region: set[int] = set()
            for b in s.body:
                region.update(range(b.lineno, (b.end_lineno or b.lineno) + 1))
            if i > 0:
                prev = stmts[i - 1]
                region.update(range(prev.lineno, (prev.end_lineno or prev.lineno) + 1))
            for r in removes:
                out.setdefault(r, set()).update(region)
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(s, attr, None)
            if isinstance(sub, list) and sub and isinstance(sub[0], ast.stmt):
                _protected_lines(sub, out)
        for h in getattr(s, "handlers", []) or []:
            _protected_lines(h.body, out)


def _check_journal_module(sf: SourceFile, findings: list[Finding]) -> None:
    """C003 module direction: appends and confirms must co-exist."""
    m = MACHINES["journal"]
    appends: list[ast.Call] = []
    confirms: list[ast.Call] = []
    dispatches = 0
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name in m["append"]:
            appends.append(node)
        elif name in m["confirm"]:
            confirms.append(node)
        elif name in m["dispatch"]:
            dispatches += 1
    exempt = sf.path.startswith(_CONFIRM_EXEMPT_PREFIX)
    if appends and not confirms and not dispatches and not exempt:
        findings.append(
            Finding(
                sf.path,
                appends[0].lineno,
                "KBT-C003",
                "module appends journal intents but never dispatches or "
                "confirms — every intent it writes is an orphan",
                symbol="journal.append_only",
            )
        )
    if confirms and not appends and not exempt:
        findings.append(
            Finding(
                sf.path,
                confirms[0].lineno,
                "KBT-C003",
                "module confirms journal intents it never appends — "
                "outside recovery/ (takeover confirms a dead leader's "
                "intents) that is a sequencing inversion",
                symbol="journal.confirm_only",
            )
        )


# -- driver -------------------------------------------------------------------


def _functions(tree: ast.AST):
    """Yield (functions, owning class-or-None) at module level and one
    class level deep — the whole codebase's shape."""
    mod_fns = [
        n for n in getattr(tree, "body", [])
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    if mod_fns:
        yield mod_fns, None
    for n in getattr(tree, "body", []):
        if isinstance(n, ast.ClassDef):
            meths = [
                m for m in n.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            if meths:
                yield meths, n


def _qual(cls: Optional[ast.ClassDef], fn) -> str:
    return f"{cls.name}.{fn.name}" if cls is not None else fn.name


def analyze(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        _check_dispatch_scope(sf, findings)
        _check_breaker_scope(sf, findings)
        _check_listeners(sf, findings)
        _check_journal_module(sf, findings)
        for holder, cls in _functions(sf.tree):
            for fn in holder:
                qual = _qual(cls, fn)
                for sem_cls in (_ResourceSem, _JournalSem, _StreamStateSem):
                    sem = sem_cls(sf, qual, findings)
                    _PathEngine(sem).run(fn)
    return findings
