"""A4 — snapshot-escape analyzer (KBT-S001/S002).

Session snapshots (``ssn.jobs`` / ``ssn.nodes`` / ``ssn.queues``) are
clones the actions and plugins reason over for one cycle. Mutating them
is legal only through the session/Statement APIs (``ssn.allocate``,
``ssn.evict``, ``stmt.evict/pipeline``): those maintain the operation
log (so a gang that misses quorum rolls back), bump ``state_seq`` (so
memoized scorers invalidate), and fire the allocate/deallocate event
handlers (so DRF/proportion shares track reality). A direct write —
``task.node_name = n`` or ``node.add_task(task)`` from an action —
skips all three: shares desync silently and the mutation survives
``Statement.discard``.

The analyzer runs over ``plugins/`` and ``actions/`` and performs a
per-function lexical taint walk:

- roots: any expression reaching through ``ssn.jobs`` / ``ssn.nodes``
  / ``ssn.queues`` (also ``session.``); taint propagates through
  subscripts, ``.get()`` / ``.values()`` / ``.items()`` / ``.pop()``,
  iteration (``for job in ssn.jobs.values():``), simple assignment,
  and snapshot-graph attributes (``job.tasks``,
  ``job.task_status_index``, ``node.tasks``);
- violations: an attribute store whose base is tainted (S001), or a
  call of a known mutator method (``add_task``, ``remove_task``,
  ``update_task``, ``update_task_status``, ``add_task_info``,
  ``delete_task_info``, ``set_pod_group``, ``set_pdb``, ``set_node``)
  on a tainted receiver (S002).

Calls on ``ssn``/``stmt``/``statement`` objects themselves are the
sanctioned API and never flagged. The walk is intra-procedural and
under-approximate by design (taint does not flow through ``self.*`` or
collections built elsewhere); vetted bulk-replay equivalents that fire
anyway belong in the baseline with their parity evidence as the reason.
"""

from __future__ import annotations

import ast

from kube_batch_tpu.analysis import Finding, SourceFile

SESSION_NAMES = {"ssn", "session"}
SNAPSHOT_COLLECTIONS = {"jobs", "nodes", "queues"}
# attributes that stay inside the snapshot object graph
GRAPH_ATTRS = {"tasks", "task_status_index", "pod_group", "pdb", "nodes", "jobs"}
DERIVING_METHODS = {"get", "values", "items", "pop", "clone_shallow"}
MUTATORS = {
    "add_task", "remove_task", "update_task", "update_task_status",
    "add_task_info", "delete_task_info", "set_pod_group",
    "unset_pod_group", "set_pdb", "unset_pdb", "set_node",
}
SCOPES = ("kube_batch_tpu/plugins/", "kube_batch_tpu/actions/")


class _FunctionTaint(ast.NodeVisitor):
    def __init__(self, sf: SourceFile, qualname: str, findings: list[Finding]) -> None:
        self.sf = sf
        self.qualname = qualname
        self.findings = findings
        self.tainted: set[str] = set()

    # -- taint predicates ----------------------------------------------------

    def _is_tainted(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            base = node.value
            # ssn.jobs / ssn.nodes / ssn.queues roots
            if (
                isinstance(base, ast.Name)
                and base.id in SESSION_NAMES
                and node.attr in SNAPSHOT_COLLECTIONS
            ):
                return True
            # job.tasks etc: stay in the graph
            if node.attr in GRAPH_ATTRS and self._is_tainted(base):
                return True
            return False
        if isinstance(node, ast.Subscript):
            return self._is_tainted(node.value)
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in DERIVING_METHODS:
                return self._is_tainted(fn.value)
            return False
        if isinstance(node, (ast.IfExp,)):
            return self._is_tainted(node.body) or self._is_tainted(node.orelse)
        if isinstance(node, ast.BoolOp):
            return any(self._is_tainted(v) for v in node.values)
        return False

    def _taint_target(self, target: ast.expr) -> None:
        if isinstance(target, ast.Name):
            self.tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._taint_target(e)

    # -- propagation ---------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_store_targets(node.targets, node)
        if self._is_tainted(node.value):
            for t in node.targets:
                self._taint_target(t)
        else:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.tainted.discard(t.id)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store_targets([node.target], node)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if self._is_tainted(node.iter):
            self._taint_target(node.target)
        self.generic_visit(node)

    def visit_comprehension_gens(self, gens) -> None:
        for g in gens:
            if self._is_tainted(g.iter):
                self._taint_target(g.target)

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    def visit_SetComp(self, node: ast.SetComp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        self.visit_comprehension_gens(node.generators)
        self.generic_visit(node)

    # -- violations ----------------------------------------------------------

    def _noqa(self, lineno: int) -> bool:
        lines = self.sf.lines
        return 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]

    def _check_store_targets(self, targets, node: ast.AST) -> None:
        for t in targets:
            if isinstance(t, ast.Attribute) and self._is_tainted(t.value):
                if not self._noqa(node.lineno):
                    base = ast.unparse(t.value) if hasattr(ast, "unparse") else "?"
                    self.findings.append(
                        Finding(
                            self.sf.path, node.lineno, "KBT-S001",
                            f"direct write to snapshot object attribute "
                            f"`{base}.{t.attr}` in {self.qualname} — go "
                            "through ssn.allocate/evict or a Statement so "
                            "the op log, state_seq and event handlers see it",
                            symbol=f"{self.qualname}.{t.attr}",
                        )
                    )
            elif isinstance(t, ast.Subscript) and self._is_tainted(t.value):
                if not self._noqa(node.lineno):
                    self.findings.append(
                        Finding(
                            self.sf.path, node.lineno, "KBT-S001",
                            f"direct item write into a snapshot collection "
                            f"in {self.qualname} — snapshot membership "
                            "changes must go through the session APIs",
                            symbol=f"{self.qualname}.[]",
                        )
                    )

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in MUTATORS
            and self._is_tainted(fn.value)
        ):
            if not self._noqa(node.lineno):
                self.findings.append(
                    Finding(
                        self.sf.path, node.lineno, "KBT-S002",
                        f"snapshot mutator .{fn.attr}() called directly in "
                        f"{self.qualname} — use ssn.allocate/evict or a "
                        "Statement (undo log + events + state_seq)",
                        symbol=f"{self.qualname}.{fn.attr}",
                    )
                )
        self.generic_visit(node)


def _outer_functions(tree: ast.AST):
    """Module-level functions and class methods; nested defs are walked
    inside their parent's checker so closures share its taint."""
    stack = [(tree, "")]
    while stack:
        node, prefix = stack.pop()
        body = getattr(node, "body", [])
        for child in body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, f"{prefix}{child.name}"
            elif isinstance(child, ast.ClassDef):
                stack.append((child, f"{prefix}{child.name}."))


def analyze(files: list[SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for sf in files:
        if not sf.path.startswith(SCOPES):
            continue
        for fn, qualname in _outer_functions(sf.tree):
            _FunctionTaint(sf, qualname, findings).generic_visit(fn)
    return findings
