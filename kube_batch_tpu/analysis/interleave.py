"""Deterministic interleaving model checker for the streaming/store
concurrency layer (KBT-I0xx, its own CLI:
``python -m kube_batch_tpu.analysis.interleave``).

The static suite proves lifecycle and lock invariants *per path*; this
module proves them *per schedule*. The production concurrency units —
store event fan-out, micro-cycle drains, full cycles, takeover
reconciliation, late in-flight dispatches — are modeled as **logical
threads**: lists of named atomic steps executed from one real driver
thread against the real objects (``ClusterStore``, ``SchedulerCache``
without its writer pool so every dispatch is inline, ``StreamTrigger``
/ ``StreamState`` wired exactly as the crash-consistency e2e wires
them). The explorer then drives each scenario through **every
distinguishable interleaving** of those steps and checks, per
schedule:

- the scenario's invariants (all arrivals bound, no arrival lost from
  the backlog, journal left with zero orphans, placements equal to the
  uninterrupted twin, ...);
- zero lost and zero duplicate binds, counted as store-level
  ``"" -> node`` transitions by an event handler — the same detector
  tests/test_streaming.py pins the crash e2e with;
- bind-for-bind parity across schedules: every clean schedule of a
  parity scenario must produce the identical placement map;
- no lock-order reversal, via a :class:`LockOrderWitness` wrapped
  around the real locks (store/cache/trigger/journal);
- footprint honesty: each step declares the shared state it may touch,
  and the witness's ``on_acquire`` hook records what it *actually*
  locked — an undeclared acquisition is itself a finding, because the
  pruning below would then be unsound.

**DPOR-lite**: two adjacent steps from different threads with disjoint
declared footprints commute, so their two orders are the same trace.
The explorer enumerates only the canonical representative of each
commutation class (the lexicographic normal form: no adjacent pair may
have ``tid(a) > tid(b)`` with independent footprints) — classic
partial-order reduction, sized down for fixed finite scenarios.

**Determinism / replay**: scenarios use a :class:`VirtualClock`
(injected into the degradation-ladder breakers, advanced once per
step), fresh worlds per schedule, and no randomness — a schedule is
fully identified by its trace id ``<scenario>:<tid digits>``. A
counterexample's trace id is its replay seed:
``python -m kube_batch_tpu.analysis.interleave --replay broken_drain:011``
re-runs exactly that schedule step by step, verbosely.

The seven default scenarios: ``micro_vs_full``, ``event_vs_invalidate``,
``takeover_vs_dispatch``, ``watch410_vs_drain`` (ISSUE 9),
``two_scheduler_conflict`` (ISSUE 10 — two federated schedulers racing
optimistic gang dispatches onto one node),
``dispatch_vs_next_solve`` (ISSUE 13 — cycle N's deferred dispatch
racing cycle N+1's snapshot through the KBT_PIPELINE dispatch
fence), and ``adopt_vs_dispatch`` (ISSUE 16 — slot adoption racing a
straggler conditional dispatch from the killed owner). The
intentionally broken fixture
``broken_drain`` (a trigger whose ``drain()`` empties the backlog
instead of copy-until-prune) is excluded from the default set; it
exists so the seeded-counterexample loop stays demonstrably alive —
``tests/test_interleave.py`` replays its counterexample by trace id.

Baseline: ``hack/interleave-baseline.toml`` (same grammar/loader as
the lint baseline; absent file = empty baseline). Zero live entries
today — the four scenarios explore clean.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from kube_batch_tpu.analysis import (
    Finding,
    apply_baseline,
    load_baseline,
    repo_root,
)

__all__ = [
    "VirtualClock",
    "Step",
    "Scenario",
    "ScheduleResult",
    "ScenarioReport",
    "SCENARIOS",
    "FIXTURES",
    "explore",
    "main",
]

_SELF_PATH = "kube_batch_tpu/analysis/interleave.py"
BASELINE = os.path.join("hack", "interleave-baseline.toml")


class VirtualClock:
    """Deterministic monotonic clock: schedule position, not wall time.
    Injected into the degradation ladder's breakers for the duration of
    a drive so any backoff/half-open decision depends on the schedule
    alone, and advanced one tick per executed step."""

    def __init__(self, start: float = 0.0) -> None:
        self._t = start

    def now(self) -> float:
        return self._t

    def advance(self, dt: float = 1.0) -> float:
        self._t += dt
        return self._t


@dataclass(frozen=True)
class Step:
    """One atomic unit of a logical thread. ``footprint`` declares the
    shared state the step may touch — lock names as wrapped by the
    scenario witness, plus virtual tokens (``stream_state``) for shared
    objects that have no lock. Disjoint footprints ⇒ the steps commute
    (checked at runtime against the locks actually acquired)."""

    name: str
    fn: Callable[[], None]
    footprint: frozenset


@dataclass
class ScheduleResult:
    trace: str  # "<scenario>:<tid digits>"
    steps: list  # [(virtual time, tid, step name)]
    violations: list  # [str]
    fingerprint: object = None  # placement map for parity comparison


@dataclass
class ScenarioReport:
    name: str
    describe: str
    schedules: int = 0
    pruned_branches: int = 0
    results: list = field(default_factory=list)  # [ScheduleResult]

    @property
    def counterexamples(self) -> list:
        return [r for r in self.results if r.violations]

    def findings(self) -> list:
        out = []
        for r in self.counterexamples:
            for v in r.violations:
                code = "KBT-I002" if "footprint" in v or "model error" in v else "KBT-I001"
                out.append(
                    Finding(
                        _SELF_PATH, 1, code,
                        f"[{r.trace}] {v} (replay: python -m "
                        f"kube_batch_tpu.analysis.interleave --replay {r.trace})",
                        symbol=r.trace,
                    )
                )
        return out


# -- schedule enumeration (lexicographic normal forms) ------------------------


def _schedules(plan: list) -> tuple[list, int]:
    """All canonical interleavings of ``plan`` (a list of per-thread
    Step lists). A sequence is canonical iff no adjacent pair has
    ``tid(a) > tid(b)`` with disjoint footprints — exactly one
    representative per commutation class survives. Returns
    (orders, pruned branch count)."""
    counts = [len(t) for t in plan]
    total = sum(counts)
    out: list = []
    pruned = 0

    def rec(prefix: list, pos: list, last) -> None:
        nonlocal pruned
        if len(prefix) == total:
            out.append(tuple(prefix))
            return
        for tid in range(len(plan)):
            if pos[tid] >= counts[tid]:
                continue
            step = plan[tid][pos[tid]]
            if last is not None:
                ltid, lstep = last
                if ltid > tid and not (lstep.footprint & step.footprint):
                    pruned += 1  # swap-equivalent canonical form exists
                    continue
            prefix.append(tid)
            pos[tid] += 1
            rec(prefix, pos, (tid, step))
            prefix.pop()
            pos[tid] -= 1

    rec([], [0] * len(plan), None)
    return out, pruned


# -- scenario scaffolding -----------------------------------------------------

# Serial pipeline without drf/proportion, the conf the streaming parity
# suite states its bind-for-bind invariant over (tests/test_streaming.py).
_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: true
"""

# Footprint tokens. Lock names match the witness wrapping in
# Scenario._wire; STATE is the virtual token for the (lockless,
# loop-thread-confined) StreamState resident table.
L_STORE = "store._lock"
L_CACHE = "cache._mutex"
L_TRIG = "trigger._lock"
L_JOURNAL = "journal._lock"
STATE = "stream_state"
F_ALL = frozenset({L_STORE, L_CACHE, L_TRIG, L_JOURNAL, STATE})
F_EVENT = frozenset({L_STORE, L_CACHE, L_TRIG})
F_STATE = frozenset({STATE})
F_TRIG = frozenset({L_TRIG})


class Scenario:
    """One fixed concurrency drama. ``build()`` constructs a fresh
    world and sets ``self.threads``; the explorer executes one schedule
    and then calls ``invariants()`` / ``fingerprint()``."""

    name = ""
    describe = ""
    parity = True  # clean schedules must agree on fingerprint()

    def __init__(self, workdir: str) -> None:
        self.workdir = workdir
        self.clock = VirtualClock()
        self.threads: list = []
        self._orig_breaker_clocks: dict = {}
        self.journal = None
        self.standby_journal = None

    # -- world building (mirrors tests/test_streaming.py's harness) ----------

    def _wire(
        self,
        nodes: int = 4,
        die_after: Optional[int] = None,
        conf_text: str = _CONF,
    ):
        from kube_batch_tpu import faults
        from kube_batch_tpu.cache import ClusterStore, SchedulerCache
        from kube_batch_tpu.cache.store import PODS, EventHandler
        from kube_batch_tpu.recovery import WriteIntentJournal
        from kube_batch_tpu.scheduler import Scheduler
        from kube_batch_tpu.streaming import StreamState, StreamTrigger
        from kube_batch_tpu.utils.locking import LockOrderWitness

        conf = os.path.join(self.workdir, "conf.yaml")
        with open(conf, "w", encoding="utf-8") as fh:
            fh.write(conf_text)
        self.store = ClusterStore()
        self._seed(self.store, nodes)
        self.bind_counts: dict = {}

        def on_update(old, new):
            if not old.node_name and new.node_name:
                key = f"{new.namespace}/{new.name}"
                self.bind_counts[key] = self.bind_counts.get(key, 0) + 1

        self.store.add_event_handler(PODS, EventHandler(on_update=on_update))
        self.journal = WriteIntentJournal(os.path.join(self.workdir, "leader.wal"))
        binder = None
        if die_after is not None:
            binder = _DyingBinder(self.store, die_after)
        self.cache = SchedulerCache(self.store, journal=self.journal, binder=binder)
        # no cache.run(): the writer pool stays off, every dispatch is
        # inline — the step IS the dispatch, which is what makes the
        # schedule the only source of nondeterminism
        self.sched = Scheduler(
            self.cache, scheduler_conf=conf, schedule_period=1000.0
        )
        self.trigger = self._make_trigger()
        self.state = StreamState()
        self.sched._stream_trigger = self.trigger
        self.sched._stream_state = self.state
        self.trigger.attach()

        self.witness = LockOrderWitness()
        self.store._lock = self.witness.wrap(L_STORE, self.store._lock)
        self.cache._mutex = self.witness.wrap(L_CACHE, self.cache._mutex)
        self.trigger._lock = self.witness.wrap(L_TRIG, self.trigger._lock)
        self.journal._lock = self.witness.wrap(L_JOURNAL, self.journal._lock)
        # Field-level witness over the lockless resident table: every
        # actual StreamState access reports as the STATE token, so a
        # step that touches it without declaring STATE in its footprint
        # is caught the same way an undeclared lock acquire is (the
        # explorer's on_access hook feeds the same observed set).
        from kube_batch_tpu.utils.race import RaceWitness

        self.race = RaceWitness(clock=self.clock.now)
        self.race.watch(
            self.state,
            {"nodes": "touch", "valid": "rw", "reason": "rw"},
            token=STATE,
        )
        for b in faults.solver_ladder.breakers.values():
            self._orig_breaker_clocks[b] = b._clock
            b._clock = self.clock.now

    @staticmethod
    def _make_trigger():
        from kube_batch_tpu.streaming import StreamTrigger

        return StreamTrigger()

    @staticmethod
    def _seed(store, nodes: int) -> None:
        from kube_batch_tpu.testing import build_node, build_queue, build_resource_list

        store.create_queue(build_queue("default"))
        for i in range(nodes):
            store.create_node(
                build_node(
                    f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=64)
                )
            )

    @staticmethod
    def _arrive(store, name: str, members: int) -> None:
        from kube_batch_tpu.testing import build_pod, build_pod_group, build_resource_list

        store.create_pod_group(build_pod_group(name, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{name}-p{m}", group_name=name,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )

    # -- step factories -------------------------------------------------------

    def s_full(self, label: str = "full_cycle") -> Step:
        return Step(label, self.sched.run_once, F_ALL)

    def s_micro(self, label: str = "micro_drain") -> Step:
        def fn():
            self.sched.run_micro(self.trigger.drain())

        return Step(label, fn, F_ALL)

    def s_arrive(self, gang: str, members: int) -> Step:
        return Step(
            f"arrive_{gang}",
            lambda: self._arrive(self.store, gang, members),
            F_EVENT,
        )

    # -- harness surface ------------------------------------------------------

    def build(self) -> None:
        raise NotImplementedError

    def placements(self) -> dict:
        from kube_batch_tpu.cache.store import PODS

        return {
            f"{p.namespace}/{p.name}": p.node_name for p in self.store.list(PODS)
        }

    def fingerprint(self):
        return self.placements()

    def invariants(self) -> list:
        out = []
        placed = self.placements()
        unbound = sorted(k for k, v in placed.items() if not v)
        if unbound:
            out.append(f"arrivals never bound: {unbound}")
        dupes = {k: n for k, n in self.bind_counts.items() if n != 1}
        if dupes:
            out.append(f"non-exactly-once bind transitions: {dupes}")
        out.extend(self._journal_invariant())
        return out

    def _journal_invariant(self) -> list:
        from kube_batch_tpu.recovery import WriteIntentJournal

        if self.journal is None:
            return []
        orphans = WriteIntentJournal.replay(self.journal.path).orphans
        if orphans:
            return [
                "journal left with unconfirmed intents: "
                + ", ".join(f"{i.op} {i.pod} seq={i.seq}" for i in orphans)
            ]
        return []

    def cleanup(self) -> None:
        try:
            self.trigger.detach()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        for j in (self.journal, self.standby_journal):
            if j is not None:
                try:
                    j.close()
                except Exception:  # noqa: BLE001
                    pass
        for b, clk in self._orig_breaker_clocks.items():
            b._clock = clk


class _DyingBinder:
    """SIGKILL stand-in (the crash e2e's device): the Nth store bind
    raises a BaseException no retry ladder survives."""

    class LeaderKilled(BaseException):
        pass

    def __init__(self, store, die_after: int) -> None:
        from kube_batch_tpu.cache.cache import StoreBinder

        self._inner = StoreBinder(store)
        self.left = die_after

    def bind(self, pod, hostname: str) -> None:
        if self.left <= 0:
            raise _DyingBinder.LeaderKilled()
        self.left -= 1
        self._inner.bind(pod, hostname)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


# -- the four scenarios -------------------------------------------------------


class MicroVsFull(Scenario):
    name = "micro_vs_full"
    describe = (
        "a gang arrival + micro-cycle drain racing the periodic full "
        "cycle and its backstop: every schedule must bind the gang "
        "exactly once, identically"
    )

    def build(self) -> None:
        self._wire(nodes=4)
        self.sched.run_once()  # adopt the resident table
        self.threads = [
            [self.s_full("full_cycle"), self.s_full("full_backstop")],
            [self.s_arrive("g1", 3), self.s_micro()],
        ]


class EventVsInvalidate(Scenario):
    name = "event_vs_invalidate"
    describe = (
        "a node-patch event + arrival + micro racing an external "
        "resident-table invalidation and the full cycle that re-adopts "
        "it: the invalid window may skip the micro but never lose the "
        "arrival or resurrect the dead table"
    )

    def build(self) -> None:
        self._wire(nodes=4)
        self.sched.run_once()

        def patch_node():
            # same-capacity relabel of an existing node: the patch
            # flows through trigger -> apply_node_patches without
            # changing any placement decision (parity stays exact)
            from kube_batch_tpu.testing import build_node, build_resource_list

            self.store.update_node(
                build_node(
                    "n0",
                    build_resource_list(cpu=16, memory="16Gi", pods=64),
                    labels={"interleave/patched": "1"},
                )
            )

        self.threads = [
            [
                Step(
                    "invalidate_resident",
                    lambda: self.state.invalidate("external bound churn"),
                    F_STATE,
                ),
                self.s_full("full_readopt"),
            ],
            [
                Step("node_patch_event", patch_node, F_EVENT),
                self.s_arrive("g1", 3),
                self.s_micro(),
            ],
        ]


class TakeoverVsDispatch(Scenario):
    name = "takeover_vs_dispatch"
    describe = (
        "a leader killed mid-micro-dispatch left the journal holding an "
        "in-flight intent; the standby's reconciliation + full cycle "
        "race the dead leader's late-landing store write: idempotent "
        "re-dispatch must converge to the uninterrupted twin with zero "
        "lost and zero duplicate binds in every order"
    )

    def build(self) -> None:
        from kube_batch_tpu.cache import ClusterStore, SchedulerCache
        from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal
        from kube_batch_tpu.scheduler import Scheduler

        # the uninterrupted twin: one full cycle over the complete world
        twin = ClusterStore()
        self._seed(twin, 4)
        self._arrive(twin, "g0", 6)
        conf = os.path.join(self.workdir, "twin.yaml")
        with open(conf, "w", encoding="utf-8") as fh:
            fh.write(_CONF)
        Scheduler(SchedulerCache(twin), scheduler_conf=conf).run_once()
        from kube_batch_tpu.cache.store import PODS

        self.expected = {
            f"{p.namespace}/{p.name}": p.node_name for p in twin.list(PODS)
        }
        if not all(self.expected.values()):
            raise RuntimeError("model error: twin full cycle left pods unbound")

        # the real run: leader dies on its third inline dispatch
        self._wire(nodes=4, die_after=2)
        self.sched.run_once()
        self._arrive(self.store, "g0", 6)
        try:
            self.sched.run_micro(self.trigger.drain())
        except _DyingBinder.LeaderKilled:
            pass
        else:
            raise RuntimeError("model error: DyingBinder never fired")
        replay = WriteIntentJournal.replay(self.journal.path)
        if not replay.orphans:
            raise RuntimeError("model error: kill left no in-flight intent")
        orphan = min(replay.orphans, key=lambda i: i.seq)
        self.standby_journal = WriteIntentJournal(self.journal.path)

        def straggler():
            # the dead leader's write was already in flight: it lands
            # late, bound for exactly the journaled node
            from kube_batch_tpu.cache.cache import StoreBinder

            ns, _, pname = orphan.pod.partition("/")
            pod = self.store.get_pod(ns, pname)
            if pod is not None:
                StoreBinder(self.store).bind(pod, orphan.node)

        def reconcile():
            reconcile_journal(self.standby_journal, self.store)

        def standby_full():
            conf2 = os.path.join(self.workdir, "standby.yaml")
            with open(conf2, "w", encoding="utf-8") as fh:
                fh.write(_CONF)
            Scheduler(
                SchedulerCache(self.store), scheduler_conf=conf2
            ).run_once()

        self.threads = [
            [Step("late_dispatch_lands", straggler, F_EVENT)],
            [
                Step("takeover_reconcile", reconcile, F_ALL),
                Step("standby_full_cycle", standby_full, F_EVENT | {L_JOURNAL}),
            ],
        ]

    def invariants(self) -> list:
        out = super().invariants()
        placed = self.placements()
        if placed != self.expected:
            diff = {
                k: (placed.get(k), self.expected.get(k))
                for k in set(placed) | set(self.expected)
                if placed.get(k) != self.expected.get(k)
            }
            out.append(f"diverged from the uninterrupted twin: {diff}")
        return out


class Watch410VsDrain(Scenario):
    name = "watch410_vs_drain"
    describe = (
        "a watch client re-listing after 410 Gone re-delivers the "
        "gang's add events into the dirty feed while the micro drain "
        "and backstop run: duplicate deliveries must never double-bind "
        "or lose an arrival"
    )

    def build(self) -> None:
        from kube_batch_tpu.cache.store import PODS

        self._wire(nodes=4)
        self.sched.run_once()
        self._arrive(self.store, "g0", 3)
        relisted = [p for p in self.store.list(PODS)]

        def relist_dup():
            # the re-list window re-emits adds for objects already
            # delivered — straight into the module dirty feed, exactly
            # where cache.py publishes store events
            from kube_batch_tpu.ops import encode_cache

            for p in relisted:
                encode_cache.note_store_event(PODS, p.metadata.uid, p, None)

        self.threads = [
            [Step("relist_duplicates", relist_dup, F_TRIG)],
            [self.s_micro(), self.s_full("full_backstop")],
        ]


class TwoSchedulerConflict(Scenario):
    name = "two_scheduler_conflict"
    describe = (
        "two federated schedulers snapshot the same store version and "
        "race gang dispatches onto ONE node: whichever dispatch lands "
        "second must lose its optimistic check (stale_node), refresh "
        "its snapshot version and win the retry — every schedule ends "
        "with both gangs bound exactly once, zero journal orphans on "
        "either journal, identical placements, no in-place mutations"
    )

    # every step contends on the store lock, so nothing prunes: all six
    # interleavings of {snap,bind} x {A,B} run
    L_CACHE_A = "cache_a._mutex"
    L_CACHE_B = "cache_b._mutex"
    L_JOURNAL_A = "journal_a._lock"
    L_JOURNAL_B = "journal_b._lock"

    def build(self) -> None:
        from kube_batch_tpu.cache import ClusterStore
        from kube_batch_tpu.cache.store import PODS, EventHandler
        from kube_batch_tpu.faults.mutation_detector import MutationDetector
        from kube_batch_tpu.federation import FederatedCache
        from kube_batch_tpu.recovery import WriteIntentJournal
        from kube_batch_tpu.utils.locking import LockOrderWitness

        self.store = ClusterStore()
        self._seed(self.store, nodes=1)  # one node: every dispatch collides
        self.bind_counts: dict = {}

        def on_update(old, new):
            if not old.node_name and new.node_name:
                key = f"{new.namespace}/{new.name}"
                self.bind_counts[key] = self.bind_counts.get(key, 0) + 1

        self.store.add_event_handler(PODS, EventHandler(on_update=on_update))
        self._arrive(self.store, "ga", 3)
        self._arrive(self.store, "gb", 3)
        self.journal = WriteIntentJournal(os.path.join(self.workdir, "a.wal"))
        self.standby_journal = WriteIntentJournal(
            os.path.join(self.workdir, "b.wal")
        )
        # gang-keyed shards chosen so ga -> A, gb -> B deterministically
        # (crc32 is stable); no writer pools: each bind step IS its
        # conditional store transaction, retries included
        self.cache_a = self._shard_cache_for("ga", self.journal)
        self.cache_b = self._shard_cache_for("gb", self.standby_journal)
        self.detector = MutationDetector(self.store)
        self.detector.snapshot()

        self.witness = LockOrderWitness()
        self.store._lock = self.witness.wrap(L_STORE, self.store._lock)
        self.cache_a._mutex = self.witness.wrap(self.L_CACHE_A, self.cache_a._mutex)
        self.cache_b._mutex = self.witness.wrap(self.L_CACHE_B, self.cache_b._mutex)
        self.journal._lock = self.witness.wrap(self.L_JOURNAL_A, self.journal._lock)
        self.standby_journal._lock = self.witness.wrap(
            self.L_JOURNAL_B, self.standby_journal._lock
        )

        f_snap_a = frozenset({L_STORE, self.L_CACHE_A})
        f_snap_b = frozenset({L_STORE, self.L_CACHE_B})
        # a dispatch touches everything: its own mutex + journal, the
        # store, AND the peer cache (the commit's update events fan out
        # to the peer's informer handlers synchronously)
        f_bind_a = frozenset(
            {L_STORE, self.L_CACHE_A, self.L_CACHE_B, self.L_JOURNAL_A}
        )
        f_bind_b = frozenset(
            {L_STORE, self.L_CACHE_A, self.L_CACHE_B, self.L_JOURNAL_B}
        )
        self.threads = [
            [
                Step("snapshot_a", lambda: self.cache_a.snapshot(), f_snap_a),
                Step("dispatch_a", lambda: self._bind_gang(self.cache_a, "ga"), f_bind_a),
            ],
            [
                Step("snapshot_b", lambda: self.cache_b.snapshot(), f_snap_b),
                Step("dispatch_b", lambda: self._bind_gang(self.cache_b, "gb"), f_bind_b),
            ],
        ]

    def _shard_cache_for(self, gang: str, journal):
        """A FederatedCache whose shard is whichever bucket ``gang``
        hashes into (2 shards, gang key) — the scenario stays valid if
        crc32's bucket assignment ever changes."""
        from kube_batch_tpu.federation import FederatedCache, shard_index
        from kube_batch_tpu.api.job_info import job_key

        shard = shard_index(job_key("default", gang), 2)
        return FederatedCache(
            self.store, shard=shard, shards=2, shard_key="gang", journal=journal
        )

    @staticmethod
    def _bind_gang(cache, gang: str) -> None:
        from kube_batch_tpu.api.job_info import job_key
        from kube_batch_tpu.api.types import TaskStatus

        uid = job_key("default", gang)
        with cache._mutex:
            job = cache.jobs.get(uid)
            pending = (
                list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
                if job is not None
                else []
            )
        if not pending:
            raise RuntimeError(f"model error: gang {gang} has no pending tasks")
        cache.bind_many([(t, "n0") for t in pending])

    def invariants(self) -> list:
        out = super().invariants()
        from kube_batch_tpu.recovery import WriteIntentJournal

        orphans = WriteIntentJournal.replay(self.standby_journal.path).orphans
        if orphans:
            out.append(
                "scheduler B's journal left with unconfirmed intents: "
                + ", ".join(f"{i.op} {i.pod} seq={i.seq}" for i in orphans)
            )
        mutated = self.detector.violations()
        if mutated:
            out.append(f"in-place mutation of store objects: {mutated}")
        from kube_batch_tpu.federation import fsck

        out.extend(fsck(self.store))
        return out


class AdoptVsDispatch(Scenario):
    name = "adopt_vs_dispatch"
    describe = (
        "slot adoption (ISSUE 16) racing a straggler dispatch from the "
        "killed owner: the dead shard's in-flight conditional gang "
        "transaction lands late — against the survivor's takeover "
        "reconciliation and its post-adoption full cycle. Whichever "
        "lands first, the optimistic check arbitrates: the straggler "
        "either wins (reconciliation confirms the landed binds) or "
        "loses StaleWrite (reconciliation already re-dispatched). Every "
        "schedule must end with both gangs bound exactly once on the "
        "journaled placements, both journals orphan-free, the slot "
        "owned by the survivor, and fsck clean"
    )

    L_CACHE_V = "cache_victim._mutex"
    L_CACHE_S = "cache_survivor._mutex"
    L_JOURNAL_V = "journal_victim._lock"
    L_JOURNAL_S = "journal_survivor._lock"

    def build(self) -> None:
        from kube_batch_tpu.api.job_info import job_key
        from kube_batch_tpu.cache import ClusterStore
        from kube_batch_tpu.cache.store import PODS, EventHandler
        from kube_batch_tpu.federation import (
            FederatedCache,
            ShardSlotManager,
            shard_index,
            shard_journal_path,
            slot_lease_name,
        )
        from kube_batch_tpu.recovery import WriteIntentJournal
        from kube_batch_tpu.utils.locking import LockOrderWitness

        self.store = ClusterStore()
        self._seed(self.store, nodes=1)  # one node: parity is trivial
        self.bind_counts: dict = {}

        def on_update(old, new):
            if not old.node_name and new.node_name:
                key = f"{new.namespace}/{new.name}"
                self.bind_counts[key] = self.bind_counts.get(key, 0) + 1

        self.store.add_event_handler(PODS, EventHandler(on_update=on_update))

        # gang "ga" belongs to the victim's slot; pick the survivor's
        # own gang so it provably hashes into the OTHER slot (the
        # scenario stays valid if crc32's bucket assignment changes)
        self.victim_slot = shard_index(job_key("default", "ga"), 2)
        self.survivor_slot = 1 - self.victim_slot
        survivor_gang = next(
            g for g in ("gb", "gc", "gd", "ge", "gf")
            if shard_index(job_key("default", g), 2) == self.survivor_slot
        )
        self._arrive(self.store, "ga", 3)
        self._arrive(self.store, survivor_gang, 3)
        self.survivor_gang = survivor_gang

        # journals live where adoption's takeover reconciliation looks:
        # shard-{slot}.wal under the shared journal dir
        self.journal = WriteIntentJournal(
            shard_journal_path(self.workdir, self.victim_slot)
        )
        self.standby_journal = WriteIntentJournal(
            shard_journal_path(self.workdir, self.survivor_slot)
        )
        victim = FederatedCache(
            self.store, shard=self.victim_slot, shards=2, shard_key="gang",
            journal=self.journal, binder=_CondDyingBinder(self.store),
        )
        self.cache_survivor = FederatedCache(
            self.store, shard=self.survivor_slot, shards=2, shard_key="gang",
            journal=self.standby_journal,
        )
        self.cache_victim = victim

        # the kill, pre-schedule and deterministic: the victim journals
        # its gang's intents, then its conditional transaction dies
        # mid-flight (BaseException through the optimistic-bind path)
        victim.snapshot()
        self.stale_version = victim._snapshot_version
        try:
            _bind_gang_pending(victim, "ga")
        except _DyingBinder.LeaderKilled:
            pass
        else:
            raise RuntimeError("model error: conditional DyingBinder never fired")
        replay = WriteIntentJournal.replay(self.journal.path)
        if len(replay.orphans) != 3:
            raise RuntimeError(
                "model error: kill left "
                f"{len(replay.orphans)} in-flight intent(s), wanted 3"
            )
        self.bindings = [
            (*intent.pod.partition("/")[::2], intent.node)
            for intent in sorted(replay.orphans, key=lambda i: i.seq)
        ]

        # the survivor: owns its slot (lease + manager state); the
        # victim's slot lease is NOT created — an expired/never-renewed
        # lease and a missing one take the same adoption path
        self.mgr = ShardSlotManager(
            self.store, self.cache_survivor, identity="survivor",
            lease_s=1000.0, renew_s=100.0, adopt=True,
            journal_dir=self.workdir, grace_s=0.0, rebalance=0,
        )
        self.store.try_acquire_lease(
            slot_lease_name(self.survivor_slot), "survivor", 1000.0
        )
        self.mgr._set_owned({self.survivor_slot})

        self.witness = LockOrderWitness()
        self.store._lock = self.witness.wrap(L_STORE, self.store._lock)
        self.cache_victim._mutex = self.witness.wrap(
            self.L_CACHE_V, self.cache_victim._mutex
        )
        self.cache_survivor._mutex = self.witness.wrap(
            self.L_CACHE_S, self.cache_survivor._mutex
        )
        self.journal._lock = self.witness.wrap(self.L_JOURNAL_V, self.journal._lock)
        self.standby_journal._lock = self.witness.wrap(
            self.L_JOURNAL_S, self.standby_journal._lock
        )

        def straggler():
            # the dead owner's write was already on the wire: the SAME
            # conditional transaction, carrying the snapshot version it
            # captured before dying — the optimistic check decides
            from kube_batch_tpu.cache.cache import StoreBinder
            from kube_batch_tpu.cache.store import StaleWrite

            try:
                StoreBinder(self.store).bind_many_versioned(
                    self.bindings, self.stale_version
                )
            except StaleWrite:
                pass  # reconciliation landed first; the dead owner lost

        def adopt():
            # the probe's winning half: take the orphaned slot's lease,
            # then the full takeover (reconcile the dead owner's journal,
            # widen the owned set, re-ingest the backlog)
            self.store.try_acquire_lease(
                slot_lease_name(self.victim_slot), "survivor", 1000.0
            )
            self.mgr._adopt(self.victim_slot, t0=self.clock.now())

        def survivor_full():
            from kube_batch_tpu.cache import SchedulerCache  # noqa: F401
            from kube_batch_tpu.scheduler import Scheduler

            conf = os.path.join(self.workdir, "survivor.yaml")
            with open(conf, "w", encoding="utf-8") as fh:
                fh.write(_CONF)
            Scheduler(self.cache_survivor, scheduler_conf=conf).run_once()

        # every step can reach the store, both caches (commit events fan
        # out to both mirrors synchronously) and both journals — nothing
        # prunes, all three interleavings run
        f_all = frozenset({
            L_STORE, self.L_CACHE_V, self.L_CACHE_S,
            self.L_JOURNAL_V, self.L_JOURNAL_S,
        })
        self.threads = [
            [Step("straggler_dispatch_lands", straggler, f_all)],
            [
                Step("adopt_slot_takeover", adopt, f_all),
                Step("survivor_full_cycle", survivor_full, f_all),
            ],
        ]

    def invariants(self) -> list:
        out = super().invariants()
        from kube_batch_tpu.federation import fsck
        from kube_batch_tpu.recovery import WriteIntentJournal

        orphans = WriteIntentJournal.replay(self.standby_journal.path).orphans
        if orphans:
            out.append(
                "survivor's journal left with unconfirmed intents: "
                + ", ".join(f"{i.op} {i.pod} seq={i.seq}" for i in orphans)
            )
        if self.victim_slot not in self.cache_survivor.owned_slots:
            out.append(
                f"survivor never adopted slot {self.victim_slot} "
                f"(owned: {sorted(self.cache_survivor.owned_slots)})"
            )
        placed = self.placements()
        moved = {
            f"{ns}/{name}": (placed.get(f"{ns}/{name}"), node)
            for ns, name, node in self.bindings
            if placed.get(f"{ns}/{name}") != node
        }
        if moved:
            out.append(
                "killed owner's gang diverged from its journaled "
                f"placement (got, want): {moved}"
            )
        out.extend(fsck(self.store, shard_key="gang"))
        return out


class _CondDyingBinder:
    """Conditional-path SIGKILL stand-in: the first optimistic gang
    transaction dies mid-flight (after the intents are journaled,
    before anything lands) — the adopt_vs_dispatch premise."""

    def __init__(self, store) -> None:
        from kube_batch_tpu.cache.cache import StoreBinder

        self._inner = StoreBinder(store)

    def bind_many_versioned(self, bindings, snapshot_version):
        raise _DyingBinder.LeaderKilled()

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def _bind_gang_pending(cache, gang: str) -> None:
    """Dispatch every pending task of ``gang`` through the cache's bulk
    (conditional) bind path — shared by the two federated scenarios."""
    from kube_batch_tpu.api.job_info import job_key
    from kube_batch_tpu.api.types import TaskStatus

    uid = job_key("default", gang)
    with cache._mutex:
        job = cache.jobs.get(uid)
        pending = (
            list(job.task_status_index.get(TaskStatus.PENDING, {}).values())
            if job is not None
            else []
        )
    if not pending:
        raise RuntimeError(f"model error: gang {gang} has no pending tasks")
    cache.bind_many([(t, "n0") for t in pending])


# -- the intentionally broken fixture ----------------------------------------


def _lossy_trigger():
    """``drain()`` empties the backlog instead of copy-until-prune —
    the bug class StreamTrigger.drain's docstring warns about. Exists
    only so the explorer demonstrably finds and replays a
    counterexample (trace ``broken_drain:011``)."""
    from kube_batch_tpu.streaming import StreamTrigger

    class Lossy(StreamTrigger):
        def drain(self):
            work = super().drain()
            with self._lock:
                self._gangs.clear()  # the bug
            return work

    return Lossy()


class BrokenDrain(Scenario):
    name = "broken_drain"
    describe = (
        "FIXTURE (intentionally broken): a lossy drain() races a "
        "staleness mark; the schedule where the stale drain precedes "
        "the serving drain loses the gang from the backlog with no "
        "full cycle left to save it"
    )
    parity = False  # schedules legitimately differ (no backstop)

    @staticmethod
    def _make_trigger():
        return _lossy_trigger()

    def build(self) -> None:
        self._wire(nodes=4)
        self.sched.run_once()
        self._arrive(self.store, "g1", 3)
        self.threads = [
            [
                Step(
                    "mark_stale",
                    lambda: self.trigger._mark_stale("watch ring overflow"),
                    F_TRIG,
                )
            ],
            [self.s_micro("drain_micro_1"), self.s_micro("drain_micro_2")],
        ]

    def invariants(self) -> list:
        # binding everything is NOT required here (no backstop full
        # cycle by construction); what is required is that nothing
        # pending vanished from the backlog
        from kube_batch_tpu.streaming import gang_key_of
        from kube_batch_tpu.cache.store import PODS

        out = []
        pending_gangs = {
            gang_key_of(p) for p in self.store.list(PODS) if not p.node_name
        }
        with self.trigger._lock:
            backlog = set(self.trigger._gangs)
        lost = sorted(pending_gangs - backlog)
        if lost:
            out.append(
                f"arrival lost: gang(s) {lost} are pending in the store "
                "but gone from the trigger backlog — no micro-cycle will "
                "ever serve them"
            )
        dupes = {k: n for k, n in self.bind_counts.items() if n > 1}
        if dupes:
            out.append(f"duplicate bind transitions: {dupes}")
        return out


# The pipelined-cycles scenario routes allocation through xla_allocate
# — the only action with a deferrable post-solve phase —
# with min_device_pairs 0 so the tiny model cluster cannot be rerouted
# to serial by the size floor (the same pin the parity suites use).
# With the writer pool off (the harness never calls cache.run()),
# submit_dispatch runs the deferred closure inline at submission, so
# the fence/deferred-tail protocol executes in full while the schedule
# stays the only source of nondeterminism.
_CONF_PIPELINE = _CONF.replace(
    'actions: "enqueue, allocate, backfill"',
    'actions: "enqueue, xla_allocate, backfill"\n'
    "actionArguments:\n"
    "  xla_allocate:\n"
    '    min_device_pairs: "0"',
)


class DispatchVsNextSolve(Scenario):
    name = "dispatch_vs_next_solve"
    describe = (
        "pipelined cycles (KBT_PIPELINE): cycle N's deferred dispatch "
        "racing cycle N+1's snapshot through the dispatch fence, with "
        "a gang arrival + micro drain in flight — every schedule must "
        "bind both gangs exactly once, identically, and leave the "
        "fence clean"
    )

    def build(self) -> None:
        from kube_batch_tpu import pipeline

        self._saved_pipeline_env = os.environ.get(pipeline.ENV)
        os.environ[pipeline.ENV] = "1"
        pipeline.reset()
        # One node (it fits both gangs): whichever cycle binds first,
        # every pod lands on n0, so bind-for-bind parity holds across
        # schedules even though g1/g2 bind order varies.
        self._wire(nodes=1, conf_text=_CONF_PIPELINE)
        self.sched.run_once()  # adopt the resident table
        self._arrive(self.store, "g1", 3)  # cycle N has binds to defer
        # Prune g1 from the trigger backlog (drain() alone copies
        # without removing): the racing micro-cycle can then only ever
        # serve g2, so every schedule has at least one full cycle with
        # work to defer — without this, a micro-first schedule drains
        # everything and the fence protocol never runs.
        self.trigger.prune({"default/g1"})
        self.threads = [
            [self.s_full("full_cycle_n"), self.s_full("full_cycle_n1")],
            [self.s_arrive("g2", 3), self.s_micro()],
        ]

    def invariants(self) -> list:
        from kube_batch_tpu import pipeline

        out = super().invariants()
        if pipeline.fence.degraded_reason is not None:
            out.append(
                "pipeline degraded to synchronous during a clean "
                f"schedule: {pipeline.fence.degraded_reason}"
            )
        if pipeline.fence.pending():
            out.append(
                "dispatch fence left armed after every cycle completed "
                "— a deferred dispatch was never joined"
            )
        if pipeline.fence._dispatch_s <= 0.0:
            out.append(
                "model error: no cycle recorded a deferred dispatch — "
                "the pipelined path never engaged (serial reroute?) and "
                "the scenario checked nothing"
            )
        return out

    def cleanup(self) -> None:
        from kube_batch_tpu import pipeline

        pipeline.reset()
        if self._saved_pipeline_env is None:
            os.environ.pop(pipeline.ENV, None)
        else:
            os.environ[pipeline.ENV] = self._saved_pipeline_env
        super().cleanup()


# The admission gate has its own RLock the witness does not wrap; the
# virtual token serializes gate-touching steps in the commutation check
# the same way STATE does for the lockless resident table.
GATE = "admission_gate"
F_GATE = frozenset({GATE})


class AdmissionStorm(Scenario):
    name = "admission_storm"
    describe = (
        "front-door admission (decide + lane charge) racing brownout "
        "escalation ticks, a closed-lane shed, and the micro/full "
        "dispatch whose bind echoes credit the lanes back: every "
        "schedule must bind each admitted pod exactly once, never "
        "admit through the closed lane, land on the same brownout "
        "level, and leave zero inflight once all echoes are in"
    )

    def build(self) -> None:
        from kube_batch_tpu import admission
        from kube_batch_tpu.cache.store import PODS, EventHandler

        self._wire(nodes=4)
        self.sched.run_once()  # adopt the resident table
        # Two sustained over-SLO ticks (UP_TICKS) escalate the ladder
        # regardless of where they land in the schedule; the high lane
        # is brownout-protected and the low lane is rate-closed, so
        # every decide outcome is schedule-independent by construction.
        hot = {
            "enabled": True,
            "slo": {"time_to_bind": {"high": {"n": 50, "p99": 5.0}}},
            "backlog_pods": 0.0,
            "shard_up": {"http://s0": True},
            "node_conflict_topk": {},
        }
        self.gate = admission.AdmissionGate(
            [admission.LaneSpec("high", 100, rate=50.0, burst=50.0, backlog=120),
             admission.LaneSpec("low", 0, rate=1e-4, burst=1.0, backlog=120)],
            fleet_fn=lambda: hot, age_fn=lambda: 0.0,
            slo_s=1.0, interval_s=1000.0,
        )
        # the storm pre-state: the low lane burned its burst before this
        # window opens, and at 1e-4 tokens/s it cannot accrue a whole
        # token during the run — every schedule sheds it identically
        # (shed_rate before a tick lands, shed_brownout after)
        self.gate.lanes["low"].bucket._tokens = 0.0
        self.shed_decisions: list = []

        def on_update(old, new):
            # the server's wiring: a bind echo credits the lane backlog
            if not old.node_name and new.node_name:
                self.gate.note_done(f"{new.namespace}/{new.name}")

        self.store.add_event_handler(PODS, EventHandler(on_update=on_update))

        def admit_and_arrive():
            for m in range(2):
                d = self.gate.decide("high", f"default/g1-p{m}")
                if not d.admitted:
                    raise AssertionError(
                        f"protected high lane shed an arrival: {d.reason}"
                    )
            self._arrive(self.store, "g1", 2)

        def force_tick():
            # the step IS the tick: rewind the interval clock so
            # maybe_tick fires exactly here and nowhere else (decide's
            # own maybe_tick stays blocked by the 1000s interval)
            self.gate._last_tick = -1e9
            self.gate.maybe_tick()

        def shed_low():
            self.shed_decisions.append(self.gate.decide("low", "default/shed-0"))

        self.threads = [
            [
                Step("admit_arrive_high", admit_and_arrive, F_EVENT | F_GATE),
                Step("micro_drain",
                     lambda: self.sched.run_micro(self.trigger.drain()),
                     F_ALL | F_GATE),
            ],
            [
                Step("pressure_tick_1", force_tick, F_GATE),
                Step("pressure_tick_2", force_tick, F_GATE),
            ],
            [
                Step("shed_low", shed_low, F_GATE),
                Step("full_backstop", self.sched.run_once, F_ALL | F_GATE),
            ],
        ]

    def fingerprint(self):
        # placements + the settled controller level: schedules must
        # agree on both (two hot ticks always escalate exactly once)
        return (tuple(sorted(self.placements().items())),
                self.gate.controller.level)

    def invariants(self) -> list:
        out = super().invariants()
        lanes = self.gate.lanes
        if lanes["low"].admitted != 0:
            out.append(
                f"closed low lane admitted {lanes['low'].admitted} pods"
            )
        if lanes["high"].admitted != 2:
            out.append(
                f"high lane admitted {lanes['high'].admitted} pods, want 2"
            )
        for d in self.shed_decisions:
            if d.admitted or not d.reason.startswith("shed_"):
                out.append(f"closed-lane decide leaked through: {d}")
            if d.retry_after_s <= 0:
                out.append(f"shed without Retry-After guidance: {d}")
        inflight = sum(l.inflight for l in lanes.values())
        if inflight != 0:
            out.append(
                f"{inflight} admitted pods never credited back — a bind "
                "echo was lost or double-charged"
            )
        if self.gate.controller.level < 1:
            out.append(
                "two sustained over-SLO ticks never escalated the "
                "brownout ladder — the overload response is inert"
            )
        return out


class UnderdeclaredState(Scenario):
    name = "underdeclared_state"
    describe = (
        "FIXTURE (intentionally broken): a step reads the lockless "
        "resident table (StreamState.valid) without declaring the "
        "STATE token in its footprint — the field-level RaceWitness "
        "upgrades the under-declaration into a KBT-I002 model error "
        "that pure lock-acquire observation could never see"
    )
    parity = False  # the seeded violation aborts fingerprinting

    def build(self) -> None:
        self._wire(nodes=2)
        self.threads = [
            # footprint claims trigger-lock only; the body touches the
            # watched resident table -> observed {STATE} ⊄ F_TRIG
            [Step("peek_state", lambda: self.state.valid, F_TRIG)],
        ]


SCENARIOS = {
    c.name: c
    for c in (
        MicroVsFull,
        EventVsInvalidate,
        TakeoverVsDispatch,
        Watch410VsDrain,
        TwoSchedulerConflict,
        DispatchVsNextSolve,
        AdoptVsDispatch,
        AdmissionStorm,
    )
}
FIXTURES = {
    BrokenDrain.name: BrokenDrain,
    UnderdeclaredState.name: UnderdeclaredState,
}


# -- explorer -----------------------------------------------------------------


def _run_schedule(scn_cls, root: str, order, trace: str, verbose: bool = False) -> ScheduleResult:
    from kube_batch_tpu import faults

    faults.registry.reset()
    faults.solver_ladder.reset()
    scn = scn_cls(tempfile.mkdtemp(prefix="run-", dir=root))
    result = ScheduleResult(trace=trace, steps=[], violations=[])
    try:
        try:
            scn.build()
        except Exception as e:  # noqa: BLE001 - a broken builder is a finding
            result.violations.append(
                f"model error: scenario build raised {type(e).__name__}: {e}"
            )
            return result
        observed: dict = {}
        cursor = {"i": -1}

        def on_acquire(name: str) -> None:
            if cursor["i"] >= 0:
                observed.setdefault(cursor["i"], set()).add(name)

        scn.witness.on_acquire = on_acquire
        race = getattr(scn, "race", None)
        if race is not None:
            # field-level: actual watched-state accesses (STATE et al.)
            # feed the same observed-vs-footprint check as lock acquires
            race.on_access = on_acquire
        pos = [0] * len(scn.threads)
        for i, tid in enumerate(order):
            step = scn.threads[tid][pos[tid]]
            pos[tid] += 1
            cursor["i"] = i
            t = scn.clock.advance(1.0)
            try:
                step.fn()
            except Exception as e:  # noqa: BLE001 - a raising step is a finding
                result.violations.append(
                    f"step {step.name} raised {type(e).__name__}: {e}"
                )
                break
            finally:
                cursor["i"] = -1
            result.steps.append((t, tid, step.name))
            if verbose:
                print(f"  t={t:>4.0f}  T{tid}  {step.name}")
            extra = sorted(observed.get(i, set()) - step.footprint)
            if extra:
                result.violations.append(
                    f"model error: step {step.name} acquired undeclared "
                    f"lock(s)/state token(s) {extra} — footprint "
                    "under-declared, DPOR pruning would be unsound"
                )
        result.violations.extend(scn.witness.violations)
        result.violations.extend(scn.invariants())
        if not result.violations:
            result.fingerprint = scn.fingerprint()
    finally:
        scn.cleanup()
    return result


def explore(name: str, root: Optional[str] = None, verbose: bool = False) -> ScenarioReport:
    """Drive one scenario through every canonical schedule."""
    scn_cls = SCENARIOS.get(name) or FIXTURES.get(name)
    if scn_cls is None:
        raise SystemExit(
            f"unknown scenario {name!r} (have: "
            f"{', '.join([*SCENARIOS, *FIXTURES])})"
        )
    own_root = root is None
    root = root or tempfile.mkdtemp(prefix="kbt-interleave-")
    try:
        plan_scn = scn_cls(tempfile.mkdtemp(prefix="plan-", dir=root))
        try:
            try:
                plan_scn.build()
            except Exception as e:  # noqa: BLE001 - broken builder = finding
                return ScenarioReport(
                    name=scn_cls.name, describe=scn_cls.describe,
                    results=[
                        ScheduleResult(
                            trace=f"{scn_cls.name}:build", steps=[],
                            violations=[
                                "model error: scenario build raised "
                                f"{type(e).__name__}: {e}"
                            ],
                        )
                    ],
                )
            plan = plan_scn.threads
            orders, pruned = _schedules(plan)
        finally:
            plan_scn.cleanup()
        report = ScenarioReport(
            name=scn_cls.name, describe=scn_cls.describe,
            schedules=len(orders), pruned_branches=pruned,
        )
        for order in orders:
            trace = f"{scn_cls.name}:{''.join(str(t) for t in order)}"
            report.results.append(
                _run_schedule(scn_cls, root, order, trace, verbose=verbose)
            )
        if scn_cls.parity:
            clean = [r for r in report.results if not r.violations]
            fps = {json.dumps(r.fingerprint, sort_keys=True) for r in clean}
            if len(fps) > 1:
                samples = sorted(
                    (json.dumps(r.fingerprint, sort_keys=True), r.trace) for r in clean
                )
                report.results.append(
                    ScheduleResult(
                        trace=f"{scn_cls.name}:parity",
                        steps=[],
                        violations=[
                            "bind-for-bind parity broken across schedules: "
                            f"{samples[0][1]} and {samples[-1][1]} disagree "
                            f"on placements"
                        ],
                    )
                )
        return report
    finally:
        if own_root:
            shutil.rmtree(root, ignore_errors=True)


def replay(trace: str) -> ScheduleResult:
    """Re-run one schedule by its trace id, verbosely."""
    name, _, digits = trace.partition(":")
    scn_cls = SCENARIOS.get(name) or FIXTURES.get(name)
    if scn_cls is None or not digits or not digits.isdigit():
        raise SystemExit(f"unknown trace {trace!r} (want <scenario>:<tid digits>)")
    order = tuple(int(d) for d in digits)
    root = tempfile.mkdtemp(prefix="kbt-replay-")
    try:
        print(f"replaying {trace}:")
        return _run_schedule(scn_cls, root, order, trace, verbose=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


# -- CLI ----------------------------------------------------------------------


def main(argv: Optional[list] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    strict = "--strict" in argv
    as_json = "--json" in argv
    do_list = "--list" in argv
    only = None
    trace = None
    if "--scenario" in argv:
        only = argv[argv.index("--scenario") + 1]
    if "--replay" in argv:
        trace = argv[argv.index("--replay") + 1]
    known = {"--strict", "--json", "--list", "--scenario", "--replay"}
    unknown = [
        a for a in argv
        if a.startswith("--") and a not in known
    ]
    if unknown:
        print(f"unknown option(s): {unknown}", file=sys.stderr)
        print(__doc__.split("\n\n")[0], file=sys.stderr)
        return 2

    if do_list:
        for pool, tag in ((SCENARIOS, ""), (FIXTURES, "  [fixture]")):
            for name, cls in pool.items():
                print(f"{name}{tag}: {cls.describe}")
        return 0

    if trace is not None:
        r = replay(trace)
        for v in r.violations:
            print(f"  VIOLATION: {v}")
        print(f"replay {trace}: {'FAIL' if r.violations else 'clean'}")
        return 1 if r.violations else 0

    t0 = time.perf_counter()
    names = [only] if only else list(SCENARIOS)
    reports = [explore(n) for n in names]
    findings = [f for rep in reports for f in rep.findings()]

    repo = repo_root()
    bl = load_baseline(os.path.join(repo, BASELINE), repo)
    kept, suppressed, stale = apply_baseline(findings, bl)
    kept.extend(bl.errors)
    if strict:
        kept.extend(stale)

    if as_json:
        print(
            json.dumps(
                {
                    "scenarios": [
                        {
                            "name": rep.name,
                            "schedules": rep.schedules,
                            "pruned_branches": rep.pruned_branches,
                            "counterexamples": [
                                {"trace": r.trace, "violations": r.violations}
                                for r in rep.counterexamples
                            ],
                        }
                        for rep in reports
                    ],
                    "findings": [f.render() for f in kept],
                    "suppressed": len(suppressed),
                    "elapsed_s": round(time.perf_counter() - t0, 2),
                },
                indent=2,
            )
        )
    else:
        for rep in reports:
            status = (
                "clean" if not rep.counterexamples
                else f"{len(rep.counterexamples)} counterexample(s)"
            )
            print(
                f"interleave: {rep.name}: {rep.schedules} schedule(s), "
                f"{rep.pruned_branches} branch(es) pruned, {status}"
            )
        for f in kept:
            print(f.render())
        print(
            f"interleave: {sum(r.schedules for r in reports)} schedule(s) "
            f"across {len(reports)} scenario(s), {len(kept)} finding(s), "
            f"{len(suppressed)} suppressed, "
            f"{time.perf_counter() - t0:.1f}s"
        )
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
