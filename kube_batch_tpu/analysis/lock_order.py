"""A5 — interprocedural lock-order analyzer (KBT-D001/D002).

Built on A1's lock universe (the guarded-by seed map, ``#: guarded_by``
annotations, and any ``threading.Lock/RLock/Condition`` assigned in a
class): every lock gets a node ``Class._attr`` in a static
**acquisition graph**, with an edge ``A -> B`` wherever code acquires
``B`` while lexically (or, through the call summaries below,
transitively) holding ``A``.

Interprocedural model, deliberately shallow but cross-file:

- per-method **summaries** — the set of lock nodes a method acquires
  and the blocking calls it makes — computed to fixpoint over
  ``self.method()`` calls within a class;
- **collaborator edges** across classes: ``self.<attr>.method()``
  follows the attribute to its class when the attribute is either
  assigned a known class's constructor in this file
  (``self.journal = WriteIntentJournal(...)``) or listed in the
  injected-dependency seed map below (``SchedulerCache._store`` is a
  ``ClusterStore``). The callee's summary locks/blocking calls are
  charged to the held region at the call site.

Checks:

- **KBT-D001**: a cycle in the acquisition graph (ABBA and longer) —
  two code paths that interleave under load and deadlock. One finding
  per cycle, anchored at one participating acquisition site, with
  every edge's site in the message.
- **KBT-D002**: a blocking API reached while a lock is held —
  ``os.fsync``, ``time.sleep``, ``subprocess.*``, future
  ``.result()``, device syncs (``block_until_ready``,
  ``jax.device_get``), socket ``sendall``/``recv``.
  ``Condition.wait``/``wait_for`` on the *held* condition is exempt
  (it releases the lock while blocking); callbacks stashed for later
  execution are invisible, same as A1.

Dynamic dispatch (event handlers, plugin callbacks) is out of reach by
design — the runtime :class:`kube_batch_tpu.utils.locking.LockOrderWitness`
covers that half in the chaos suite.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional, Union

from kube_batch_tpu.analysis import Finding, SourceFile
from kube_batch_tpu.analysis.lock_discipline import (
    SEED_GUARDED,
    _annotated_guards,
    _class_locks,
)

_FuncDef = Union[ast.FunctionDef, ast.AsyncFunctionDef]

# Injected dependencies the constructor-call inference cannot see:
# (path, class, attr) -> collaborator class name (resolved globally —
# class names in the lock universe are unique).
SEED_COLLABORATORS: dict[tuple[str, str, str], str] = {
    ("kube_batch_tpu/cache/cache.py", "SchedulerCache", "_store"): "ClusterStore",
    ("kube_batch_tpu/cache/cache.py", "SchedulerCache", "journal"): "WriteIntentJournal",
    ("kube_batch_tpu/cache/cache.py", "StoreVolumeBinder", "_store"): "ClusterStore",
    ("kube_batch_tpu/server.py", "WatchHub", "journal"): "WriteIntentJournal",
}

# blocking call signatures: attribute-call names and (root, attr) pairs
_BLOCKING_METHODS = {
    "fsync": "os.fsync",
    "sleep": "time.sleep / blocking sleep",
    "result": "future .result() (blocks on the pool)",
    "block_until_ready": "device sync",
    "device_get": "device->host sync",
    "sendall": "socket send",
    "recv": "socket recv",
    "urlopen": "network fetch",
}
_SUBPROCESS_CALLS = {"run", "check_call", "check_output", "Popen", "call"}


@dataclass
class _Acq:
    """One acquisition site: lock node + where."""

    node: str
    path: str
    line: int
    where: str  # Class.method


@dataclass
class _Summary:
    acquires: dict[str, _Acq] = field(default_factory=dict)  # node -> first site
    blocking: dict[str, tuple[str, int, str]] = field(default_factory=dict)
    # blocking: api -> (path, line, where) of the first site


@dataclass
class _Class:
    path: str
    name: str
    node: ast.ClassDef
    locks: set[str]  # lock attr names owned by this class
    conds: set[str]  # the subset assigned threading.Condition
    collaborators: dict[str, str]  # attr -> class name
    methods: dict[str, _FuncDef] = field(default_factory=dict)
    summaries: dict[str, _Summary] = field(default_factory=dict)


def _lock_attrs_of(sf: SourceFile, cls: ast.ClassDef) -> tuple[set[str], set[str]]:
    """(all lock attrs, condition attrs) for a class: ctor-assigned locks
    plus locks named by the seed map / annotations (guard values)."""
    locks: set[str] = set(_class_locks(cls))
    conds: set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name == "Condition":
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        conds.add(t.attr)
    seed = SEED_GUARDED.get(sf.path, {}).get(cls.name, {})
    locks.update(seed.values())
    locks.update(_annotated_guards(sf).get(cls.name, {}).values())
    return locks, conds


def _collaborators_of(sf: SourceFile, cls: ast.ClassDef, known: set[str]) -> dict[str, str]:
    """attr -> collaborator class: `self.attr = KnownClass(...)`
    assignments plus the injected-dependency seed map."""
    out: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            fn = node.value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name in known:
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        out[t.attr] = name
    for (path, cname, attr), target in SEED_COLLABORATORS.items():
        if path == sf.path and cname == cls.name and target in known:
            out[attr] = target
    return out


def _self_attr(node: ast.expr) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodWalker(ast.NodeVisitor):
    """One pass over a method body: records acquisitions, edges under
    the current held set, blocking calls, and self/collaborator calls
    (charged from the callee's current summary — the caller loops to
    fixpoint)."""

    def __init__(
        self,
        sf: SourceFile,
        cls: _Class,
        method: str,
        classes_by_name: dict[str, _Class],
        edges: dict[tuple[str, str], _Acq],
        blocking_sites: list[Finding],
        summary: _Summary,
    ) -> None:
        self.sf = sf
        self.cls = cls
        self.method = method
        self.by_name = classes_by_name
        self.edges = edges
        self.blocking_sites = blocking_sites
        self.summary = summary
        self.held: list[str] = []  # lock nodes, outermost first
        self.held_attrs: list[str] = []  # the self.<attr> spelling of each
        self._root = True
        self._reported: set[tuple] = set()

    # -- helpers -------------------------------------------------------------

    def _noqa(self, lineno: int) -> bool:
        lines = self.sf.lines
        return 0 < lineno <= len(lines) and "noqa" in lines[lineno - 1]

    def _where(self) -> str:
        return f"{self.cls.name}.{self.method}"

    def _record_acquire(self, node_name: str, lineno: int) -> None:
        acq = _Acq(node_name, self.sf.path, lineno, self._where())
        self.summary.acquires.setdefault(node_name, acq)
        for held in self.held:
            if held != node_name:
                self.edges.setdefault((held, node_name), acq)

    def _record_blocking(self, api: str, desc: str, lineno: int) -> None:
        self.summary.blocking.setdefault(api, (self.sf.path, lineno, self._where()))
        if self.held and not self._noqa(lineno):
            key = ("D002", lineno, api)
            if key in self._reported:
                return
            self._reported.add(key)
            self.blocking_sites.append(
                Finding(
                    self.sf.path,
                    lineno,
                    "KBT-D002",
                    f"{desc} while holding {self.held[-1]} in "
                    f"{self._where()} — every thread needing the lock "
                    "stalls for the blocking latency (move it outside "
                    "the critical section, or baseline with the "
                    "ordering argument)",
                    symbol=f"{self._where()}.{api}",
                )
            )

    def _charge_summary(self, callee: _Summary, lineno: int) -> None:
        """A call whose callee acquires locks / blocks: edges from every
        held lock, and blocking propagated to this summary (reported
        here if held)."""
        for node_name, acq in callee.acquires.items():
            self.summary.acquires.setdefault(node_name, acq)
            for held in self.held:
                if held != node_name:
                    self.edges.setdefault(
                        (held, node_name),
                        _Acq(node_name, self.sf.path, lineno, self._where()),
                    )
        for api, (bpath, bline, bwhere) in callee.blocking.items():
            self.summary.blocking.setdefault(api, (bpath, bline, bwhere))
            if self.held and not self._noqa(lineno):
                key = ("D002", lineno, api)
                if key not in self._reported:
                    self._reported.add(key)
                    self.blocking_sites.append(
                        Finding(
                            self.sf.path,
                            lineno,
                            "KBT-D002",
                            f"call into {bwhere} ({api}: see "
                            f"{bpath}:{bline}) while holding "
                            f"{self.held[-1]} in {self._where()} — the "
                            "blocking call runs inside this critical "
                            "section",
                            symbol=f"{self._where()}.{api}",
                        )
                    )

    # -- traversal -----------------------------------------------------------

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self._root:
            self._root = False
            self.generic_visit(node)
        # nested defs: skip — stashed callbacks run on other threads

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            self.visit(item.context_expr)
        acquired: list[tuple[str, str]] = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.cls.locks:
                node_name = f"{self.cls.name}.{attr}"
                self._record_acquire(node_name, item.context_expr.lineno)
                acquired.append((node_name, attr))
        for node_name, attr in acquired:
            self.held.append(node_name)
            self.held_attrs.append(attr)
        for item in node.items:
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.held.pop()
            self.held_attrs.pop()

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            # self.method(...)
            recv_attr = _self_attr(fn.value)
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                callee = self.cls.summaries.get(fn.attr)
                if callee is not None:
                    self._charge_summary(callee, node.lineno)
            elif recv_attr is not None:
                # self.<attr>.method(...)
                if recv_attr in self.cls.locks and fn.attr in ("wait", "wait_for"):
                    # Condition.wait on the HELD condition releases it —
                    # exempt; on a lock not held it is just odd, and on a
                    # different held lock's condition it blocks for real.
                    if recv_attr not in self.held_attrs:
                        self._record_blocking(
                            f"{recv_attr}.wait",
                            f"Condition wait on self.{recv_attr} (not the "
                            "held lock — does not release it)",
                            node.lineno,
                        )
                else:
                    target = self.cls.collaborators.get(recv_attr)
                    if target is not None:
                        tcls = self.by_name.get(target)
                        callee = tcls.summaries.get(fn.attr) if tcls else None
                        if callee is not None:
                            self._charge_summary(callee, node.lineno)
                    self._check_blocking_attr(fn, node.lineno)
            else:
                self._check_blocking_attr(fn, node.lineno)
        self.generic_visit(node)

    def _check_blocking_attr(self, fn: ast.Attribute, lineno: int) -> None:
        root = fn.value
        while isinstance(root, ast.Attribute):
            root = root.value
        root_name = root.id if isinstance(root, ast.Name) else ""
        if fn.attr in _BLOCKING_METHODS:
            # jnp/np .sleep etc. don't exist; cheap root filter for recv
            # (queue.recv would still be blocking — keep it)
            self._record_blocking(
                f"{root_name + '.' if root_name else ''}{fn.attr}",
                f"blocking call {root_name + '.' if root_name else ''}"
                f"{fn.attr}() ({_BLOCKING_METHODS[fn.attr]})",
                lineno,
            )
        elif root_name == "subprocess" and fn.attr in _SUBPROCESS_CALLS:
            self._record_blocking(
                f"subprocess.{fn.attr}",
                f"subprocess.{fn.attr}() (blocks on the child)",
                lineno,
            )


def _collect_classes(files: list[SourceFile]) -> dict[str, _Class]:
    """The lock universe: every class owning at least one known lock."""
    out: dict[str, _Class] = {}
    for sf in files:
        for node in sf.tree.body if isinstance(sf.tree, ast.Module) else []:
            if not isinstance(node, ast.ClassDef):
                continue
            locks, conds = _lock_attrs_of(sf, node)
            if not locks:
                continue
            c = _Class(sf.path, node.name, node, locks, conds, {})
            for meth in node.body:
                if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    c.methods[meth.name] = meth
                    c.summaries[meth.name] = _Summary()
            out[node.name] = c
    for sf in files:
        for name, c in out.items():
            if c.path == sf.path:
                c.collaborators = _collaborators_of(sf, c.node, set(out))
    return out


def _cycles(edges: dict[tuple[str, str], _Acq]) -> list[list[str]]:
    """Elementary cycles via SCC + per-SCC DFS; small graphs only.
    Returns each cycle once as a node list rotated to its minimum."""
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # Tarjan SCC
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    stack: list[str] = []
    on: set[str] = set()
    sccs: list[set[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(graph[v]):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = set()
            while True:
                w = stack.pop()
                on.discard(w)
                scc.add(w)
                if w == v:
                    break
            sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    cycles: list[list[str]] = []
    seen: set[tuple[str, ...]] = set()
    for scc in sccs:
        if len(scc) < 2:
            continue
        # enumerate simple cycles within the SCC (tiny in practice)
        nodes = sorted(scc)

        def dfs(start: str, v: str, path: list[str]) -> None:
            for w in sorted(graph[v]):
                if w == start and len(path) >= 2:
                    i = path.index(min(path))
                    key = tuple(path[i:] + path[:i])
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(key))
                elif w in scc and w not in path and w > start:
                    dfs(start, w, path + [w])

        for n in nodes:
            dfs(n, n, [n])
    return cycles


def analyze(files: list[SourceFile]) -> list[Finding]:
    classes = _collect_classes(files)
    by_path = {sf.path: sf for sf in files}
    edges: dict[tuple[str, str], _Acq] = {}
    blocking: list[Finding] = []

    # fixpoint over summaries: edges/blocking are recomputed fresh each
    # round so call charging sees the latest callee summaries
    for _round in range(6):
        before = {
            (c.name, m): (frozenset(s.acquires), frozenset(s.blocking))
            for c in classes.values()
            for m, s in c.summaries.items()
        }
        edges = {}
        blocking = []
        for c in classes.values():
            sf = by_path.get(c.path)
            if sf is None:
                continue
            for mname, meth in c.methods.items():
                walker = _MethodWalker(
                    sf, c, mname, classes, edges, blocking, c.summaries[mname]
                )
                walker.visit(meth)
        after = {
            (c.name, m): (frozenset(s.acquires), frozenset(s.blocking))
            for c in classes.values()
            for m, s in c.summaries.items()
        }
        if before == after:
            break

    findings: list[Finding] = list(blocking)
    for cycle in _cycles(edges):
        pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
        sites = []
        for a, b in pairs:
            acq = edges.get((a, b))
            if acq is not None:
                sites.append(f"{a} -> {b} at {acq.path}:{acq.line} ({acq.where})")
        anchor = edges.get(pairs[0])
        findings.append(
            Finding(
                anchor.path if anchor else "kube_batch_tpu",
                anchor.line if anchor else 0,
                "KBT-D001",
                "lock-order cycle: " + "; ".join(sites)
                + " — pick one global order and re-nest the inner "
                "acquisition",
                symbol="cycle:" + "<->".join(cycle),
            )
        )
    # stable order
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.symbol))
    return findings
