"""L7: process entry — flags, metrics HTTP server, leader election
(reference cmd/kube-batch/app/server.go:63-140 +
cmd/kube-batch/app/options/options.go:33-90).

``SchedulerServer`` assembles the full stack for one process: an
in-process ClusterStore (the API-server stand-in), the SchedulerCache,
the Scheduler loop on its own thread, and a ThreadingHTTPServer that
exposes:

- ``GET /metrics``   — Prometheus text exposition (promhttp.Handler
  equivalent; serves metrics.render_prometheus_text);
- ``GET /healthz``   — liveness;
- ``GET /version``   — version.info();
- ``GET /debug/trace`` — flight-recorder ring (recent cycle traces;
  ``?dump=1`` also writes the JSONL + Chrome trace files);
- ``GET /debug/slo`` — per-queue time-to-bind / queue-wait quantiles
  (kube_batch_tpu/obs SLO accountant);
- ``GET /debug/explain`` — per-gang unschedulability forensics records
  and cross-gang aggregate (kube_batch_tpu/obs/explain; ``?gang=ns/name``
  filters to one gang);
- ``GET|POST /apis/v1alpha1/queues`` and
  ``DELETE /apis/v1alpha1/queues/<name>`` — the queue CRD surface the
  reference CLI talks to (pkg/cli/queue);
- ``GET|POST /apis/v1alpha1/pods`` / ``nodes`` / ``podgroups`` /
  ``priorityclasses`` / ``poddisruptionbudgets`` / ``persistentvolumes`` /
  ``persistentvolumeclaims`` / ``storageclasses`` and the matching
  ``DELETE`` routes — the workload-ingestion surface an external control
  plane uses to feed the in-process cluster (the list/watch half the
  reference gets from the Kubernetes API server; here creations fan out
  to the cache's event handlers through the store). Pod ingestion also
  stands in for the k8s admission controller: a pod without an explicit
  priority gets it resolved from its named PriorityClass or the global
  default class, matching what kube-batch reads pre-resolved from
  pod.Spec.Priority upstream.

Pod JSON: ``{"name", "namespace", "group", "requests": {"cpu": 1,
"memory": "512Mi", ...scalars}, "priority", "priority_class_name",
"labels", "node_selector", "node_name", "phase", "scheduler_name"}``. Node JSON: ``{"name",
"allocatable": {...}, "labels"}``. PodGroup JSON: ``{"name",
"namespace", "queue", "min_member"}``.

HA: the reference elects a leader through a ConfigMap resource lock
(server.go:96-137). Two tiers here:

- single host (``--lock-file``): an OS file lock (``flock``) — exactly
  one scheduler process per lock file runs the loop; the kernel releases
  the lock if the holder dies and a blocked standby takes over;
- cluster-wide (``--lease-url``): a Lease object in a shared
  ClusterStore, renewed over the HTTP API with the reference's
  15 s lease / 10 s renew-deadline / 5 s retry semantics
  (``StoreLeaseElector``); any scheduler-API endpoint can arbitrate,
  arbitration runs atomically under the arbiter's clock, and a leader
  that cannot renew within the deadline exits fatally
  (OnStoppedLeading glog.Fatalf parity, server.go:133-135).
"""

from __future__ import annotations

import argparse
import fcntl
import json
import threading
import time
import urllib.parse
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kube_batch_tpu import faults, log, metrics, obs, version
from kube_batch_tpu.apis.types import ObjectMeta, Queue, QueueSpec
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.cache.store import KINDS, AlreadyExists, EventHandler, StaleWrite
from kube_batch_tpu.scheduler import Scheduler

DEFAULT_SCHEDULER_NAME = "kube-batch-tpu"
DEFAULT_SCHEDULE_PERIOD = 1.0
DEFAULT_QUEUE = "default"
DEFAULT_LISTEN_ADDRESS = ":8080"


# -- wire serialization (shared by the list and watch endpoints) ------------

SERIALIZERS = {
    "queues": lambda q: {"name": q.name, "weight": q.spec.weight},
    "pods": lambda p: {
        "namespace": p.namespace,
        "name": p.name,
        "phase": p.phase.value,
        "node": p.node_name,
    },
    "nodes": lambda n: {"name": n.name, "allocatable": dict(n.allocatable)},
    "podgroups": lambda g: {
        "namespace": g.metadata.namespace,
        "name": g.name,
        "queue": g.spec.queue,
        "min_member": g.spec.min_member,
        "phase": g.status.phase.value,
    },
    "priorityclasses": lambda pc: {
        "name": pc.name,
        "value": pc.value,
        "global_default": pc.global_default,
    },
    "poddisruptionbudgets": lambda b: {
        "namespace": b.metadata.namespace,
        "name": b.name,
        "min_available": b.min_available,
        "selector": b.selector,
    },
    "persistentvolumes": lambda v: {
        "name": v.name,
        "capacity": v.capacity_storage,
        "storage_class": v.storage_class_name,
        "phase": v.phase.value,
        "claim_ref": v.claim_ref,
    },
    "persistentvolumeclaims": lambda c: {
        "namespace": c.namespace,
        "name": c.name,
        "storage_class": c.storage_class_name,
        "request": c.request_storage,
        "phase": c.phase.value,
        "volume_name": c.volume_name,
    },
    "storageclasses": lambda s: {
        "name": s.name,
        "provisioner": s.provisioner,
        "volume_binding_mode": s.volume_binding_mode.value,
    },
    "leases": lambda l: {
        "name": l.name,
        "holder": l.holder_identity,
        "lease_duration": l.lease_duration_seconds,
        "acquire_time": l.acquire_time,
        "renew_time": l.renew_time,
        "transitions": l.lease_transitions,
    },
}


class WatchHub:
    """List+watch for external consumers (VERDICT r3 item 4): the store's
    event fan-out journaled with monotonic sequence numbers and exposed
    over HTTP long-poll (`GET /apis/v1alpha1/watch/<kind>?since=N`).

    The reference's clients get this from the generated
    SharedInformerFactory against the API server
    (pkg/client/informers/externalversions/factory.go); in-process, the
    hub subscribes one handler per kind and keeps a bounded ring of
    events **per kind**: one slow watcher of a churning kind can only
    ever hold MAX_EVENTS of that kind's events — it cannot grow the
    buffer without limit, and it cannot evict a quiet kind's events.
    `since` is the resourceVersion returned by list/watch replies; a
    client that falls behind its kind's ring gets `gone` and must
    re-list, exactly the k8s 410-Gone contract."""

    MAX_EVENTS = 8192  # ring capacity PER KIND

    def __init__(self, store: ClusterStore, max_events: Optional[int] = None) -> None:
        self._cond = threading.Condition()
        self.max_events = max_events or self.MAX_EVENTS
        # kind -> ring of (seq, verb, body), seq-ascending
        self._events: dict[str, deque] = {k: deque() for k in KINDS}
        self._seq = 0
        # Newest dropped seq per kind: Gone fires only when events of the
        # *requested* kind actually fell out of its ring, so a watcher of
        # a quiet kind is not forced to re-list because pods churned.
        self._dropped: dict[str, int] = {}
        self._closed = False
        # The journal is lazy: until the first list/watch consumer reads
        # a resourceVersion, events only bump the counter — no body
        # serialization, ring append, or notify on the store's hot
        # mutation path. `_journal_start` is the seq at activation;
        # a `since` before it is Gone (nothing earlier was journaled,
        # and no client can legitimately hold such an rv).
        self._active = False
        self._journal_start = 0
        for kind in KINDS:
            store.add_event_handler(
                kind,
                EventHandler(
                    on_add=lambda obj, k=kind: self._emit(k, "ADDED", obj),
                    on_update=lambda old, new, k=kind: self._emit(
                        k, "MODIFIED", new, old
                    ),
                    on_delete=lambda obj, k=kind: self._emit(k, "DELETED", obj),
                ),
            )

    def _emit(self, kind: str, verb: str, obj, old=None) -> None:
        if not self._active:
            # Double-checked under the lock; pre-activation events only
            # bump the counter (nobody is owed them).
            with self._cond:
                if not self._active:
                    self._seq += 1
                    return
        # The ring holds the object itself; serialization happens at poll
        # time per consumer (observability summary vs the full-fidelity
        # wire codec for store backends). Store objects are replaced, not
        # mutated (the mutation detector enforces it), so a late poll
        # serializes exactly the state the event captured. MODIFIED
        # entries also carry the replaced object so a v2 delta consumer
        # can be served the field-level patch; the patch itself is
        # computed lazily at first delta poll and cached in the entry
        # (slot 4) — computed once per event, not per consumer, and
        # never on the mutation hot path.
        with self._cond:
            self._seq += 1
            ring = self._events[kind]
            if len(ring) >= self.max_events:
                # true 410 on overflow: the dropped seq fences every
                # watcher holding an rv at or before it into a re-list
                self._dropped[kind] = ring.popleft()[0]
            ring.append([self._seq, verb, obj, old, None])
            self._cond.notify_all()

    def close(self) -> None:
        """Wake every blocked poll (server shutdown)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def _activate_locked(self) -> None:
        if not self._active:
            self._active = True
            self._journal_start = self._seq

    @property
    def resource_version(self) -> int:
        with self._cond:
            self._activate_locked()
            return self._seq

    def _event_payload(self, kind: str, entry: list, ser, delta: bool) -> dict:
        """Serialize one ring entry for a consumer. ``delta`` (v2 wire
        consumers only) turns MODIFIED into a field-level patch and
        DELETED into a bare key tombstone; ADDED always carries the
        full object (the client has nothing to patch)."""
        seq, verb, obj, old, cached = entry
        if delta and verb == "MODIFIED" and old is not None:
            patch = cached
            if patch is None:
                from kube_batch_tpu.apis.wire import delta_of

                from kube_batch_tpu.cache.store import obj_key

                patch = {"key": obj_key(kind, obj)}
                patch.update(delta_of(kind, old, obj))
                entry[4] = patch  # computed once per event, cached
            return {"seq": seq, "type": verb, "delta": patch}
        if delta and verb == "DELETED":
            from kube_batch_tpu.cache.store import obj_key

            return {"seq": seq, "type": verb, "key": obj_key(kind, obj)}
        return {"seq": seq, "type": verb, "object": ser(obj)}

    def _collect_locked(
        self, kind: str, since: int, wire: bool, delta: bool
    ) -> list[dict]:
        """Events past ``since`` for one kind. Ring entries are
        seq-ascending: walk from the right only as far as `since` —
        O(new events), not O(ring). Caller holds ``_cond``."""
        if wire:
            from kube_batch_tpu.apis.wire import to_wire as ser
        else:
            ser = SERIALIZERS[kind]
        batch: list[dict] = []
        for entry in reversed(self._events[kind]):
            if entry[0] <= since:
                break
            batch.append(self._event_payload(kind, entry, ser, delta))
        batch.reverse()
        return batch

    def poll(
        self,
        kind: str,
        since: int,
        timeout: float,
        stop: threading.Event,
        wire: bool = False,
        delta: bool = False,
    ) -> tuple[str, list[dict], int]:
        """("ok" | "gone", events, resourceVersion). Blocks up to
        `timeout` seconds for the first event past `since`. ``wire``
        selects the full-fidelity codec (apis/wire.py, store backends)
        over the observability summary serializer; ``delta`` (v2)
        additionally compresses MODIFIED events into field patches."""
        if faults.should_fire("watch.drop"):
            # Injected stream drop: the 410-Gone contract — the client
            # must re-list and resume from the returned resourceVersion.
            with self._cond:
                return "gone", [], self._seq
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                self._activate_locked()
                if since < max(self._dropped.get(kind, 0), self._journal_start):
                    return "gone", [], self._seq
                batch = self._collect_locked(kind, since, wire, delta)
                if batch:
                    return "ok", batch, self._seq
                remaining = deadline - time.monotonic()
                if remaining <= 0 or stop.is_set() or self._closed:
                    return "ok", [], self._seq
                self._cond.wait(min(remaining, 1.0))

    def poll_multi(
        self,
        cursors: dict[str, int],
        timeout: float,
        stop: threading.Event,
        delta: bool = False,
    ) -> tuple[dict[str, dict], int]:
        """The v2 combined long-poll: one blocking call over EVERY
        subscribed kind's cursor, returning the moment ANY kind has an
        event past its cursor — the client's pump thread blocks here on
        the server instead of walking kinds with per-kind timeouts.
        Returns ``({kind: {"status": "ok"|"gone", "events": [...]}},
        resourceVersion)``; per-kind gone (ring overflow) rides inline
        so one fallen-behind kind re-lists without aborting the rest.
        Always the full-fidelity wire codec (backend consumers only)."""
        if faults.should_fire("watch.drop"):
            with self._cond:
                return (
                    {k: {"status": "gone", "events": []} for k in cursors},
                    self._seq,
                )
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                self._activate_locked()
                out: dict[str, dict] = {}
                ready = False
                for kind, since in cursors.items():
                    if since < max(self._dropped.get(kind, 0), self._journal_start):
                        out[kind] = {"status": "gone", "events": []}
                        ready = True
                        continue
                    batch = self._collect_locked(kind, since, True, delta)
                    out[kind] = {"status": "ok", "events": batch}
                    ready = ready or bool(batch)
                remaining = deadline - time.monotonic()
                if ready or remaining <= 0 or stop.is_set() or self._closed:
                    return out, self._seq
                self._cond.wait(min(remaining, 1.0))


class LeaderElector:
    """flock-based leader election (see module docstring)."""

    def __init__(self, lock_file: str, identity: str) -> None:
        self.lock_file = lock_file
        self.identity = identity
        self._fh = None

    def acquire(self, blocking: bool = True) -> bool:
        self._fh = open(self.lock_file, "a+")  # noqa: SIM115 - held for process life
        flags = fcntl.LOCK_EX | (0 if blocking else fcntl.LOCK_NB)
        try:
            fcntl.flock(self._fh, flags)
        except BlockingIOError:
            self._fh.close()
            self._fh = None
            return False
        self._fh.seek(0)
        self._fh.truncate()
        self._fh.write(self.identity)
        self._fh.flush()
        log.infof("became leader: %s", self.identity)
        return True

    def release(self) -> None:
        if self._fh is not None:
            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()
            self._fh = None


class StoreLeaseElector:
    """Cluster-wide leader election through a lease in a ClusterStore —
    the distributed half of HA (the flock LeaderElector stays the
    single-host fast path). Mirrors the reference's
    leaderelection.RunOrDie over a ConfigMap resource lock
    (cmd/kube-batch/app/server.go:115-139): lease_duration 15 s,
    renew_deadline 10 s, retry_period 5 s, identity
    ``hostname_pid_uuid``.

    The arbiter is either an in-process ``ClusterStore`` or the HTTP
    base URL of any scheduler-API server (``http://host:port``) — two
    machines point at the same URL and exactly one leads. The entire
    acquire-or-renew ladder executes atomically inside the arbiter under
    the ARBITER's clock, so candidate clock skew cannot split the lease.

    Renewal failures (network, arbiter down) are tolerated until
    ``renew_deadline`` has passed since the last successful renewal;
    then ``on_lost`` fires — process-level callers treat that as fatal,
    exactly like the reference's OnStoppedLeading glog.Fatalf
    (server.go:133-135)."""

    def __init__(
        self,
        arbiter,
        lease_name: str,
        identity: str,
        lease_duration: float = 15.0,
        renew_deadline: float = 10.0,
        retry_period: float = 5.0,
    ) -> None:
        self.arbiter = arbiter
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self.is_leader = False

    # -- one arbitration round-trip ----------------------------------------

    def _post(self, verb: str, payload: dict, timeout: float) -> dict:
        """One lease POST to the remote arbiter (shared by acquire and
        release so the path/encoding scheme cannot drift apart)."""
        import urllib.request

        req = urllib.request.Request(
            f"{self.arbiter.rstrip('/')}/apis/v1alpha1/leases/"
            f"{urllib.parse.quote(self.lease_name, safe='')}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())

    def _try_acquire(self, timeout: float = 5.0) -> bool:
        """One acquire-or-renew attempt; True iff we hold the lease.
        ``timeout`` bounds the HTTP round-trip — the renewal loop shrinks
        it to its remaining deadline budget so a hanging arbiter cannot
        push loss-detection past the lease expiry."""
        if faults.should_fire("lease.renew"):
            raise faults.FaultInjected("lease.renew: injected arbiter partition")
        if isinstance(self.arbiter, str):
            return bool(
                self._post(
                    "acquire",
                    {
                        "identity": self.identity,
                        "lease_duration": self.lease_duration,
                    },
                    timeout,
                ).get("acquired")
            )
        lease = self.arbiter.try_acquire_lease(
            self.lease_name, self.identity, self.lease_duration
        )
        return lease.holder_identity == self.identity

    def _release(self, timeout: float = 5.0) -> None:
        try:
            if isinstance(self.arbiter, str):
                self._post("release", {"identity": self.identity}, timeout)
            else:
                self.arbiter.release_lease(self.lease_name, self.identity)
        except Exception as e:  # best-effort: expiry will hand over anyway
            log.errorf("lease release failed (standby waits out the lease): %s", e)

    # -- lifecycle ----------------------------------------------------------

    def acquire(self, blocking: bool = True) -> bool:
        """Contend until the lease is ours (retry_period cadence, like
        client-go's acquire loop). Non-blocking: one attempt."""
        while not self._stop.is_set():
            try:
                if self._try_acquire():
                    self.is_leader = True
                    log.infof(
                        "became leader: %s (lease %s)", self.identity, self.lease_name
                    )
                    return True
            except Exception as e:
                log.errorf("lease acquire attempt failed: %s", e)
            if not blocking:
                return False
            self._stop.wait(self.retry_period)
        return False

    def start_renewing(self, on_lost) -> None:
        """Background renewal at retry_period cadence; fires ``on_lost``
        (once) if renew_deadline passes without a successful renewal or
        the arbiter reports another holder. A separate watchdog enforces
        the deadline on WALL time, independent of the renewal thread —
        urllib's timeout is per-socket-operation, so an arbiter dripping
        bytes could otherwise pin a renewal attempt (and loss detection)
        past the lease expiry."""
        lost_once = threading.Event()
        lost_lock = threading.Lock()  # watchdog + renewal race on the set

        def fire_lost(why: str) -> None:
            with lost_lock:
                if lost_once.is_set():
                    return
                lost_once.set()
            self._lose(why, on_lost)

        state = {"last_ok": time.monotonic()}

        def watchdog() -> None:
            while not self._stop.wait(
                min(0.5, max(0.05, self.renew_deadline / 10))
            ):
                if time.monotonic() - state["last_ok"] >= self.renew_deadline:
                    fire_lost("renew deadline exceeded (watchdog)")
                    return

        def loop() -> None:
            last_ok = state["last_ok"]
            wait = self.retry_period
            while not self._stop.wait(wait):
                # Deadline budget bounds each attempt (client-go bounds
                # renewals with a renewDeadline-scoped context for the
                # same reason): a hanging arbiter must not delay loss-
                # detection past the point where the lease can expire
                # under a standby.
                remaining = self.renew_deadline - (time.monotonic() - last_ok)
                if remaining <= 0:
                    fire_lost("renew deadline exceeded before attempt")
                    return
                try:
                    if self._try_acquire(timeout=max(0.5, min(5.0, remaining))):
                        if lost_once.is_set():
                            return  # watchdog already declared the loss
                        last_ok = time.monotonic()
                        state["last_ok"] = last_ok
                        wait = self.retry_period
                        continue
                    # someone else holds it — we were fenced out
                    fire_lost("lost to another holder")
                    return
                except Exception as e:
                    log.errorf("lease renewal attempt failed: %s", e)
                elapsed = time.monotonic() - last_ok
                if elapsed >= self.renew_deadline:
                    fire_lost("renew deadline exceeded")
                    return
                # After a failure, retry fast enough that several attempts
                # fit inside the remaining budget — a single transient
                # arbiter blip must not consume the whole deadline.
                wait = max(
                    0.05, min(self.retry_period, (self.renew_deadline - elapsed) / 3)
                )

        self._thread = threading.Thread(target=loop, name="kb-lease", daemon=True)
        self._thread.start()
        self._watchdog = threading.Thread(
            target=watchdog, name="kb-lease-watchdog", daemon=True
        )
        self._watchdog.start()

    def _lose(self, why: str, on_lost) -> None:
        log.errorf("lease %s: %s", self.lease_name, why)
        was_leader = self.is_leader
        self.is_leader = False
        if was_leader:
            # Best-effort release BEFORE on_lost (ADVICE r5): a renewal
            # already in flight when the watchdog fired can still land at
            # the arbiter (urllib's timeout is per-socket-op), silently
            # re-extending a dead leader's lease by a full window while
            # the standby waits it out. Clearing the holder bounds that
            # window to the in-flight attempt. Short timeout: on_lost is
            # typically a fatal exit, and renew_deadline + this bound must
            # stay under lease_duration (15/10/5 reference ratios hold).
            self._release(timeout=min(2.0, self.retry_period))
        on_lost()

    def release(self) -> None:
        """Stop renewing and hand the lease off gracefully. The release
        POST is sent only once the renewal thread has provably finished —
        an in-flight renewal landing after the release would silently
        re-take the lease for a dying process; if the thread cannot be
        joined in time we skip the hand-off and let the standby wait out
        the lease (the crash path, safe)."""
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=2)
            self._watchdog = None
        joined = True
        if self._thread is not None:
            self._thread.join(timeout=7)  # > max attempt timeout + retry
            joined = not self._thread.is_alive()
            if joined:
                self._thread = None
        if self.is_leader:
            self.is_leader = False
            if joined:
                self._release()
            else:
                log.errorf(
                    "lease %s: renewal still in flight at shutdown; skipping "
                    "graceful release (standby waits out the lease)",
                    self.lease_name,
                )


def _make_handler(server: "SchedulerServer"):
    class Handler(BaseHTTPRequestHandler):
        # Wire protocol v2: HTTP/1.1 keep-alive so the backend client's
        # connection pool reuses sockets across requests (_reply always
        # sends Content-Length, which 1.1 persistence requires). A
        # v1-pinned server keeps http.server's 1.0 default — one
        # connection per op, exactly the pre-v2 wire behavior.
        if getattr(server, "wire_protocol", 2) >= 2:
            protocol_version = "HTTP/1.1"
            # TCP_NODELAY (socketserver applies this per connection in
            # StreamRequestHandler.setup): without it every
            # reused-connection round trip sits out the Nagle vs
            # delayed-ACK interaction (~40ms) — more latency than the
            # whole RTT the keep-alive transport exists to amortize.
            disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # route http.server chatter to V(4)
            log.V(4).infof("http: " + fmt, *args)

        def _reply(self, code: int, body: str, ctype: str = "application/json",
                   headers: Optional[dict] = None) -> None:
            self._reply_bytes(code, body.encode(), ctype, headers)

        def _reply_bytes(self, code: int, data: bytes, ctype: str,
                         headers: Optional[dict] = None) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(data)

        def _wants_binary(self) -> bool:
            """Content negotiation for /backend/v1/ replies: the client
            advertises the binary codec in Accept; a v1-pinned server
            never honors it (the client then sees JSON come back and
            keeps speaking JSON — negotiation by response)."""
            from kube_batch_tpu.apis.wire import BINARY_CONTENT_TYPE

            return server.wire_protocol >= 2 and BINARY_CONTENT_TYPE in (
                self.headers.get("Accept") or ""
            )

        def _backend_reply(self, code: int, payload: dict) -> None:
            """Serialize a /backend/v1/ reply in the negotiated codec.
            Error replies stay JSON on purpose: a mixed-version client
            must be able to read a 404/409/410 before (or without)
            codec agreement."""
            from kube_batch_tpu.apis import wire as wire_mod

            if code == 200 and self._wants_binary():
                self._reply_bytes(
                    code,
                    wire_mod.dumps_binary(payload),
                    wire_mod.BINARY_CONTENT_TYPE,
                )
            else:
                self._reply(code, json.dumps(payload))

        def do_GET(self):  # noqa: N802 (http.server API)
            parsed = urllib.parse.urlsplit(self.path)
            path = parsed.path
            if path == "/metrics":
                # Refresh the SLO quantile gauges from the sliding
                # windows right before exposition — scrape-time freshness
                # without a publisher thread. When this process is a
                # fleet aggregator (KBT_FLEET), refresh the cluster-wide
                # rollup the same way (internally rate-limited).
                obs.slo.publish()
                from kube_batch_tpu.obs import fleet as obs_fleet

                obs_fleet.refresh()
                from kube_batch_tpu import admission

                admission.publish()
                self._reply(
                    200, metrics.render_prometheus_text(), "text/plain; version=0.0.4"
                )
            elif path == "/healthz":
                self._reply(200, "ok", "text/plain")
            elif path == "/version":
                self._reply(200, "\n".join(version.info()) + "\n", "text/plain")
            elif path == "/debug/trace":
                # Flight-recorder peek: the bounded ring of recent cycle
                # traces. ``?dump=1`` additionally writes the jsonl +
                # Chrome trace files and returns their paths.
                query = urllib.parse.parse_qs(parsed.query)
                payload = {
                    "enabled": obs.enabled(),
                    "traces": obs.recorder.trace_count(),
                    "spans": obs.recorder.spans(),
                }
                if query.get("dump", ["0"])[0] not in ("", "0", "false"):
                    payload["dump"] = obs.recorder.dump(reason="debug_endpoint")
                self._reply(200, json.dumps(payload))
            elif path == "/debug/slo":
                # ``?raw=1`` returns the serialized mergeable sketches
                # (the fleet aggregation wire form) instead of the
                # human-readable quantile snapshot.
                query = urllib.parse.parse_qs(parsed.query)
                if query.get("raw", ["0"])[0] not in ("", "0", "false"):
                    from kube_batch_tpu.obs import fleet as obs_fleet

                    self._reply(200, json.dumps(obs_fleet.raw_slo_payload()))
                else:
                    self._reply(200, json.dumps(obs.slo.snapshot()))
            elif path == "/debug/fleet":
                # The cluster-wide rollup: a forced scrape of the
                # configured peers, merged. {"enabled": false} when
                # KBT_FLEET is off.
                from kube_batch_tpu.obs import fleet as obs_fleet

                self._reply(200, json.dumps(obs_fleet.refresh(force=True)))
            elif path == "/debug/explain":
                # Unschedulability forensics registry (obs/explain):
                # per-gang reason records + cross-gang aggregate;
                # ``?gang=ns/name`` filters to one gang.
                from kube_batch_tpu.obs import explain as obs_explain

                query = urllib.parse.parse_qs(parsed.query)
                gang = query.get("gang", [""])[0] or None
                self._reply(200, json.dumps(obs_explain.debug_payload(gang)))
            elif path == "/debug/admission":
                # Admission control plane (admission.py): per-lane
                # buckets/backlogs/shed counters plus the backpressure
                # controller's level/pressure. {"enabled": false} when
                # KBT_ADMISSION is off.
                from kube_batch_tpu import admission

                self._reply(200, json.dumps(admission.debug_payload()))
            elif path == "/backend/v1/version":
                # Store-backend protocol (cache/backend.py): the store
                # version optimistic writes are checked against. A v2
                # server additionally advertises its protocol level and
                # capabilities here — the client's one negotiation read;
                # a v1 server's bare reply IS the downgrade signal.
                payload = {"storeVersion": server.store.version}
                if server.wire_protocol >= 2:
                    payload.update(
                        {
                            "protocol": 2,
                            "codecs": ["json", "binary"],
                            "features": ["delta", "txn", "longpoll"],
                        }
                    )
                self._backend_reply(200, payload)
            elif path == "/backend/v1/watchall":
                # v2 combined long-poll (absent under a v1 pin: the 404
                # sends a v2 client back to per-kind polling).
                if server.wire_protocol < 2:
                    self._reply(404, json.dumps({"error": "not found"}))
                    return
                query = urllib.parse.parse_qs(parsed.query)
                import math

                try:
                    timeout = float(query.get("timeout", ["30"])[0])
                    cursors = {}
                    for part in query.get("cursors", [""])[0].split(","):
                        if not part:
                            continue
                        kind, _, since = part.partition(":")
                        if kind not in SERIALIZERS:
                            raise ValueError(kind)
                        cursors[kind] = int(since or "0")
                except ValueError:
                    self._reply(400, json.dumps({"error": "bad cursors/timeout"}))
                    return
                if not math.isfinite(timeout):
                    self._reply(400, json.dumps({"error": "bad cursors/timeout"}))
                    return
                timeout = min(max(timeout, 0.0), 300.0)
                delta = query.get("delta", ["0"])[0] not in ("", "0", "false")
                kinds, rv = server.watch_hub.poll_multi(
                    cursors, timeout, server._stop, delta=delta
                )
                metrics.register_longpoll_wakeup(
                    "events"
                    if any(k["events"] or k["status"] == "gone" for k in kinds.values())
                    else "timeout"
                )
                self._backend_reply(
                    200,
                    {
                        "kinds": kinds,
                        "resourceVersion": rv,
                        "storeVersion": server.store.version,
                    },
                )
            elif path.startswith("/backend/v1/watch/"):
                kind = path[len("/backend/v1/watch/"):]
                if kind not in SERIALIZERS:
                    self._reply(404, json.dumps({"error": f"unknown kind {kind!r}"}))
                    return
                query = urllib.parse.parse_qs(parsed.query)
                try:
                    since = int(query.get("since", ["0"])[0])
                    timeout = float(query.get("timeout", ["30"])[0])
                except ValueError:
                    self._reply(400, json.dumps({"error": "bad since/timeout"}))
                    return
                import math

                if not math.isfinite(timeout):
                    self._reply(400, json.dumps({"error": "bad since/timeout"}))
                    return
                timeout = min(max(timeout, 0.0), 300.0)
                delta = server.wire_protocol >= 2 and query.get("delta", ["0"])[
                    0
                ] not in ("", "0", "false")
                status, events, rv = server.watch_hub.poll(
                    kind, since, timeout, server._stop, wire=True, delta=delta
                )
                if status == "gone":
                    self._reply(
                        410, json.dumps({"error": "too old", "resourceVersion": rv})
                    )
                    return
                self._backend_reply(
                    200, {"events": events, "resourceVersion": rv}
                )
            elif path.startswith("/backend/v1/"):
                from kube_batch_tpu.apis.wire import to_wire

                kind = path[len("/backend/v1/"):]
                if kind not in SERIALIZERS:
                    self._reply(404, json.dumps({"error": "not found"}))
                    return
                # rv BEFORE the list, same at-least-once rule as the
                # observability list endpoint below.
                rv = server.watch_hub.resource_version
                store_v = server.store.version
                items = [to_wire(obj) for obj in server.store.list(kind)]
                self._backend_reply(
                    200,
                    {
                        "items": items,
                        "resourceVersion": rv,
                        "storeVersion": store_v,
                    },
                )
            elif path.startswith("/apis/v1alpha1/watch/"):
                kind = path[len("/apis/v1alpha1/watch/"):]
                if kind not in SERIALIZERS:
                    self._reply(404, json.dumps({"error": f"unknown kind {kind!r}"}))
                    return
                query = urllib.parse.parse_qs(parsed.query)
                try:
                    since = int(query.get("since", ["0"])[0])
                    timeout = float(query.get("timeout", ["30"])[0])
                except ValueError:
                    self._reply(400, json.dumps({"error": "bad since/timeout"}))
                    return
                import math

                if not math.isfinite(timeout):  # nan/inf would spin forever
                    self._reply(400, json.dumps({"error": "bad since/timeout"}))
                    return
                timeout = min(max(timeout, 0.0), 300.0)
                status, events, rv = server.watch_hub.poll(
                    kind, since, timeout, server._stop
                )
                if status == "gone":
                    # k8s 410 Gone: the client's resourceVersion fell out
                    # of the ring; it must re-list and resume from there.
                    self._reply(
                        410, json.dumps({"error": "too old", "resourceVersion": rv})
                    )
                    return
                self._reply(
                    200, json.dumps({"events": events, "resourceVersion": rv})
                )
            elif path.startswith("/apis/v1alpha1/"):
                kind = path[len("/apis/v1alpha1/"):]
                ser = SERIALIZERS.get(kind)
                if ser is None:
                    self._reply(404, json.dumps({"error": "not found"}))
                    return
                # rv read BEFORE the list: a watch from this rv re-delivers
                # anything that lands between the two reads (at-least-once)
                # rather than silently skipping it.
                rv = server.watch_hub.resource_version
                items = [ser(obj) for obj in server.store.list(kind)]
                self._reply(
                    200, json.dumps({"items": items, "resourceVersion": rv})
                )
            else:
                self._reply(404, json.dumps({"error": "not found"}))

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length", "0"))
            raw = self.rfile.read(length) or b"{}"
            ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
            from kube_batch_tpu.apis.wire import BINARY_CONTENT_TYPE, loads_binary

            if ctype == BINARY_CONTENT_TYPE:
                if server.wire_protocol < 2:
                    raise ValueError(
                        "binary request body on a v1 server (re-negotiate: "
                        "GET /backend/v1/version)"
                    )
                return loads_binary(raw)
            return json.loads(raw)

        def _backend_post(self, tail: str, body: dict) -> None:
            """Store-backend mutation surface (cache/backend.py client).

            Conditional writes carry the caller's snapshot version and a
            stale one is a 409 with the full StaleWrite payload — the
            client re-raises it so the dispatch path is backend-agnostic.
            The generic CRUD route takes full-fidelity wire objects
            (apis/wire.py), unlike the lossy ingestion routes below.
            """
            from kube_batch_tpu.apis import wire

            def parse_bindings(raw) -> list:
                if not isinstance(raw, list):
                    raise ValueError("bindings must be a list")
                bindings = []
                for entry in raw:
                    if not (isinstance(entry, (list, tuple)) and len(entry) == 3):
                        raise ValueError(
                            "each binding must be [namespace, name, hostname]"
                        )
                    bindings.append(tuple(str(x) for x in entry))
                return bindings

            try:
                if tail == "bind":
                    bindings = parse_bindings(body.get("bindings"))
                    version = int(body.get("snapshotVersion", 0))
                    # Store-side half of the distributed bind trace: the
                    # client (cache/backend.py) sends its gang.bind span
                    # context in X-KBT-* headers; parenting on it makes a
                    # federated conflict retry one connected trace across
                    # scheduler and arbiter processes.
                    with obs.span(
                        "store.bind",
                        parent=obs.from_headers(self.headers),
                        binds=len(bindings),
                        version=version,
                    ) as bspan:
                        applied = server.store.conditional_bind_many(
                            bindings, version
                        )
                        bspan.set_attr("applied", len(applied))
                    self._backend_reply(
                        200,
                        {
                            "applied": len(applied),
                            "storeVersion": server.store.version,
                        },
                    )
                elif tail == "txn":
                    # v2 coalesced conditional writes: one round trip, a
                    # batch of per-gang transactions, per-transaction 409
                    # results inline (the HTTP status stays 200 — one
                    # conflicted gang must not fail its batchmates).
                    if server.wire_protocol < 2:
                        self._reply(404, json.dumps({"error": "not found"}))
                        return
                    txns = body.get("txns")
                    if not isinstance(txns, list):
                        raise ValueError("txns must be a list")
                    results = []
                    with obs.span(
                        "store.txn",
                        parent=obs.from_headers(self.headers),
                        txns=len(txns),
                    ) as tspan:
                        for txn in txns:
                            if not isinstance(txn, dict):
                                raise ValueError("each txn must be an object")
                            op = txn.get("op")
                            version = int(txn.get("snapshotVersion", 0))
                            try:
                                if op == "bind":
                                    applied = server.store.conditional_bind_many(
                                        parse_bindings(txn.get("bindings")), version
                                    )
                                    results.append({"applied": len(applied)})
                                elif op in ("evict", "unbind"):
                                    old = server.store.conditional_evict(
                                        str(txn.get("namespace", "")),
                                        str(txn.get("name", "")),
                                        version,
                                    )
                                    results.append({"evicted": old is not None})
                                else:
                                    raise ValueError(f"unknown txn op {op!r}")
                            except StaleWrite as e:
                                results.append(
                                    {
                                        "conflict": {
                                            "kind": e.kind,
                                            "key": e.key,
                                            "reason": e.reason,
                                            "expected": e.expected,
                                            "actual": e.actual,
                                        }
                                    }
                                )
                        tspan.set_attr(
                            "conflicts",
                            sum(1 for r in results if "conflict" in r),
                        )
                    metrics.observe_txn_batch_size(len(txns))
                    self._backend_reply(
                        200,
                        {
                            "results": results,
                            "storeVersion": server.store.version,
                        },
                    )
                elif tail == "evict":
                    namespace = str(body.get("namespace", ""))
                    name = str(body.get("name", ""))
                    if not name:
                        raise ValueError("name must be non-empty")
                    version = int(body.get("snapshotVersion", 0))
                    old = server.store.conditional_evict(namespace, name, version)
                    self._backend_reply(
                        200,
                        {
                            "evicted": old is not None,
                            "storeVersion": server.store.version,
                        },
                    )
                elif tail in SERIALIZERS:
                    verb = body.get("verb")
                    if verb == "create":
                        obj = wire.decode_kind(tail, body.get("object") or {})
                        server.store.create(tail, obj)
                    elif verb == "update":
                        obj = wire.decode_kind(tail, body.get("object") or {})
                        server.store.update(tail, obj)
                    elif verb == "delete":
                        key = body.get("key")
                        if not isinstance(key, str) or not key:
                            raise ValueError("delete needs a non-empty string key")
                        server.store.delete(tail, key)
                    else:
                        raise ValueError(f"unknown verb {verb!r}")
                    self._backend_reply(
                        200, {"storeVersion": server.store.version}
                    )
                else:
                    self._reply(404, json.dumps({"error": "not found"}))
            except StaleWrite as e:
                # Optimistic-concurrency loss: typed 409 so the backend
                # client can reconstruct the exact conflict and the loser
                # resyncs only the conflicted gang.
                self._reply(
                    409,
                    json.dumps(
                        {
                            "conflict": {
                                "kind": e.kind,
                                "key": e.key,
                                "reason": e.reason,
                                "expected": e.expected,
                                "actual": e.actual,
                            }
                        }
                    ),
                )

        def do_POST(self):  # noqa: N802
            from kube_batch_tpu.apis.types import PodPhase
            from kube_batch_tpu.testing import (
                build_node,
                build_pod,
                build_pod_group,
                build_resource_list,
            )

            # Validation before anything reaches the store: a type-poisoned
            # object (str priority, str labels) would not fail here — it
            # would fail inside every subsequent scheduling cycle.
            def field(body, key, typ, default, required: bool = False):
                if key not in body:
                    if required:
                        raise ValueError(f"missing required field {key!r}")
                    return default
                val = body[key]
                if isinstance(val, bool) and typ is not bool:
                    raise ValueError(f"field {key!r} must be {typ.__name__}, got bool")
                if typ is int and isinstance(val, (int, str)):
                    return int(val)
                if not isinstance(val, typ):
                    raise ValueError(
                        f"field {key!r} must be {typ.__name__}, got {type(val).__name__}"
                    )
                return val

            def resource_list(d) -> dict:
                if not isinstance(d, dict):
                    raise ValueError("resource list must be an object")
                for k, v in d.items():
                    if isinstance(v, bool) or not isinstance(v, (int, float, str)):
                        raise ValueError(
                            f"resource {k!r} must be a number or quantity string"
                        )
                # k8s-style quantity strings ("8Gi", "500m") -> floats
                return build_resource_list(
                    cpu=d.get("cpu", 0),
                    memory=d.get("memory", 0),
                    pods=int(d.get("pods", 0)),
                    **{k: v for k, v in d.items() if k not in ("cpu", "memory", "pods")},
                )

            try:
                body = self._read_body()
                if not isinstance(body, dict):
                    raise ValueError("request body must be a JSON object")
                if self.path.startswith("/backend/v1/"):
                    self._backend_post(self.path[len("/backend/v1/"):], body)
                elif self.path == "/apis/v1alpha1/queues":
                    name = field(body, "name", str, None, required=True)
                    weight = field(body, "weight", int, 1)
                    if weight < 1:
                        raise ValueError("weight must be >= 1")
                    server.store.create_queue(
                        Queue(metadata=ObjectMeta(name=name), spec=QueueSpec(weight=weight))
                    )
                    self._reply(201, json.dumps({"name": name, "weight": weight}))
                elif self.path == "/apis/v1alpha1/pods":
                    name = field(body, "name", str, None, required=True)
                    namespace = field(body, "namespace", str, "default")
                    pod = build_pod(
                        namespace=namespace,
                        name=name,
                        node_name=field(body, "node_name", str, ""),
                        phase=PodPhase(field(body, "phase", str, "Pending")),
                        req=resource_list(body.get("requests", {})),
                        group_name=field(body, "group", str, ""),
                        labels=field(body, "labels", dict, None),
                        priority=field(body, "priority", int, None),
                        node_selector=field(body, "node_selector", dict, None),
                        scheduler_name=field(
                            body, "scheduler_name", str, server.cache.scheduler_name
                        ),
                        volumes=[
                            str(v) for v in field(body, "volumes", list, []) or []
                        ],
                    )
                    pod.priority_class_name = field(body, "priority_class_name", str, "")
                    # Admission-controller stand-in: kube-batch reads
                    # pod.Spec.Priority already resolved by k8s admission
                    # from the PriorityClass; with no admission layer here,
                    # ingestion resolves it (named class, else the global
                    # default class).
                    if pod.priority is None:
                        pc = None
                        if pod.priority_class_name:
                            pc = server.store.get(
                                "priorityclasses", pod.priority_class_name
                            )
                            if pc is None:
                                raise ValueError(
                                    f"unknown priority class {pod.priority_class_name!r}"
                                )
                        else:
                            pc = next(
                                (
                                    c
                                    for c in server.store.list("priorityclasses")
                                    if c.global_default
                                ),
                                None,
                            )
                        if pc is not None:
                            pod.priority = pc.value
                    # Per-tenant admission (admission.py): resolve the
                    # pod's queue (explicit field, else its podgroup's,
                    # else the default), ask the lane gate, and refuse
                    # overload loudly — 429 + Retry-After, never a
                    # silent drop or an unbounded queue.
                    from kube_batch_tpu import admission

                    decision = None
                    pod_key = f"{pod.namespace}/{pod.name}"
                    if admission.enabled():
                        from kube_batch_tpu.apis.types import (
                            GROUP_NAME_ANNOTATION_KEY,
                        )

                        queue = field(body, "queue", str, "")
                        group = pod.metadata.annotations.get(
                            GROUP_NAME_ANNOTATION_KEY, ""
                        )
                        if not queue and group:
                            pg = server.store.get(
                                "podgroups", f"{pod.namespace}/{group}"
                            )
                            if pg is not None:
                                queue = pg.spec.queue
                        decision = admission.decide(
                            queue or server.cache.default_queue, pod_key
                        )
                    if decision is not None and not decision.admitted:
                        self._reply(
                            429,
                            json.dumps({
                                "error": "admission shed",
                                "lane": decision.lane,
                                "reason": decision.reason,
                                "retry_after_s": round(decision.retry_after_s, 3),
                            }),
                            headers={
                                "Retry-After": str(
                                    max(1, int(decision.retry_after_s + 0.999))
                                )
                            },
                        )
                    else:
                        try:
                            server.store.create_pod(pod)
                        except Exception:
                            admission.release(pod_key)
                            raise
                        self._reply(
                            201,
                            json.dumps(
                                {"namespace": pod.namespace, "name": pod.name}
                            ),
                        )
                elif self.path == "/apis/v1alpha1/nodes":
                    name = field(body, "name", str, None, required=True)
                    node = build_node(
                        name,
                        resource_list(body.get("allocatable", {})),
                        labels=field(body, "labels", dict, None),
                    )
                    server.store.create_node(node)
                    self._reply(201, json.dumps({"name": node.name}))
                elif self.path == "/apis/v1alpha1/podgroups":
                    name = field(body, "name", str, None, required=True)
                    namespace = field(body, "namespace", str, "default")
                    pg = build_pod_group(
                        name,
                        namespace=namespace,
                        queue=field(body, "queue", str, server.cache.default_queue),
                        min_member=field(body, "min_member", int, 1),
                    )
                    server.store.create_pod_group(pg)
                    self._reply(
                        201,
                        json.dumps({"namespace": pg.metadata.namespace, "name": pg.name}),
                    )
                elif self.path == "/apis/v1alpha1/priorityclasses":
                    from kube_batch_tpu.apis.types import PriorityClass

                    name = field(body, "name", str, None, required=True)
                    pc = PriorityClass(
                        metadata=ObjectMeta(name=name, uid=f"pc-{name}"),
                        value=field(body, "value", int, 0),
                        global_default=field(body, "global_default", bool, False),
                    )
                    server.store.create_priority_class(pc)
                    self._reply(201, json.dumps({"name": name, "value": pc.value}))
                elif self.path == "/apis/v1alpha1/poddisruptionbudgets":
                    from kube_batch_tpu.apis.types import PodDisruptionBudget

                    name = field(body, "name", str, None, required=True)
                    namespace = field(body, "namespace", str, "default")
                    pdb = PodDisruptionBudget(
                        metadata=ObjectMeta(
                            name=name, namespace=namespace, uid=f"pdb-{namespace}-{name}"
                        ),
                        min_available=field(body, "min_available", int, 0),
                        selector=field(body, "selector", dict, None) or {},
                    )
                    server.store.create_pdb(pdb)
                    self._reply(201, json.dumps({"namespace": namespace, "name": name}))
                elif self.path == "/apis/v1alpha1/persistentvolumes":
                    from kube_batch_tpu.apis.types import (
                        NodeSelectorTerm,
                        PersistentVolume,
                    )
                    from kube_batch_tpu.testing import parse_quantity

                    name = field(body, "name", str, None, required=True)
                    terms = []
                    for t in field(body, "node_affinity", list, []) or []:
                        if not isinstance(t, dict):
                            raise ValueError("node_affinity entries must be objects")
                        terms.append(
                            NodeSelectorTerm(
                                key=str(t.get("key", "")),
                                operator=str(t.get("operator", "In")),
                                values=[str(v) for v in t.get("values", [])],
                            )
                        )
                    from kube_batch_tpu.apis.types import VolumePhase

                    pv = PersistentVolume(
                        metadata=ObjectMeta(name=name, uid=f"pv-{name}"),
                        capacity_storage=parse_quantity(body.get("capacity", 0)),
                        storage_class_name=field(body, "storage_class", str, ""),
                        node_affinity=terms,
                        # Mirroring an existing cluster needs bound state
                        # expressible at ingestion time.
                        claim_ref=field(body, "claim_ref", str, ""),
                        phase=VolumePhase(field(body, "phase", str, "Available")),
                    )
                    server.store.create_persistent_volume(pv)
                    self._reply(201, json.dumps({"name": name}))
                elif self.path == "/apis/v1alpha1/persistentvolumeclaims":
                    from kube_batch_tpu.apis.types import PersistentVolumeClaim
                    from kube_batch_tpu.testing import parse_quantity

                    name = field(body, "name", str, None, required=True)
                    namespace = field(body, "namespace", str, "default")
                    from kube_batch_tpu.apis.types import VolumePhase

                    volume_name = field(body, "volume_name", str, "")
                    pvc = PersistentVolumeClaim(
                        metadata=ObjectMeta(
                            name=name, namespace=namespace, uid=f"pvc-{namespace}-{name}"
                        ),
                        storage_class_name=field(body, "storage_class", str, ""),
                        request_storage=parse_quantity(body.get("request", 0)),
                        volume_name=volume_name,
                        phase=VolumePhase(
                            field(body, "phase", str, "Bound" if volume_name else "Pending")
                        ),
                    )
                    server.store.create_persistent_volume_claim(pvc)
                    self._reply(201, json.dumps({"namespace": namespace, "name": name}))
                elif (
                    self.path.startswith("/apis/v1alpha1/leases/")
                    and self.path.endswith(("/acquire", "/release"))
                ):
                    # Leader-election arbitration endpoint: the whole
                    # acquire-or-renew ladder runs atomically inside the
                    # store under the ARBITER's clock (store.py
                    # try_acquire_lease) — the role the reference's API
                    # server plays for its ConfigMap resource lock
                    # (cmd/kube-batch/app/server.go:115-139).
                    parts = self.path.strip("/").split("/")
                    if len(parts) != 5:
                        # a raw '/' in the name would smear across path
                        # segments and arbitrate the wrong scope —
                        # electors quote(name, safe="") to prevent this
                        raise ValueError(
                            "lease path must be /apis/v1alpha1/leases/<name>/<verb> "
                            "(percent-encode the name)"
                        )
                    # unquote restores the exact scope so HTTP and
                    # in-process candidates on the same name contend on
                    # the same lease
                    lease_name, verb = urllib.parse.unquote(parts[3]), parts[4]
                    if not lease_name:
                        raise ValueError("lease name must be non-empty")
                    identity = field(body, "identity", str, None, required=True)
                    if verb == "acquire":
                        duration = body.get("lease_duration", 15.0)
                        if isinstance(duration, bool) or not isinstance(
                            duration, (int, float)
                        ):
                            raise ValueError("lease_duration must be a number")
                        lease = server.store.try_acquire_lease(
                            lease_name, identity, float(duration)
                        )
                    else:
                        lease = server.store.release_lease(lease_name, identity)
                    if lease is None:
                        self._reply(404, json.dumps({"error": "lease not found"}))
                        return
                    self._reply(
                        200,
                        json.dumps(
                            {
                                "name": lease_name,
                                "holder": lease.holder_identity,
                                "acquired": lease.holder_identity == identity,
                                "lease_duration": lease.lease_duration_seconds,
                                "renew_time": lease.renew_time,
                                "transitions": lease.lease_transitions,
                            }
                        ),
                    )
                elif self.path == "/apis/v1alpha1/storageclasses":
                    from kube_batch_tpu.apis.types import (
                        StorageClass,
                        VolumeBindingMode,
                    )

                    name = field(body, "name", str, None, required=True)
                    sc = StorageClass(
                        metadata=ObjectMeta(name=name, uid=f"sc-{name}"),
                        provisioner=field(body, "provisioner", str, ""),
                        volume_binding_mode=VolumeBindingMode(
                            field(body, "volume_binding_mode", str, "Immediate")
                        ),
                    )
                    server.store.create_storage_class(sc)
                    self._reply(201, json.dumps({"name": name}))
                else:
                    self._reply(404, json.dumps({"error": "not found"}))
            except AlreadyExists as e:
                self._reply(409, json.dumps({"error": str(e.args[0])}))
            except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
                self._reply(400, json.dumps({"error": str(e)}))

        def do_DELETE(self):  # noqa: N802
            parts = self.path.strip("/").split("/")
            try:
                if parts[:2] != ["apis", "v1alpha1"] or len(parts) < 4:
                    self._reply(404, json.dumps({"error": "not found"}))
                    return
                kind, rest = parts[2], parts[3:]
                if kind == "queues" and len(rest) == 1:
                    server.store.delete_queue(rest[0])
                elif kind == "nodes" and len(rest) == 1:
                    server.store.delete_node(rest[0])
                elif kind == "pods" and len(rest) == 2:
                    server.store.delete_pod(rest[0], rest[1])
                elif kind == "podgroups" and len(rest) == 2:
                    server.store.delete_pod_group(rest[0], rest[1])
                elif kind == "priorityclasses" and len(rest) == 1:
                    server.store.delete_priority_class(rest[0])
                elif kind == "poddisruptionbudgets" and len(rest) == 2:
                    server.store.delete("poddisruptionbudgets", f"{rest[0]}/{rest[1]}")
                elif kind == "persistentvolumes" and len(rest) == 1:
                    server.store.delete_persistent_volume(rest[0])
                elif kind == "persistentvolumeclaims" and len(rest) == 2:
                    server.store.delete_persistent_volume_claim(rest[0], rest[1])
                elif kind == "storageclasses" and len(rest) == 1:
                    server.store.delete("storageclasses", rest[0])
                else:
                    self._reply(404, json.dumps({"error": "not found"}))
                    return
            except KeyError as e:
                self._reply(404, json.dumps({"error": str(e)}))
                return
            self._reply(200, json.dumps({"deleted": "/".join(parts[3:])}))

    return Handler


class SchedulerServer:
    """One process worth of scheduler: store + cache + loop + HTTP."""

    def __init__(
        self,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = DEFAULT_SCHEDULE_PERIOD,
        default_queue: str = DEFAULT_QUEUE,
        listen_address: str = DEFAULT_LISTEN_ADDRESS,
        store: Optional[ClusterStore] = None,
        journal_path: Optional[str] = None,
        store_backend_url: Optional[str] = None,
        wire_protocol: int = 2,
    ) -> None:
        import os

        # Store-backend wire protocol this server SPEAKS (not what any
        # client negotiated): 2 advertises delta watch / txn batches /
        # the binary codec on /backend/v1/version and serves HTTP/1.1
        # keep-alive; 1 pins the pre-v2 surface byte-for-byte (mixed-
        # version drills and the bench's v1 twin rows pass 1 here).
        self.wire_protocol = int(wire_protocol)

        # Federation mode (--store-backend): this process schedules over
        # a remote store's /backend/v1/ protocol instead of owning an
        # in-process store. The LoopbackBackend mirror duck-types the
        # store surface, so the watch hub, the observability reads and
        # the workload API below all serve (and proxy) from it.
        self.backend = None
        if store_backend_url:
            from kube_batch_tpu.cache.backend import LoopbackBackend

            self.backend = LoopbackBackend(store_backend_url)
            self.store = self.backend
        else:
            self.store = store or ClusterStore()
        self.watch_hub = WatchHub(self.store)
        # Crash-consistent write side (recovery/): --journal / KBT_JOURNAL
        # attaches a bind-intent WAL to the cache; start() reconciles it
        # against store truth before the loop runs.
        self.journal = None
        journal_path = journal_path or os.environ.get("KBT_JOURNAL", "").strip()
        if journal_path:
            from kube_batch_tpu.recovery import WriteIntentJournal

            self.journal = WriteIntentJournal(journal_path)
        self.slot_manager = None
        if self.backend is not None:
            from kube_batch_tpu.federation import (
                ENV as FED_ENV,
                FederatedCache,
                parse_shard_spec,
                shard_journal_dir,
                shard_journal_path,
                shard_key_mode,
            )

            shard, shards = parse_shard_spec(
                os.environ.get(FED_ENV, "").strip() or "1"
            )
            # Dynamic resharding: KBT_SHARD_JOURNAL_DIR gives every shard
            # a well-known per-slot journal (shard-{i}.wal) that a
            # survivor reconciles on adoption; an explicit --journal /
            # KBT_JOURNAL path wins.
            if self.journal is None and shards > 1 and shard_journal_dir():
                from kube_batch_tpu.recovery import WriteIntentJournal

                self.journal = WriteIntentJournal(
                    shard_journal_path(shard_journal_dir(), shard)
                )
            self.cache = FederatedCache(
                self.backend, shard=shard, shards=shards,
                shard_key=shard_key_mode(), scheduler_name=scheduler_name,
                default_queue=default_queue, journal=self.journal,
                staleness_fn=self.backend.snapshot_age,
            )
            if shards > 1:
                # Leased shard slots: this process holds (and renews) the
                # lease for its primary slot and adopts orphaned peers'
                # slots; the LoopbackBackend is the lease arbiter (its
                # lease verbs POST the store process's
                # /apis/v1alpha1/leases/ endpoint, its LEASES mirror is
                # the slot-watch).
                from kube_batch_tpu.federation import ShardSlotManager

                self.slot_manager = ShardSlotManager(
                    self.backend, self.cache,
                    identity=f"{scheduler_name}-{shard}@{os.getpid()}",
                    on_owned_change=lambda adopted, removed: (
                        self.scheduler.on_owned_slots_changed(adopted, removed)
                    ),
                )
        else:
            self.cache = SchedulerCache(
                self.store, scheduler_name=scheduler_name,
                default_queue=default_queue, journal=self.journal,
            )
        self.scheduler = Scheduler(
            self.cache, scheduler_conf=scheduler_conf, schedule_period=schedule_period
        )
        host, _, port = listen_address.rpartition(":")
        # Unlike the reference's ListenAddress (app/options/options.go),
        # which only serves metrics/healthz, this port also carries the
        # unauthenticated mutating workload API — so a bare ":8080"
        # defaults to loopback; binding other interfaces requires naming
        # them explicitly (e.g. "0.0.0.0:8080").
        self.httpd = ThreadingHTTPServer((host or "127.0.0.1", int(port)), _make_handler(self))
        self.httpd.daemon_threads = True
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    @property
    def listen_port(self) -> int:
        return self.httpd.server_address[1]

    def reconcile(self):
        """Takeover reconciliation (recovery/reconcile.py): scan the
        bind-intent journal against store truth — confirm landed writes,
        re-dispatch orphans, roll back half-bound gangs. Runs before the
        loop on every start (process restart AND lease takeover both
        pass through here: a leader only start()s after acquiring)."""
        if self.journal is None:
            return None
        from kube_batch_tpu.recovery import reconcile_journal

        return reconcile_journal(self.journal, self.store)

    def start(self) -> None:
        # Ensure the default queue exists (the reference expects an admin
        # to create it; the in-process store bootstraps it — in
        # federation mode the store process owns that bootstrap).
        if (
            self.backend is None
            and self.store.get("queues", self.cache.default_queue) is None
        ):
            self.store.create_queue(
                Queue(metadata=ObjectMeta(name=self.cache.default_queue))
            )
        self.reconcile()
        # Arm the workload-API admission gate (KBT_ADMISSION) and keep
        # its backlog accounting truthful: an admitted pod stops
        # counting against its lane when it binds, or when it is
        # deleted while still pending (client gave up / reaper).
        from kube_batch_tpu import admission

        if admission.configure() and self.backend is None:
            self.store.add_event_handler(
                "pods",
                EventHandler(
                    on_update=lambda old, new: (
                        admission.note_done(f"{new.namespace}/{new.name}")
                        if (not old.node_name and new.node_name) else None
                    ),
                    on_delete=lambda obj: admission.note_done(
                        f"{obj.namespace}/{obj.name}"
                    ),
                ),
            )
        if self.backend is not None:
            self.backend.start()
        if self.slot_manager is not None:
            # Acquire in the background: the cache already owns its
            # primary slot's filter, and optimistic binds keep a brief
            # double-ownership overlap correct — so scheduling need not
            # wait out a reclaim handshake with a survivor that adopted
            # our slot while we were down.
            threading.Thread(
                target=self.slot_manager.start,
                kwargs={"deadline_s": 3600.0},
                name="kb-slot-acquire",
                daemon=True,
            ).start()
        self._stop.clear()
        t_http = threading.Thread(
            target=self.httpd.serve_forever, name="kb-http", daemon=True
        )
        t_sched = threading.Thread(
            target=self.scheduler.run, args=(self._stop,), name="kb-loop", daemon=True
        )
        t_http.start()
        t_sched.start()
        self._threads = [t_http, t_sched]

    def stop(self) -> None:
        self._stop.set()
        if self.slot_manager is not None:
            # release owned slots so survivors adopt immediately instead
            # of waiting out the lease (the graceful half of failover)
            self.slot_manager.stop(release=True)
        self.watch_hub.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self.backend is not None:
            self.backend.stop()
        self.cache.stop()
        for t in self._threads:
            t.join(timeout=10)
        self._threads.clear()
        if self.journal is not None:
            self.journal.close()


def build_parser() -> argparse.ArgumentParser:
    """Flags at parity with options.go:57-78."""
    p = argparse.ArgumentParser(
        prog="kube-batch-tpu",
        description="TPU-native batch scheduler (kube-batch capability parity)",
    )
    p.add_argument(
        "--scheduler-name",
        default=DEFAULT_SCHEDULER_NAME,
        help="handle pods whose scheduler_name matches this",
    )
    p.add_argument(
        "--scheduler-conf", default="", help="absolute path of the scheduler conf file"
    )
    p.add_argument(
        "--schedule-period",
        type=float,
        default=DEFAULT_SCHEDULE_PERIOD,
        help="seconds between scheduling cycles",
    )
    p.add_argument(
        "--default-queue", default=DEFAULT_QUEUE, help="default queue for jobs"
    )
    p.add_argument(
        "--listen-address",
        default=DEFAULT_LISTEN_ADDRESS,
        help="HTTP listen address for /metrics and the workload/queue API; "
        "a bare ':PORT' binds loopback only — this port carries an "
        "unauthenticated mutating API, so name an interface (e.g. "
        "'0.0.0.0:8080') to expose it",
    )
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="acquire the lock file before running the loop (HA standby)",
    )
    p.add_argument(
        "--lock-file",
        default="",
        help="leader-election lock file (single-host HA; required with "
        "--leader-elect unless --lease-url is set)",
    )
    p.add_argument(
        "--lease-url",
        default="",
        help="base URL of the lease arbiter (any scheduler-API endpoint, "
        "e.g. http://store-host:8080) for cluster-wide leader election; "
        "replaces --lock-file when set",
    )
    p.add_argument(
        "--lease-name",
        default="kube-batch",
        help="lease object name under the arbiter (reference lock object "
        "name, server.go:117)",
    )
    p.add_argument(
        "--store-backend",
        default="",
        help="base URL of a store process (e.g. http://store:8080): run "
        "this scheduler over its /backend/v1/ protocol instead of an "
        "in-process store — federation mode. The shard is "
        "KBT_FEDERATION='i/N', the partition key KBT_SHARD_KEY "
        "(queue|namespace|gang); conflicting placements resolve by "
        "optimistic concurrency (losers retry with a fresh snapshot)",
    )
    p.add_argument(
        "--journal",
        default="",
        help="bind-intent journal (WAL) path for crash-consistent "
        "failover; reconciled against store truth on startup/takeover "
        "(env KBT_JOURNAL; empty = journaling off)",
    )
    p.add_argument(
        "--fleet",
        default="",
        help="comma-separated peer base URLs (http://host:port) to "
        "aggregate fleet-wide SLO sketches and counters from — serves "
        "cluster-wide kbt..._fleet_* gauges on /metrics and the merged "
        "rollup on /debug/fleet (env KBT_FLEET; empty = off). Works "
        "from any scheduler, or standalone with an unmatched "
        "--scheduler-name as a dedicated observatory",
    )
    p.add_argument("--version", action="store_true", help="show version and quit")
    p.add_argument("-v", type=int, default=0, help="log verbosity (glog -v)")
    return p


def run(argv: Optional[list[str]] = None) -> None:
    """reference app.Run (server.go:63-140)."""
    opt = build_parser().parse_args(argv)
    if opt.version:
        version.print_version_and_exit()
    if opt.leader_elect and not (opt.lock_file or opt.lease_url):
        raise SystemExit(
            "--lock-file or --lease-url must be set when --leader-elect is enabled"
        )
    log.set_verbosity(opt.v)
    # Last-gasp observability: dump the flight-recorder ring on SIGTERM
    # (chains any previously-installed handler). SIGKILL can't be
    # caught — that story is the dump-on-fault/abort paths plus the
    # journal trace links.
    obs.install_signal_dump()
    if opt.fleet:
        # The flag arms the same env the hot-reload path resolves, so a
        # conf without a fleet: key cannot undo it on the next cycle.
        import os as _os

        from kube_batch_tpu.obs import fleet as _fleet

        _os.environ[_fleet.ENV] = opt.fleet
        _fleet.configure()

    elector = None
    if opt.leader_elect:
        import os
        import socket
        import uuid

        identity = f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        if opt.lease_url:
            elector = StoreLeaseElector(opt.lease_url, opt.lease_name, identity)
            log.infof(
                "waiting for leadership on lease %s at %s ...",
                opt.lease_name, opt.lease_url,
            )
            elector.acquire(blocking=True)

            def _lost() -> None:
                # the reference's OnStoppedLeading is glog.Fatalf
                # (server.go:133-135): a fenced-out leader must not keep
                # mutating cluster state.
                log.errorf("leaderelection lost")
                os._exit(1)

            elector.start_renewing(_lost)
        else:
            elector = LeaderElector(opt.lock_file, identity)
            log.infof("waiting for leadership on %s ...", opt.lock_file)
            elector.acquire(blocking=True)

    server = SchedulerServer(
        scheduler_name=opt.scheduler_name,
        scheduler_conf=opt.scheduler_conf or None,
        schedule_period=opt.schedule_period,
        default_queue=opt.default_queue,
        listen_address=opt.listen_address,
        journal_path=opt.journal or None,
        store_backend_url=opt.store_backend or None,
    )
    # start() reconciles the journal before the loop: both the restart
    # and the lease-takeover path land here only once leadership (if
    # any) is held, so reconciliation always runs under the lease.
    server.start()
    log.infof(
        "kube-batch-tpu %s serving on :%d, scheduling every %.2fs",
        version.VERSION, server.listen_port, opt.schedule_period,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if elector is not None:
            elector.release()


if __name__ == "__main__":
    run()
