"""L7: process entry — flags, metrics HTTP server, leader election
(reference cmd/kube-batch/app/server.go:63-140 +
cmd/kube-batch/app/options/options.go:33-90).

``SchedulerServer`` assembles the full stack for one process: an
in-process ClusterStore (the API-server stand-in), the SchedulerCache,
the Scheduler loop on its own thread, and a ThreadingHTTPServer that
exposes:

- ``GET /metrics``   — Prometheus text exposition (promhttp.Handler
  equivalent; serves metrics.render_prometheus_text);
- ``GET /healthz``   — liveness;
- ``GET /version``   — version.info();
- ``GET /apis/v1alpha1/queues``            — list queues (CLI backend);
- ``POST /apis/v1alpha1/queues``           — create a queue;
- ``DELETE /apis/v1alpha1/queues/<name>``  — delete a queue.

The queue endpoints are the in-process replacement for the API-server
CRD surface the reference CLI talks to (pkg/cli/queue).

HA: the reference elects a leader through a ConfigMap resource lock
(server.go:96-137). The in-process equivalent is an OS file lock
(``flock``) on ``--lock-file``: exactly one scheduler process per lock
file runs the loop; the kernel releases the lock if the holder dies, so
a standby flock-blocked on the same file takes over — the same
single-active-scheduler guarantee, lease renewal included, without an
API server to arbitrate.
"""

from __future__ import annotations

import argparse
import fcntl
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from kube_batch_tpu import log, metrics, version
from kube_batch_tpu.apis.types import ObjectMeta, Queue, QueueSpec
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.scheduler import Scheduler

DEFAULT_SCHEDULER_NAME = "kube-batch-tpu"
DEFAULT_SCHEDULE_PERIOD = 1.0
DEFAULT_QUEUE = "default"
DEFAULT_LISTEN_ADDRESS = ":8080"


class LeaderElector:
    """flock-based leader election (see module docstring)."""

    def __init__(self, lock_file: str, identity: str) -> None:
        self.lock_file = lock_file
        self.identity = identity
        self._fh = None

    def acquire(self, blocking: bool = True) -> bool:
        self._fh = open(self.lock_file, "a+")  # noqa: SIM115 - held for process life
        flags = fcntl.LOCK_EX | (0 if blocking else fcntl.LOCK_NB)
        try:
            fcntl.flock(self._fh, flags)
        except BlockingIOError:
            self._fh.close()
            self._fh = None
            return False
        self._fh.seek(0)
        self._fh.truncate()
        self._fh.write(self.identity)
        self._fh.flush()
        log.infof("became leader: %s", self.identity)
        return True

    def release(self) -> None:
        if self._fh is not None:
            fcntl.flock(self._fh, fcntl.LOCK_UN)
            self._fh.close()
            self._fh = None


def _make_handler(server: "SchedulerServer"):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # route http.server chatter to V(4)
            log.V(4).infof("http: " + fmt, *args)

        def _reply(self, code: int, body: str, ctype: str = "application/json") -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802 (http.server API)
            if self.path == "/metrics":
                self._reply(
                    200, metrics.render_prometheus_text(), "text/plain; version=0.0.4"
                )
            elif self.path == "/healthz":
                self._reply(200, "ok", "text/plain")
            elif self.path == "/version":
                self._reply(200, "\n".join(version.info()) + "\n", "text/plain")
            elif self.path == "/apis/v1alpha1/queues":
                queues = [
                    {"name": q.name, "weight": q.spec.weight}
                    for q in server.store.list("queues")
                ]
                self._reply(200, json.dumps({"items": queues}))
            else:
                self._reply(404, json.dumps({"error": "not found"}))

        def do_POST(self):  # noqa: N802
            if self.path != "/apis/v1alpha1/queues":
                self._reply(404, json.dumps({"error": "not found"}))
                return
            length = int(self.headers.get("Content-Length", "0"))
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
                name = body["name"]
                weight = int(body.get("weight", 1))
                if weight < 1:
                    raise ValueError("weight must be >= 1")
            except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
                self._reply(400, json.dumps({"error": str(e)}))
                return
            try:
                server.store.create_queue(
                    Queue(metadata=ObjectMeta(name=name), spec=QueueSpec(weight=weight))
                )
            except KeyError as e:
                self._reply(409, json.dumps({"error": str(e)}))
                return
            self._reply(201, json.dumps({"name": name, "weight": weight}))

        def do_DELETE(self):  # noqa: N802
            prefix = "/apis/v1alpha1/queues/"
            if not self.path.startswith(prefix):
                self._reply(404, json.dumps({"error": "not found"}))
                return
            name = self.path[len(prefix):]
            try:
                server.store.delete_queue(name)
            except KeyError as e:
                self._reply(404, json.dumps({"error": str(e)}))
                return
            self._reply(200, json.dumps({"deleted": name}))

    return Handler


class SchedulerServer:
    """One process worth of scheduler: store + cache + loop + HTTP."""

    def __init__(
        self,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = DEFAULT_SCHEDULE_PERIOD,
        default_queue: str = DEFAULT_QUEUE,
        listen_address: str = DEFAULT_LISTEN_ADDRESS,
        store: Optional[ClusterStore] = None,
    ) -> None:
        self.store = store or ClusterStore()
        self.cache = SchedulerCache(
            self.store, scheduler_name=scheduler_name, default_queue=default_queue
        )
        self.scheduler = Scheduler(
            self.cache, scheduler_conf=scheduler_conf, schedule_period=schedule_period
        )
        host, _, port = listen_address.rpartition(":")
        # ":8080" means all interfaces, matching the reference's
        # net.Listen semantics for ListenAddress (app/options/options.go)
        self.httpd = ThreadingHTTPServer((host or "0.0.0.0", int(port)), _make_handler(self))
        self.httpd.daemon_threads = True
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    @property
    def listen_port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> None:
        # Ensure the default queue exists (the reference expects an admin
        # to create it; the in-process store bootstraps it).
        if self.store.get("queues", self.cache.default_queue) is None:
            self.store.create_queue(
                Queue(metadata=ObjectMeta(name=self.cache.default_queue))
            )
        self._stop.clear()
        t_http = threading.Thread(
            target=self.httpd.serve_forever, name="kb-http", daemon=True
        )
        t_sched = threading.Thread(
            target=self.scheduler.run, args=(self._stop,), name="kb-loop", daemon=True
        )
        t_http.start()
        t_sched.start()
        self._threads = [t_http, t_sched]

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.cache.stop()
        for t in self._threads:
            t.join(timeout=10)
        self._threads.clear()


def build_parser() -> argparse.ArgumentParser:
    """Flags at parity with options.go:57-78."""
    p = argparse.ArgumentParser(
        prog="kube-batch-tpu",
        description="TPU-native batch scheduler (kube-batch capability parity)",
    )
    p.add_argument(
        "--scheduler-name",
        default=DEFAULT_SCHEDULER_NAME,
        help="handle pods whose scheduler_name matches this",
    )
    p.add_argument(
        "--scheduler-conf", default="", help="absolute path of the scheduler conf file"
    )
    p.add_argument(
        "--schedule-period",
        type=float,
        default=DEFAULT_SCHEDULE_PERIOD,
        help="seconds between scheduling cycles",
    )
    p.add_argument(
        "--default-queue", default=DEFAULT_QUEUE, help="default queue for jobs"
    )
    p.add_argument(
        "--listen-address",
        default=DEFAULT_LISTEN_ADDRESS,
        help="HTTP listen address for /metrics and the queue API",
    )
    p.add_argument(
        "--leader-elect",
        action="store_true",
        help="acquire the lock file before running the loop (HA standby)",
    )
    p.add_argument(
        "--lock-file",
        default="",
        help="leader-election lock file (required with --leader-elect)",
    )
    p.add_argument("--version", action="store_true", help="show version and quit")
    p.add_argument("-v", type=int, default=0, help="log verbosity (glog -v)")
    return p


def run(argv: Optional[list[str]] = None) -> None:
    """reference app.Run (server.go:63-140)."""
    opt = build_parser().parse_args(argv)
    if opt.version:
        version.print_version_and_exit()
    if opt.leader_elect and not opt.lock_file:
        raise SystemExit("--lock-file must be set when --leader-elect is enabled")
    log.set_verbosity(opt.v)

    elector = None
    if opt.leader_elect:
        import os
        import socket
        import uuid

        identity = f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        elector = LeaderElector(opt.lock_file, identity)
        log.infof("waiting for leadership on %s ...", opt.lock_file)
        elector.acquire(blocking=True)

    server = SchedulerServer(
        scheduler_name=opt.scheduler_name,
        scheduler_conf=opt.scheduler_conf or None,
        schedule_period=opt.schedule_period,
        default_queue=opt.default_queue,
        listen_address=opt.listen_address,
    )
    server.start()
    log.infof(
        "kube-batch-tpu %s serving on :%d, scheduling every %.2fs",
        version.VERSION, server.listen_port, opt.schedule_period,
    )
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if elector is not None:
            elector.release()


if __name__ == "__main__":
    run()
