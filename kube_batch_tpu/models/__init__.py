"""Synthetic workload generators — the five BASELINE.md bench configs.

The reference measures itself only against live kubemark clusters
(test/e2e/benchmark.go:49-281); this package generates equivalent hollow
cluster states in-process (no API server) so the scheduling paths can be
benchmarked and property-tested at any scale. Config shapes follow
BASELINE.md "Benchmark configs to reproduce":

1. `gang_example`      — example/job.yaml: minMember=3 gang on 3 nodes
2. `synthetic`         — 1k pods x 100 nodes, uniform small jobs
3. `multi_queue`       — 10k x 1k, multi-queue, gang jobs
4. `preempt_mix`       — 50k x 5k, priority spread + running victims
5. `multi_tenant_ml`   — TFJob/MPIJob-style PS+worker gangs, 100 queues,
                         GPU/TPU scalar resources

All quantities are milli-CPU / MiB granular so float32 device arithmetic
is exact (see ops/encode.py).
"""

from __future__ import annotations

import random

from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.apis.types import PodPhase, Taint, Toleration
from kube_batch_tpu.testing import (
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

GPU = "nvidia.com/gpu"
TPU = "google.com/tpu"


def gang_example() -> ClusterInfo:
    """Config 1: the reference's example/job.yaml — one PodGroup,
    minMember=3, on a 3-node cluster."""
    pods = [
        build_pod(name=f"qj-{i}", group_name="qj-1", req=build_resource_list(cpu=1, memory="512Mi"))
        for i in range(3)
    ]
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=2, memory="2Gi", pods=110))
        for i in range(3)
    ]
    return build_cluster(pods, nodes, [build_pod_group("qj-1", min_member=3)], [build_queue("default")])


def _uniform_nodes(n_nodes: int, cpu: int = 16, mem_mi: int = 32768, pods: int = 110) -> list:
    return [
        build_node(
            f"node-{i:05d}",
            build_resource_list(cpu=cpu, memory=f"{mem_mi}Mi", pods=pods),
        )
        for i in range(n_nodes)
    ]


def synthetic(n_pods: int = 1000, n_nodes: int = 100, tasks_per_job: int = 10, seed: int = 0) -> ClusterInfo:
    """Config 2: kubemark-style hollow density state — small gang jobs,
    one queue."""
    rng = random.Random(seed)
    pods, pgs = [], []
    n_jobs = max(n_pods // tasks_per_job, 1)
    for j in range(n_jobs):
        name = f"job-{j:05d}"
        pgs.append(build_pod_group(name, min_member=max(tasks_per_job // 2, 1)))
        for t in range(tasks_per_job):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu=f"{rng.choice([100, 250, 500])}m",
                        memory=f"{rng.choice([128, 256, 512])}Mi",
                    ),
                )
            )
    return build_cluster(pods, _uniform_nodes(n_nodes), pgs, [build_queue("default")])


def multi_queue(
    n_pods: int = 10_000, n_nodes: int = 1000, n_queues: int = 8, tasks_per_job: int = 20, seed: int = 0
) -> ClusterInfo:
    """Config 3: multi-queue gang mix (proportion-weighted queues)."""
    rng = random.Random(seed)
    queues = [build_queue(f"q{i}", weight=rng.randint(1, 4)) for i in range(n_queues)]
    for i, q in enumerate(queues):
        q.metadata.creation_timestamp = float(i)
    pods, pgs = [], []
    n_jobs = max(n_pods // tasks_per_job, 1)
    for j in range(n_jobs):
        name = f"job-{j:05d}"
        queue = queues[j % n_queues].name
        pgs.append(build_pod_group(name, queue=queue, min_member=tasks_per_job))
        for t in range(tasks_per_job):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu=f"{rng.choice([250, 500, 1000])}m",
                        memory=f"{rng.choice([256, 512, 1024])}Mi",
                    ),
                )
            )
    return build_cluster(pods, _uniform_nodes(n_nodes), pgs, queues)


def preempt_mix(
    n_pods: int = 50_000, n_nodes: int = 5000, tasks_per_job: int = 25, seed: int = 0
) -> ClusterInfo:
    """Config 4: the north-star scale — 50k pending across priority bands
    on 5k nodes partially occupied by running (and some terminating)
    victims."""
    rng = random.Random(seed)
    nodes = _uniform_nodes(n_nodes)
    pods, pgs = [], []
    # ~25% of each node pre-occupied by low-priority residents.
    for i in range(0, n_nodes, 2):
        pod = build_pod(
            name=f"victim-{i:05d}",
            node_name=f"node-{i:05d}",
            phase=PodPhase.RUNNING,
            req=build_resource_list(cpu=4, memory="8192Mi"),
            priority=1,
        )
        if rng.random() < 0.1:
            pod.metadata.deletion_timestamp = 1.0
        pods.append(pod)
    n_jobs = max(n_pods // tasks_per_job, 1)
    for j in range(n_jobs):
        name = f"job-{j:05d}"
        pgs.append(build_pod_group(name, min_member=max(tasks_per_job // 2, 1)))
        prio = rng.choice([1, 5, 9])
        for t in range(tasks_per_job):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu=f"{rng.choice([250, 500])}m", memory=f"{rng.choice([512, 1024])}Mi"
                    ),
                    priority=prio,
                )
            )
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


def multi_tenant_ml(
    n_jobs: int = 200, n_nodes: int = 500, n_queues: int = 100, seed: int = 0
) -> ClusterInfo:
    """Config 5: Kubeflow TFJob/MPIJob-shaped gangs — a small PS/launcher
    plus GPU or TPU workers — across many tenant queues."""
    rng = random.Random(seed)
    queues = [build_queue(f"tenant-{i:03d}", weight=rng.randint(1, 8)) for i in range(n_queues)]
    for i, q in enumerate(queues):
        q.metadata.creation_timestamp = float(i)
    nodes = []
    for i in range(n_nodes):
        rl = build_resource_list(cpu=32, memory="131072Mi", pods=110)
        if i % 2 == 0:
            rl[GPU] = 8.0
        else:
            rl[TPU] = 4.0
        nodes.append(build_node(f"node-{i:05d}", rl))
    pods, pgs = [], []
    for j in range(n_jobs):
        name = f"tfjob-{j:04d}"
        queue = queues[j % n_queues].name
        n_workers = rng.choice([2, 4, 8])
        accel = GPU if rng.random() < 0.5 else TPU
        pgs.append(build_pod_group(name, queue=queue, min_member=1 + n_workers))
        pods.append(
            build_pod(
                name=f"{name}-ps",
                group_name=name,
                req=build_resource_list(cpu=2, memory="4096Mi"),
            )
        )
        for w in range(n_workers):
            rl = build_resource_list(cpu=4, memory="16384Mi")
            rl[accel] = float(rng.choice([1, 2, 4]))
            pods.append(build_pod(name=f"{name}-worker-{w}", group_name=name, req=rl))
    return build_cluster(pods, nodes, pgs, queues)


def preempt_contended(
    n_nodes: int = 200, victim_tasks: int = 4, n_preemptor_jobs: int = 150,
    tasks_per_job: int = 4, seed: int = 0
) -> ClusterInfo:
    """A preemption-heavy scene for benching the preempt actions: every
    node slot held by low-priority gang members, higher-priority gangs
    starved behind them (the preempt.go:81-170 working set)."""
    rng = random.Random(seed)
    nodes = [
        build_node(f"node-{i:05d}", build_resource_list(cpu=2, memory="4096Mi", pods=10))
        for i in range(n_nodes)
    ]
    pods, pgs = [], []
    slots = [(i, s) for i in range(n_nodes) for s in range(2)]
    si = 0
    j = 0
    while si < len(slots):
        name = f"low-{j:04d}"
        pgs.append(build_pod_group(name, min_member=0))
        for t in range(victim_tasks):
            if si >= len(slots):
                break
            node_i, _ = slots[si]
            si += 1
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=f"node-{node_i:05d}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="2048Mi"),
                    priority=1,
                )
            )
        j += 1
    for j in range(n_preemptor_jobs):
        name = f"high-{j:04d}"
        pgs.append(build_pod_group(name, min_member=max(tasks_per_job // 2, 1)))
        for t in range(tasks_per_job):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu=1, memory=f"{rng.choice([1024, 2048])}Mi"
                    ),
                    priority=9,
                )
            )
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


def uniform_pool(
    n_pods: int = 400_000, n_nodes: int = 40_000, tasks_per_job: int = 250,
    churn: float = 0.0, churn_salt: int = 0,
) -> ClusterInfo:
    """Config 7: the node-class compression headline (ISSUE 20) — an
    interchangeable-fleet pool with pod-slice-sized gangs (250 tasks,
    the large-training shape this scheduler targets). Every node is
    byte-identical to the encoder (same shape, no labels, no residents)
    and the gangs cycle through two request shapes, so the solver's
    node axis folds to a handful of equivalence classes and the
    compressed solve cost is bounded by class count, not fleet size.

    ``churn > 0`` plants a RUNNING resident on every ``1/churn``-th node
    with one of 64 request shapes picked from ``churn_salt`` — the ~1%
    of a real fleet that differs from the pool at any moment. Varying
    the salt session to session moves WHICH nodes differ (and the exact
    class count) without moving the class axis' power-of-two bucket,
    which is what the bench's zero-recompile churn row measures."""
    nodes = _uniform_nodes(n_nodes)
    pods, pgs = [], []
    if churn > 0.0:
        step = max(int(1.0 / churn), 1)
        for i in range(0, n_nodes, step):
            v = (i * 31 + churn_salt * 7919) % 64
            pods.append(
                build_pod(
                    name=f"churn-{churn_salt:03d}-{i:05d}",
                    node_name=f"node-{i:05d}",
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(
                        cpu=f"{100 + 25 * (v % 8)}m",
                        memory=f"{256 + 64 * (v // 8)}Mi",
                    ),
                )
            )
    n_jobs = max(n_pods // tasks_per_job, 1)
    for j in range(n_jobs):
        name = f"job-{j:05d}"
        pgs.append(build_pod_group(name, min_member=max(tasks_per_job // 2, 1)))
        small = j % 2 == 0
        for t in range(tasks_per_job):
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    req=build_resource_list(
                        cpu="250m" if small else "500m",
                        memory="512Mi" if small else "1024Mi",
                    ),
                )
            )
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


def besteffort_mix(
    n_pods: int = 2000, n_nodes: int = 1000, seed: int = 0
) -> ClusterInfo:
    """A backfill-heavy scene: zero-request (BestEffort) pods with mixed
    selector/toleration shapes over a labeled fleet whose nodes hold few
    pods — the backfill.go:41-76 working set in the regime where the
    serial first-fit walk degrades: early nodes fill to their pod
    capacity (the reference's own "pod hole" TODO), so every later task
    re-scans the full prefix, and zone-selector pods whose zone sits
    late in node-name order walk most of the cluster per task. Verdict
    dedup makes the vectorized scan O(groups x nodes + tasks) instead of
    O(tasks x nodes)."""
    rng = random.Random(seed)
    zones = ["a", "b", "c", "d"]
    nodes = []
    block = max(n_nodes // len(zones), 1)
    for i in range(n_nodes):
        # contiguous zone blocks: zone "d" occupies the name-order tail
        node = build_node(
            f"node-{i:05d}",
            build_resource_list(cpu=4, memory="8192Mi", pods=8),
            labels={"zone": zones[min(i // block, len(zones) - 1)]},
        )
        if i % 17 == 0:
            node.taints.append(Taint(key="dedicated", effect="NoSchedule"))
        nodes.append(node)
    pods, pgs = [], []
    n_jobs = max(n_pods // 20, 1)
    for j in range(n_jobs):
        name = f"be-{j:04d}"
        pgs.append(build_pod_group(name, min_member=1))
        for t in range(20):
            pod = build_pod(name=f"{name}-t{t}", group_name=name)
            shape = rng.random()
            if shape < 0.3:
                # selector pods biased to the tail zones: the serial walk
                # rejects the whole name-order prefix every time
                pod.node_selector["zone"] = rng.choice(["c", "d", "d", "d"])
            elif shape < 0.4:
                pod.tolerations.append(
                    Toleration(key="dedicated", operator="Exists")
                )
            pods.append(pod)
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


CONFIGS = {
    "gang_example": gang_example,
    "synthetic_1k_100": lambda: synthetic(1000, 100),
    "multi_queue_10k_1k": lambda: multi_queue(10_000, 1000),
    "preempt_50k_5k": lambda: preempt_mix(50_000, 5000),
    "multi_tenant_ml": lambda: multi_tenant_ml(),
    "besteffort_2k_1k": lambda: besteffort_mix(2000, 1000),
    "uniform_pool_50k_5k": lambda: uniform_pool(50_000, 5000),
}
