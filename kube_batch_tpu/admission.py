"""Per-tenant admission lanes with fleet-SLO backpressure.

The workload-API front door (``POST /apis/v1alpha1/pods``) is the one
place overload can still be refused cheaply: once a pod is in the store
it holds watch bandwidth, mirror memory and scheduler cycles on every
shard. This module puts an admission control plane there:

- **Lanes** — every tenant queue maps to a lane with a token-bucket
  rate limit, a priority tier and a bounded backlog (admitted but not
  yet bound). Overflow is *rejected loudly* — HTTP 429 with a
  ``Retry-After`` hint — never silently dropped or queued unbounded.
- **Backpressure controller** — a hysteresis-banded feedback loop over
  *measured* fleet state: the merged ``fleet_slo_*`` p99 sketches,
  ``fleet_backlog_pods``, the node-conflict heatmap and
  ``watch_snapshot_age_seconds``. Under sustained pressure it walks a
  **brownout ladder**: lowest-priority lanes are halved, then deferred
  outright, tier by tier, so the protected (highest-priority) lane's
  p99 stays bounded while lower tiers degrade predictably. Recovery
  retraces the ladder with a wider hysteresis band and a longer dwell,
  so the controller does not flap around the set point.
- **Dark shards** — when the fleet aggregator reports a shard down
  (``fleet_shard_up=0``) the fleet signals are *incomplete*, so the
  controller holds its current brownout level (the conservative read:
  no recovery on partial data) instead of treating silence as health.

Configuration is environment-first (``KBT_ADMISSION`` holds the lane
spec; everything defaults sanely) so the drill rigs and the server wire
through the same switch. The module is also its own proof: ``python -m
kube_batch_tpu.admission`` runs a deterministic overload plant (5x
offered load; admission ON must keep the protected lane's p99 bounded
where OFF collapses), and ``--storm`` runs the live storm drill over a
real federated streaming topology.

Fault points (``KBT_FAULTS``): ``admission.shed`` sheds an admit that
would have passed (429 path under test), ``admission.controller`` kills
a controller tick (fail-static: last good outputs stay in force).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.obs import _OFF_WORDS
from kube_batch_tpu.obs import fleet as obs_fleet

__all__ = [
    "ENV",
    "DEFAULT_SPEC",
    "TokenBucket",
    "LaneSpec",
    "Decision",
    "BackpressureController",
    "AdmissionGate",
    "parse_lane_specs",
    "configure",
    "enabled",
    "active",
    "decide",
    "note_done",
    "release",
    "publish",
    "debug_payload",
    "smoke",
    "storm",
    "main",
]

ENV = "KBT_ADMISSION"
RATE_ENV = "KBT_ADMISSION_RATE"
BURST_ENV = "KBT_ADMISSION_BURST"
BACKLOG_ENV = "KBT_ADMISSION_BACKLOG"
SLO_ENV = "KBT_ADMISSION_P99_SLO_S"
BAND_ENV = "KBT_ADMISSION_BAND"
INTERVAL_ENV = "KBT_ADMISSION_INTERVAL_S"
MIN_RATE_ENV = "KBT_ADMISSION_MIN_RATE"

# Bare on-words ("1", "on", ...) arm this default lane map: a protected
# high tier, a deferrable batch tier, and the catch-all "default" lane
# (every queue without its own lane lands there) as the first brownout
# victim.
DEFAULT_SPEC = "high:100,batch:10,default:0"


def _env_float(name: str, default: float, floor: Optional[float] = None) -> float:
    try:
        value = float(os.environ.get(name, "") or default)
    except ValueError:
        value = default
    if floor is not None:
        value = max(floor, value)
    return value


def default_rate() -> float:
    """Per-lane steady-state admit rate (pods/s) when the lane spec
    does not pin one."""
    return _env_float(RATE_ENV, 50.0, floor=0.1)


def default_burst() -> float:
    """Per-lane burst allowance (bucket depth); defaults to one
    second's worth of the lane rate."""
    return _env_float(BURST_ENV, 0.0, floor=0.0)


def default_backlog() -> int:
    """Per-lane cap on admitted-but-not-yet-bound pods."""
    return int(_env_float(BACKLOG_ENV, 200.0, floor=1.0))


def p99_slo_s() -> float:
    """The protected-lane time-to-bind p99 objective the controller
    steers to."""
    return _env_float(SLO_ENV, 30.0, floor=0.1)


def hysteresis_band() -> float:
    """Dead band around pressure 1.0: escalate above ``1 + band``,
    recover below ``1 - band``, hold in between."""
    return min(0.9, _env_float(BAND_ENV, 0.2, floor=0.01))


def controller_interval_s() -> float:
    """Seconds between controller ticks."""
    return _env_float(INTERVAL_ENV, 1.0, floor=0.05)


def min_rate_factor() -> float:
    """Rate factor of a fully deferred (browned-out) lane; 0 closes the
    lane entirely until recovery."""
    return max(0.0, min(1.0, _env_float(MIN_RATE_ENV, 0.0, floor=0.0)))


# -- token bucket -------------------------------------------------------------


class TokenBucket:
    """Classic token bucket with an injectable clock (the drills run on
    a fake clock). ``rate <= 0`` means closed: takes fail with a fixed
    retry hint instead of a division."""

    def __init__(self, rate: float, burst: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._clock = clock
        self._tokens = self.burst
        self._stamp = clock()

    def _refill(self) -> None:
        now = self._clock()
        if self.rate > 0 and now > self._stamp:
            self._tokens = min(self.burst, self._tokens + (now - self._stamp) * self.rate)
        self._stamp = now

    def set_rate(self, rate: float) -> None:
        self._refill()  # settle accrual at the old rate first
        self.rate = float(rate)

    def take(self, n: float = 1.0) -> bool:
        self._refill()
        if self.rate <= 0:
            return False
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def retry_after(self) -> float:
        """Seconds until a take would plausibly succeed — the 429
        ``Retry-After`` hint. Always > 0 on the shed path."""
        if self.rate <= 0:
            return 1.0
        self._refill()
        return max(0.05, (1.0 - self._tokens) / self.rate)


# -- lanes --------------------------------------------------------------------


@dataclass(frozen=True)
class LaneSpec:
    name: str
    priority: int = 0
    rate: float = 0.0       # 0 -> default_rate()
    burst: float = 0.0      # 0 -> max(rate, default_burst())
    backlog: int = 0        # 0 -> default_backlog()


def parse_lane_specs(raw: str) -> list[LaneSpec]:
    """Parse the ``KBT_ADMISSION`` lane spec: comma-separated
    ``name:priority[:rate[:burst[:backlog]]]`` entries. Malformed
    fields fall back to defaults rather than disabling admission."""
    specs: list[LaneSpec] = []
    seen: set[str] = set()
    for chunk in raw.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        parts = chunk.split(":")
        name = parts[0].strip()
        if not name or name in seen:
            continue
        seen.add(name)

        def _num(i: int, cast, default):
            try:
                return cast(parts[i])
            except (IndexError, ValueError):
                return default

        specs.append(LaneSpec(
            name=name,
            priority=_num(1, int, 0),
            rate=_num(2, float, 0.0),
            burst=_num(3, float, 0.0),
            backlog=_num(4, int, 0),
        ))
    if specs and not any(s.name == "default" for s in specs):
        lowest = min(s.priority for s in specs)
        specs.append(LaneSpec(name="default", priority=lowest))
    return specs


class _Lane:
    """Runtime state behind a LaneSpec: the bucket, the in-flight count
    (admitted, not yet bound) and the controller-assigned rate factor."""

    def __init__(self, spec: LaneSpec, clock: Callable[[], float]) -> None:
        self.spec = spec
        self.rate = spec.rate if spec.rate > 0 else default_rate()
        self.burst = spec.burst if spec.burst > 0 else max(self.rate, default_burst())
        self.backlog_limit = spec.backlog if spec.backlog > 0 else default_backlog()
        self.bucket = TokenBucket(self.rate, self.burst, clock)
        self.factor = 1.0
        self.inflight = 0
        self.admitted = 0
        self.shed: dict[str, int] = {}

    def apply_factor(self, factor: float) -> None:
        if factor != self.factor:
            self.factor = factor
            self.bucket.set_rate(self.rate * factor)

    def snapshot(self) -> dict:
        return {
            "priority": self.spec.priority,
            "rate": self.rate,
            "burst": self.burst,
            "backlog_limit": self.backlog_limit,
            "factor": self.factor,
            "inflight": self.inflight,
            "admitted": self.admitted,
            "shed": dict(self.shed),
        }


@dataclass(frozen=True)
class Decision:
    admitted: bool
    lane: str
    reason: str                 # admitted | shed_rate | shed_backlog | shed_brownout | shed_fault
    retry_after_s: float = 0.0  # > 0 on every shed


# -- backpressure controller --------------------------------------------------


class BackpressureController:
    """Hysteresis-banded brownout ladder over measured fleet state.

    Pressure is the worst of four normalized signals — protected-lane
    p99 / SLO, fleet backlog / aggregate lane backlog budget, watch
    snapshot age / 10s, and the node-conflict heatmap mass / 50 — so
    any one saturating subsystem is enough to start shedding load.

    The ladder has two rungs per deferrable priority tier, lowest tier
    first: *half* the tier's admit rate, then *defer* it outright
    (``min_rate_factor``). The top tier is never deferred — protecting
    its p99 is the controller's whole objective. Escalation needs
    ``UP_TICKS`` consecutive above-band ticks; recovery needs
    ``DOWN_TICKS`` below-band ticks (and no dark shard), so transient
    spikes move the ladder at most one rung and the loop cannot flap.
    """

    UP_TICKS = 2
    DOWN_TICKS = 6

    def __init__(self, specs: list[LaneSpec], slo_s: Optional[float] = None,
                 band: Optional[float] = None,
                 backlog_budget: Optional[float] = None) -> None:
        self.slo_s = slo_s if slo_s is not None else p99_slo_s()
        self.band = band if band is not None else hysteresis_band()
        tiers = sorted({s.priority for s in specs}) or [0]
        self._tiers = tiers
        self._deferrable = tiers[:-1]  # top tier is untouchable
        self.max_level = 2 * len(self._deferrable)
        by_priority = sorted(specs, key=lambda s: -s.priority)
        self.protected_queue = by_priority[0].name if by_priority else ""
        self.backlog_budget = backlog_budget or 1.0
        self.level = 0
        self.pressure = 0.0
        self.dark = False
        self.ticks = 0
        self.last_outcome = "steady"
        self._above = 0
        self._below = 0

    def factor_for(self, priority: int) -> float:
        if priority not in self._deferrable:
            return 1.0
        # rung math: each deferrable tier owns two rungs, lowest first
        steps = self.level - 2 * self._deferrable.index(priority)
        if steps >= 2:
            return min_rate_factor()
        if steps == 1:
            return 0.5
        return 1.0

    def _read_pressure(self, payload: dict, watch_age: float,
                       inflight_total: int) -> tuple[float, bool]:
        slo = payload.get("slo") or {}
        ttb = slo.get("time_to_bind") or {}
        stats = ttb.get(self.protected_queue)
        if stats is None and ttb:
            p99 = max(float(s.get("p99") or 0.0) for s in ttb.values())
        else:
            p99 = float((stats or {}).get("p99") or 0.0)
        backlog = max(float(payload.get("backlog_pods") or 0.0),
                      float(inflight_total))
        conflicts = sum((payload.get("node_conflict_topk") or {}).values())
        pressure = max(
            p99 / self.slo_s,
            backlog / max(1.0, self.backlog_budget),
            max(0.0, watch_age) / 10.0,
            float(conflicts) / 50.0,
        )
        shard_up = payload.get("shard_up") or {}
        dark = bool(shard_up) and not all(shard_up.values())
        return pressure, dark

    def tick(self, payload: dict, watch_age: float,
             inflight_total: int = 0) -> str:
        """One control step. Returns the tick outcome (also counted in
        ``admission_controller_ticks``)."""
        self.ticks += 1
        if faults.should_fire("admission.controller"):
            # fail-static: a dead controller must not move the ladder —
            # the last good per-lane factors stay in force
            self.last_outcome = "fault"
            return "fault"
        pressure, dark = self._read_pressure(payload, watch_age, inflight_total)
        self.pressure = pressure
        self.dark = dark
        outcome = "steady"
        if pressure > 1.0 + self.band:
            self._above += 1
            self._below = 0
            if self._above >= self.UP_TICKS and self.level < self.max_level:
                self.level += 1
                self._above = 0
                outcome = "escalate"
        elif pressure < 1.0 - self.band:
            self._above = 0
            if dark:
                # incomplete fleet data: hold the line, don't recover
                self._below = 0
                outcome = "dark"
            else:
                self._below += 1
                if self._below >= self.DOWN_TICKS and self.level > 0:
                    self.level -= 1
                    self._below = 0
                    outcome = "recover"
        else:
            self._above = 0
            self._below = 0
            if dark:
                outcome = "dark"
        self.last_outcome = outcome
        return outcome

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "max_level": self.max_level,
            "pressure": round(self.pressure, 4),
            "dark": self.dark,
            "ticks": self.ticks,
            "last_outcome": self.last_outcome,
            "protected_queue": self.protected_queue,
            "slo_s": self.slo_s,
            "band": self.band,
        }


# -- the gate -----------------------------------------------------------------


class AdmissionGate:
    """The front-door decision point. One lock guards lanes and the
    controller; ``decide`` is called on HTTP handler threads."""

    def __init__(self, specs: list[LaneSpec],
                 clock: Callable[[], float] = time.monotonic,
                 fleet_fn: Optional[Callable[[], dict]] = None,
                 age_fn: Optional[Callable[[], float]] = None,
                 slo_s: Optional[float] = None,
                 band: Optional[float] = None,
                 interval_s: Optional[float] = None) -> None:
        if not specs:
            raise ValueError("admission gate needs at least one lane")
        self._clock = clock
        self._lock = threading.RLock()
        self.lanes: dict[str, _Lane] = {
            s.name: _Lane(s, clock) for s in specs
        }
        if "default" not in self.lanes:
            self.lanes["default"] = _Lane(
                LaneSpec("default", min(s.priority for s in specs)), clock
            )
        self.controller = BackpressureController(
            [lane.spec for lane in self.lanes.values()],
            slo_s=slo_s, band=band,
            backlog_budget=sum(l.backlog_limit for l in self.lanes.values()),
        )
        self.interval_s = interval_s if interval_s is not None else controller_interval_s()
        self._fleet_fn = fleet_fn if fleet_fn is not None else obs_fleet.refresh
        self._age_fn = (
            age_fn if age_fn is not None
            else (lambda: metrics.watch_snapshot_age.value())
        )
        self._last_tick = clock()  #: guarded_by _lock
        self._inflight_keys: dict[str, str] = {}  #: guarded_by _lock

    # -- controller plumbing --------------------------------------------------

    def maybe_tick(self) -> None:
        with self._lock:
            now = self._clock()
            if now - self._last_tick < self.interval_s:
                return
            self._last_tick = now
            try:
                payload = self._fleet_fn() or {}
            except Exception as e:  # a broken signal source is not an outage
                log.errorf("admission: fleet signal source failed: %s", e)
                payload = {}
            try:
                age = float(self._age_fn())
            except Exception:
                age = 0.0
            inflight = sum(l.inflight for l in self.lanes.values())
            outcome = self.controller.tick(payload, age, inflight)
            if outcome != "fault":
                for lane in self.lanes.values():
                    lane.apply_factor(self.controller.factor_for(lane.spec.priority))
            metrics.register_admission_controller_tick(outcome)
            metrics.set_admission_brownout_level(self.controller.level)
            metrics.set_admission_pressure(self.controller.pressure)
            for name, lane in self.lanes.items():
                metrics.set_admission_lane_rate(name, lane.rate * lane.factor)
                metrics.set_admission_lane_backlog(name, lane.inflight)

    # -- the decision ---------------------------------------------------------

    def lane_for(self, queue: str) -> _Lane:
        return self.lanes.get(queue) or self.lanes["default"]

    def decide(self, queue: str, key: Optional[str] = None) -> Decision:
        self.maybe_tick()
        with self._lock:
            lane = self.lane_for(queue)
            name = lane.spec.name
            deferred = (
                lane.spec.priority in self.controller._deferrable
                and lane.factor <= min_rate_factor()
            )
            if deferred:
                decision = Decision(False, name, "shed_brownout",
                                    max(1.0, 2 * self.interval_s))
            elif lane.inflight >= lane.backlog_limit:
                decision = Decision(False, name, "shed_backlog",
                                    max(0.5, lane.bucket.retry_after()))
            elif not lane.bucket.take():
                decision = Decision(False, name, "shed_rate",
                                    lane.bucket.retry_after())
            elif faults.should_fire("admission.shed"):
                decision = Decision(False, name, "shed_fault", 1.0)
            else:
                lane.inflight += 1
                lane.admitted += 1
                if key:
                    self._inflight_keys[key] = name
                decision = Decision(True, name, "admitted")
            if not decision.admitted:
                lane.shed[decision.reason] = lane.shed.get(decision.reason, 0) + 1
        metrics.register_admission_decision(name, decision.reason)
        return decision

    def note_done(self, key: str) -> None:
        """Credit a lane when an admitted pod binds (or is deleted while
        pending) — the backlog bound tracks admitted-but-not-yet-bound."""
        with self._lock:
            name = self._inflight_keys.pop(key, None)
            if name is None:
                return
            lane = self.lanes.get(name)
            if lane is not None and lane.inflight > 0:
                lane.inflight -= 1

    def release(self, key: str) -> None:
        """Roll back an admit whose create failed downstream."""
        self.note_done(key)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "interval_s": self.interval_s,
                "lanes": {n: l.snapshot() for n, l in self.lanes.items()},
                "controller": self.controller.snapshot(),
            }


# -- module state (the server's switch) ---------------------------------------


_gate: Optional[AdmissionGate] = None
NOOP_PAYLOAD: dict = {"enabled": False}


def enabled() -> bool:
    return _gate is not None


def active() -> Optional[AdmissionGate]:
    return _gate


def configure(spec=None) -> bool:
    """(Re)resolve the admission switch from ``KBT_ADMISSION`` (or an
    explicit spec string). On-words arm ``DEFAULT_SPEC``; anything with
    a colon is a lane spec; off-words/empty disable. Mirrors
    obs_fleet.configure so the server arms both the same way."""
    global _gate
    raw = (os.environ.get(ENV, "") if spec is None else str(spec)).strip()
    if not raw or raw.lower() in _OFF_WORDS:
        if _gate is not None:
            log.infof("admission control disabled")
        _gate = None
        return False
    if ":" not in raw:
        raw = DEFAULT_SPEC
    specs = parse_lane_specs(raw)
    if not specs:
        _gate = None
        return False
    _gate = AdmissionGate(specs)
    log.infof(
        "admission control enabled: %d lanes (%s), brownout ladder %d rungs",
        len(_gate.lanes), ", ".join(sorted(_gate.lanes)),
        _gate.controller.max_level,
    )
    return True


def decide(queue: str, key: Optional[str] = None) -> Optional[Decision]:
    """Front-door hook: None when admission is off (admit everything)."""
    gate = _gate
    if gate is None:
        return None
    return gate.decide(queue, key)


def note_done(key: str) -> None:
    gate = _gate
    if gate is not None:
        gate.note_done(key)


def release(key: str) -> None:
    gate = _gate
    if gate is not None:
        gate.release(key)


def publish() -> None:
    """Refresh the admission gauges (the /metrics scrape path)."""
    gate = _gate
    if gate is not None:
        gate.maybe_tick()


def debug_payload() -> dict:
    """The ``/debug/admission`` body."""
    gate = _gate
    if gate is None:
        return NOOP_PAYLOAD
    return gate.snapshot()


# -- smoke: deterministic overload plant --------------------------------------


SMOKE_SPEC = (
    "high:100:40:40:200,batch:10:40:40:200,low:0:40:40:200"
)


def smoke(duration_s: float = 40.0, seed: int = 42) -> dict:
    """Deterministic admission proof (``python -m kube_batch_tpu
    .admission``, the hack/verify.py ``admission_smoke`` gate).

    A fake-clock FIFO plant serves 40 pods/s; three tenants offer 200
    pods/s total (5x capacity): ``high`` 20/s, ``batch`` 60/s, ``low``
    120/s. The plant has *no* internal priority — whatever gets in
    queues FIFO — so any protection the high tenant enjoys must come
    from the admission plane. Run twice on the same seed:

    - **admission ON**: the controller walks the brownout ladder until
      inflow fits capacity. Asserts the high lane is never shed, the
      low lane is, the served p99 settles within a small multiple of
      the SLO, the ladder does not flap in the settled tail, every shed
      carried a positive Retry-After, and the controller actually
      ticked.
    - **admission OFF**: the same offered load admitted wholesale must
      measurably collapse (served p99 many times the SLO) — the
      controller has to be *why* the ON run stays bounded.
    """
    import random

    slo_s = 2.0
    capacity = 40.0
    dt = 0.02
    offered = (("high", 20.0), ("batch", 60.0), ("low", 120.0))

    def run(admission_on: bool) -> dict:
        rng = random.Random(seed)
        clock = [0.0]
        specs = parse_lane_specs(SMOKE_SPEC)
        fleet_state: dict = {"payload": {"enabled": False}}
        gate = AdmissionGate(
            specs,
            clock=lambda: clock[0],
            fleet_fn=lambda: fleet_state["payload"],
            age_fn=lambda: 0.0,
            slo_s=slo_s, band=0.2, interval_s=0.5,
        ) if admission_on else None
        # per-lane next-arrival times (independent Poisson processes)
        next_at = {name: rng.expovariate(rate) for name, rate in offered}
        queue: list[tuple[str, float, str]] = []  # (key, admit_time, lane)
        served: list[tuple[float, float, str]] = []  # (done_time, latency, lane)
        budget = 0.0
        counts = {name: {"offered": 0, "admitted": 0, "shed": 0}
                  for name, _ in offered}
        min_retry = None
        levels: list[tuple[float, int]] = []
        seq = 0
        steps = int(duration_s / dt)
        for _ in range(steps):
            clock[0] += dt
            now = clock[0]
            # arrivals
            for name, rate in offered:
                while next_at[name] <= now:
                    next_at[name] += rng.expovariate(rate)
                    seq += 1
                    key = f"{name}-{seq}"
                    counts[name]["offered"] += 1
                    if gate is None:
                        queue.append((key, now, name))
                        continue
                    decision = gate.decide(name, key)
                    if decision.admitted:
                        counts[name]["admitted"] += 1
                        queue.append((key, now, name))
                    else:
                        counts[name]["shed"] += 1
                        retry = decision.retry_after_s
                        min_retry = retry if min_retry is None else min(min_retry, retry)
            # FIFO service at fixed capacity
            budget += capacity * dt
            while budget >= 1.0 and queue:
                budget -= 1.0
                key, t0, name = queue.pop(0)
                served.append((now, now - t0, name))
                if gate is not None:
                    gate.note_done(key)
            if gate is not None:
                # the plant *is* the fleet: synthesize the merged payload
                window = [s for s in served if now - s[0] <= 5.0]
                lats = sorted(s[1] for s in window)
                p99 = lats[max(0, int(len(lats) * 0.99) - 1)] if lats else 0.0
                fleet_state["payload"] = {
                    "enabled": True,
                    "slo": {"time_to_bind": {"high": {"n": len(lats), "p99": p99}}},
                    "backlog_pods": 0.0,  # inflight feeds the backlog term
                    "shard_up": {"s0": True},
                    "node_conflict_topk": {},
                }
                gate.maybe_tick()
                if not levels or levels[-1][1] != gate.controller.level:
                    levels.append((now, gate.controller.level))
        tail_start = duration_s * 2.0 / 3.0
        tail = [lat for done, lat, _ in served if done >= tail_start]
        tail.sort()
        tail_p99 = tail[max(0, int(len(tail) * 0.99) - 1)] if tail else 0.0
        return {
            "counts": counts,
            "tail_p99_s": round(tail_p99, 3),
            "queue_final": len(queue),
            "min_retry_after_s": min_retry,
            "level_changes_tail": sum(1 for t, _ in levels if t >= tail_start),
            "level_final": levels[-1][1] if levels else 0,
            "ticks": gate.controller.ticks if gate else 0,
            "served": len(served),
        }

    on = run(True)
    off = run(False)
    ok = bool(
        on["ticks"] > 0
        and on["counts"]["high"]["shed"] == 0
        and on["counts"]["high"]["admitted"] == on["counts"]["high"]["offered"]
        and on["counts"]["low"]["shed"] > 0
        and (on["min_retry_after_s"] or 0) > 0
        and on["tail_p99_s"] <= slo_s * 3.0
        and on["level_changes_tail"] <= 4
        and off["tail_p99_s"] >= slo_s * 5.0
        and off["tail_p99_s"] > 3.0 * max(on["tail_p99_s"], 0.001)
    )
    return {
        "ok": ok,
        "slo_s": slo_s,
        "offered_pods_per_s": sum(rate for _, rate in offered),
        "capacity_pods_per_s": capacity,
        "on": on,
        "off": off,
    }


# -- storm: live overload drill over a federated streaming topology ----------


STORM_SPEC = "high:100:12:12:120,batch:10:10:10:120,low:0:10:10:120"


def storm(
    shards: int = 2,
    nodes: int = 4,
    duration_s: float = 8.0,
    kill: bool = False,
    admission_on: bool = True,
    seed: int = 7,
) -> dict:
    """Live storm cell: N streaming federated shards over one store
    server, an open-loop Poisson arrival storm at ~5x service capacity
    POSTing through the real workload API (admission gate in the door),
    a reaper recycling bound pods (sustained throughput), node churn,
    and optionally a SIGKILL'd shard mid-storm (leased slots + survivor
    adoption + MTTR, exactly-once, fsck-clean, zero journal orphans).

    Invariant-gated: throughput/latency numbers are measured output for
    the bench row; ``ok`` only checks correctness invariants plus the
    protected lane's p99 bound when admission is ON.
    """
    import json as _json
    import random
    import tempfile
    import urllib.request

    from kube_batch_tpu.apis.types import GROUP_NAME_ANNOTATION_KEY
    from kube_batch_tpu.cache import EventHandler, LoopbackBackend
    from kube_batch_tpu.cache.store import PODS, POD_GROUPS
    from kube_batch_tpu.federation import (
        FederatedCache, ShardSlotManager, fsck, shard_index,
        shard_journal_path, shard_key_of,
    )
    from kube_batch_tpu.recovery import WriteIntentJournal
    from kube_batch_tpu.obs import QuantileSketch
    from kube_batch_tpu.ops import encode_cache
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer
    from kube_batch_tpu.streaming import SMOKE_CONF
    from kube_batch_tpu.testing import build_node, build_queue, build_resource_list

    lane_rates = (("high", 6.0), ("batch", 14.0), ("low", 20.0))
    run_s = 0.8           # bound-pod dwell before the reaper recycles it
    saved_env = {k: os.environ.get(k) for k in
                 (ENV, SLO_ENV, INTERVAL_ENV, BAND_ENV)}
    os.environ[SLO_ENV] = "2.0"
    os.environ[INTERVAL_ENV] = "0.5"
    os.environ[BAND_ENV] = "0.2"
    if admission_on:
        os.environ[ENV] = STORM_SPEC
    else:
        os.environ.pop(ENV, None)
    tmpdir = tempfile.mkdtemp(prefix="kbt-storm-")
    with tempfile.NamedTemporaryFile(
        "w", suffix=".yaml", prefix="kbt-storm-", delete=False
    ) as fh:
        fh.write(SMOKE_CONF.format(streaming="true"))
        conf_path = fh.name

    server = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()
    store = server.store
    for lane, _ in lane_rates:
        store.create_queue(build_queue(lane))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=4, memory="8Gi", pods=16))
        )
    base = f"http://127.0.0.1:{server.listen_port}"

    bind_counts: dict[str, int] = {}
    latencies: dict[str, list[float]] = {lane: [] for lane, _ in lane_rates}
    bind_times: dict[str, float] = {}
    create_times: dict[str, float] = {}
    pod_lane: dict[str, str] = {}
    sketches = [QuantileSketch() for _ in range(shards)]
    state_lock = threading.Lock()
    t_kill = [None]
    first_victim_bind = [None]
    victim_slot = [0]
    binds_total = [0]

    def _on_bind(old, new) -> None:
        if old.node_name or not new.node_name:
            return
        key = f"{new.namespace}/{new.name}"
        now = time.monotonic()
        with state_lock:
            bind_counts[key] = bind_counts.get(key, 0) + 1
            binds_total[0] += 1
            bind_times[key] = now
            t0 = create_times.get(key)
            lane = pod_lane.get(key)
            if t0 is not None and lane is not None:
                latencies[lane].append(now - t0)
                slot = shard_index(shard_key_of(new, store, "gang"), shards)
                sketches[slot].add(now - t0)
                if (t_kill[0] is not None and slot == victim_slot[0]
                        and first_victim_bind[0] is None):
                    first_victim_bind[0] = now

    store.add_event_handler(PODS, EventHandler(on_update=_on_bind))
    listeners_before = encode_cache.listener_count()

    backends: list[LoopbackBackend] = []
    scheds: list[Scheduler] = []
    threads: list[threading.Thread] = []
    stops: list[threading.Event] = []
    mgrs: list = []
    stop_all = threading.Event()
    stop_reap = threading.Event()  # reaper outlives the load: it frees
    # capacity during the drain, so bound pods don't pin the cluster full
    counts = {lane: {"offered": 0, "admitted": 0, "shed": 0}
              for lane, _ in lane_rates}
    retry_ok = [True]
    seq = [0]

    def _post(path: str, body: dict) -> tuple[int, dict]:
        req = urllib.request.Request(
            base + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                return resp.status, dict(resp.headers)
        except urllib.error.HTTPError as e:
            headers = dict(e.headers)
            e.close()
            return e.code, headers

    def _arrivals() -> None:
        rng = random.Random(seed)
        total = sum(r for _, r in lane_rates)
        weights = [r / total for _, r in lane_rates]
        names = [lane for lane, _ in lane_rates]
        next_at = time.monotonic() + rng.expovariate(total)
        deadline = time.monotonic() + duration_s
        while not stop_all.is_set() and time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(0.005, next_at - now))
                continue
            next_at += rng.expovariate(total)
            lane = rng.choices(names, weights=weights)[0]
            seq[0] += 1
            name = f"st-{lane}-{seq[0]}"
            counts[lane]["offered"] += 1
            code, _ = _post(
                "/apis/v1alpha1/podgroups",
                {"name": name, "queue": lane, "min_member": 1},
            )
            if code != 201:
                continue
            key = f"default/{name}-0"
            with state_lock:
                create_times[key] = time.monotonic()
                pod_lane[key] = lane
            code, headers = _post(
                "/apis/v1alpha1/pods",
                {"name": f"{name}-0", "group": name,
                 "scheduler_name": "kube-batch-tpu",
                 "requests": {"cpu": "1", "memory": "512Mi"}},
            )
            if code == 201:
                counts[lane]["admitted"] += 1
            else:
                counts[lane]["shed"] += 1
                with state_lock:
                    create_times.pop(key, None)
                    pod_lane.pop(key, None)
                try:
                    if float(headers.get("Retry-After", "0")) <= 0:
                        retry_ok[0] = False
                except ValueError:
                    retry_ok[0] = False

    def _reaper() -> None:
        while not stop_reap.is_set():
            now = time.monotonic()
            with state_lock:
                ripe = [k for k, t in bind_times.items() if now - t >= run_s]
                for k in ripe:
                    bind_times.pop(k, None)
            for k in ripe:
                ns, name = k.split("/", 1)
                group = name.rsplit("-", 1)[0]
                try:
                    # Pods only: deleting the group races the shard
                    # schedulers' podgroup phase writes (update-of-deleted
                    # maps to HTTP 400 and aborts the whole cycle), and an
                    # empty min_member=1 group is inert for the drill.
                    store.delete(PODS, k)
                except Exception:
                    pass
            stop_reap.wait(0.1)

    def _churn() -> None:
        present = [False]
        while not stop_all.is_set():
            stop_all.wait(1.0)
            try:
                if present[0]:
                    if not any(p.node_name == "churn-n"
                               for p in store.list(PODS)):
                        store.delete_node("churn-n")
                        present[0] = False
                else:
                    store.create_node(build_node(
                        "churn-n", build_resource_list(cpu=4, memory="8Gi", pods=16)
                    ))
                    present[0] = True
            except Exception:
                pass

    result: dict = {}
    journals: list = []
    sched_threads: list[threading.Thread] = []
    victim = 0 if kill else None
    try:
        for i in range(shards):
            backend = LoopbackBackend(base)
            journal = None
            if kill:
                journal = WriteIntentJournal(shard_journal_path(tmpdir, i))
                journals.append(journal)
            cache = FederatedCache(
                backend, shard=i, shards=shards, shard_key="gang",
                staleness_fn=backend.snapshot_age, journal=journal,
            )
            cache.run()
            backend.start(period=0.02)
            backends.append(backend)
            sched = Scheduler(
                cache, scheduler_conf=conf_path, schedule_period=1.0,
            )
            scheds.append(sched)
            stop_i = threading.Event()
            stops.append(stop_i)
            if kill:
                mgr = ShardSlotManager(
                    backend, cache, identity=f"storm-{i}", lease_s=1.0,
                    renew_s=0.25, adopt=True, journal_dir=tmpdir,
                    grace_s=5.0, rebalance=0,
                    on_owned_change=(
                        lambda a, r, s=sched: s.on_owned_slots_changed(a, r)
                    ),
                )
                if not mgr.start(deadline_s=10.0):
                    raise RuntimeError(f"shard {i} never acquired its slot")
                mgrs.append(mgr)
            t = threading.Thread(
                target=sched.run, args=(stop_i,), name=f"kb-storm-{i}",
                daemon=True,
            )
            t.start()
            sched_threads.append(t)

        for fn, name in ((_arrivals, "kb-storm-arrivals"),
                         (_reaper, "kb-storm-reaper"),
                         (_churn, "kb-storm-churn")):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            threads.append(t)

        if kill:
            time.sleep(duration_s / 2.0)
            victim_slot[0] = victim
            # the "SIGKILL": stop the victim's scheduler and stop
            # renewing WITHOUT releasing — the lease must expire
            stops[victim].set()
            sched_threads[victim].join(timeout=10.0)
            with state_lock:
                t_kill[0] = time.monotonic()
            mgrs[victim].kill()
        deadline = time.monotonic() + duration_s + 1.0
        while time.monotonic() < deadline and not stop_all.is_set():
            time.sleep(0.1)
        stop_all.set()
        # drain: let admitted work finish binding before teardown
        drain_deadline = time.monotonic() + 25.0
        while time.monotonic() < drain_deadline:
            pending = [
                p for p in store.list(PODS)
                if not p.node_name and f"{p.namespace}/{p.name}" in pod_lane
            ]
            if not pending:
                break
            time.sleep(0.1)
        stuck = []
        for p in store.list(PODS):
            key = f"{p.namespace}/{p.name}"
            if p.node_name or key not in pod_lane:
                continue
            group = p.metadata.annotations.get(GROUP_NAME_ANNOTATION_KEY, "")
            pg = store.get(POD_GROUPS, f"{p.namespace}/{group}") if group else None
            stuck.append(
                f"{key} group={group or '-'} "
                f"pg={'missing' if pg is None else pg.status.phase}"
            )
        drained = not stuck
    finally:
        stop_all.set()
        stop_reap.set()
        for stop_i in stops:
            stop_i.set()
        for i, mgr in enumerate(mgrs):
            if victim is not None and i == victim:
                continue  # already killed; its lease expired
            try:
                mgr.stop(release=True)
            except Exception:
                pass
        for t in threads + sched_threads:
            t.join(timeout=10.0)
        for backend in backends:
            backend.stop()
        for sched in scheds:
            sched.cache.stop()
        for journal in journals:
            try:
                journal.close()
            except Exception:
                pass

    violations = fsck(store)
    with state_lock:
        dup_binds = {k: c for k, c in bind_counts.items() if c != 1}
        lane_p99 = {}
        for lane, lat in latencies.items():
            lat = sorted(lat)
            lane_p99[lane] = (
                round(lat[max(0, int(len(lat) * 0.99) - 1)], 3) if lat else None
            )
        bound = binds_total[0]
    merged = QuantileSketch()
    for sk in sketches:
        merged.merge(sk)
    cluster_p99 = round(merged.quantile(0.99), 3) if merged.count() else None
    mttr = None
    if kill and t_kill[0] is not None and first_victim_bind[0] is not None:
        mttr = round(first_victim_bind[0] - t_kill[0], 3)
    orphans = 0
    if kill:
        for i in range(shards):
            path = shard_journal_path(tmpdir, i)
            if os.path.exists(path):
                orphans += len(WriteIntentJournal.replay(path).orphans)
    gate_snapshot = debug_payload()
    server.stop()
    for key, value in saved_env.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value
    configure()
    import shutil
    for path in (conf_path,):
        try:
            os.unlink(path)
        except OSError:
            pass
    shutil.rmtree(tmpdir, ignore_errors=True)

    micro_cycles = sum(s.micro_cycles_run for s in scheds)
    result = {
        "admission": admission_on,
        "kill": kill,
        "shards": shards,
        "offered": {lane: c["offered"] for lane, c in counts.items()},
        "admitted": {lane: c["admitted"] for lane, c in counts.items()},
        "shed": {lane: c["shed"] for lane, c in counts.items()},
        "bound": bound,
        "pods_per_s": round(bound / duration_s, 2),
        "lane_p99_s": lane_p99,
        "cluster_p99_s": cluster_p99,
        "micro_cycles": micro_cycles,
        "mttr_s": mttr,
        "drained": drained,
        "stuck_pods": stuck[:10],
        "exactly_once": not dup_binds,
        "fsck_violations": violations,
        "journal_orphans": orphans if kill else None,
        "retry_after_present": retry_ok[0],
        "listeners_clean": encode_cache.listener_count() == listeners_before,
        "brownout_level_final": (
            (gate_snapshot.get("controller") or {}).get("level")
            if admission_on else None
        ),
    }
    ok = bool(
        result["exactly_once"]
        and not violations
        and result["drained"]
        and result["listeners_clean"]
        and result["retry_after_present"]
        and bound > 0
        and micro_cycles > 0
    )
    if admission_on and not kill:
        high = lane_p99.get("high")
        ok = ok and high is not None and high <= 5.0
        ok = ok and counts["high"]["shed"] == 0
    if kill:
        ok = ok and mttr is not None and orphans == 0
    result["ok"] = ok
    return result


def storm_row(shards: int = 2, duration_s: float = 8.0) -> dict:
    """The headline bench row: the same storm with admission ON,
    admission OFF (measured collapse), and ON + SIGKILL'd shard
    (adoption + MTTR)."""
    on = storm(shards=shards, duration_s=duration_s, admission_on=True)
    off = storm(shards=shards, duration_s=duration_s, admission_on=False)
    killed = storm(shards=shards, duration_s=duration_s, admission_on=True,
                   kill=True)
    return {
        "ok": bool(on["ok"] and killed["ok"] and off["exactly_once"]
                   and not off["fsck_violations"]),
        "on": on,
        "off": off,
        "kill": killed,
    }


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="admission control plane: deterministic 5x-overload "
        "plant (default) or the live federated storm drill (--storm)"
    )
    parser.add_argument(
        "--storm", action="store_true",
        help="run the live storm drill (on/off/kill cells) instead of "
        "the deterministic plant",
    )
    parser.add_argument("--shards", type=int, default=2)
    parser.add_argument("--duration", type=float, default=None)
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    if args.storm:
        result = storm_row(
            shards=args.shards, duration_s=args.duration or 8.0
        )
    else:
        result = smoke(duration_s=args.duration or 40.0, seed=args.seed)
    if args.json:
        print(json.dumps(result, sort_keys=True))
    elif args.storm:
        status = "ok" if result["ok"] else "FAILED"
        on, off, killed = result["on"], result["off"], result["kill"]
        print(
            f"admission storm: {status} (on: {on['pods_per_s']} pods/s, "
            f"high p99 {on['lane_p99_s'].get('high')}s, shed "
            f"{sum(on['shed'].values())}; off: high p99 "
            f"{off['lane_p99_s'].get('high')}s; kill: mttr "
            f"{killed['mttr_s']}s, orphans {killed['journal_orphans']})"
        )
    else:
        status = "ok" if result["ok"] else "FAILED"
        on, off = result["on"], result["off"]
        print(
            f"admission smoke: {status} (5x overload; on: tail p99 "
            f"{on['tail_p99_s']}s <= {result['slo_s'] * 3.0}s, high shed "
            f"{on['counts']['high']['shed']}, low shed "
            f"{on['counts']['low']['shed']}; off: tail p99 "
            f"{off['tail_p99_s']}s — collapse)"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level state would otherwise be
    # distinct from the one other modules import
    from kube_batch_tpu.admission import main as _canonical_main

    raise SystemExit(_canonical_main())
