"""Scheduler configuration schema + loader
(reference pkg/scheduler/conf/scheduler_conf.go:20-56, pkg/scheduler/util.go:31-81,
pkg/scheduler/plugins/defaults.go:22-52).

The YAML shape matches the reference exactly::

    actions: "enqueue, allocate, backfill"
    tiers:
    - plugins:
      - name: priority
      - name: gang
    - plugins:
      - name: drf
      - name: predicates
      - name: proportion
      - name: nodeorder
        arguments:
          leastrequested.weight: 2

Every per-plugin enable flag defaults to True when unset
(ApplyPluginConfDefaults).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Optional

import yaml

if TYPE_CHECKING:
    from kube_batch_tpu.framework.interface import Action

_ENABLE_FLAGS = (
    "enabled_job_order",
    "enabled_job_ready",
    "enabled_job_pipelined",
    "enabled_task_order",
    "enabled_preemptable",
    "enabled_reclaimable",
    "enabled_queue_order",
    "enabled_predicate",
    "enabled_node_order",
)

_YAML_FLAG_KEYS = {
    "enableJobOrder": "enabled_job_order",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableNodeOrder": "enabled_node_order",
}


@dataclass
class PluginOption:
    """reference scheduler_conf.go:32-56."""

    name: str = ""
    enabled_job_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    arguments: dict[str, str] = field(default_factory=dict)


@dataclass
class Tier:
    """reference scheduler_conf.go:27-30."""

    plugins: list[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConfiguration:
    """reference scheduler_conf.go:20-25, plus `action_arguments`: an
    extension the reference schema does not have (its actions take no
    conf arguments) carrying per-action knobs — e.g. xla_allocate's
    `mesh` device-mesh selection::

        actions: "enqueue, xla_allocate, backfill"
        actionArguments:
          xla_allocate:
            mesh: auto

    and ``faults``: an optional fault-injection drill spec (the same
    grammar as the ``KBT_FAULTS`` env var, see kube_batch_tpu.faults) so
    an operator can arm a failure drill with a conf push — it takes
    effect on the next cycle via the hot reload, no restart::

        faults: "bind.write:1:2,watch.drop:0.5"

    and ``streaming``: opt-in for event-driven micro-cycles between
    periodic full cycles (kube_batch_tpu.streaming; the KBT_STREAMING
    env var is the equivalent process-wide switch)::

        streaming: true

    and ``trace``: the span-tracing switch (kube_batch_tpu.obs; the
    KBT_TRACE env var is the process-wide equivalent, and an empty
    value defers to it). Hot-reloadable like ``faults`` — a conf push
    flips tracing on a live scheduler on its next cycle::

        trace: on

    and ``explain``: the unschedulability-forensics switch
    (kube_batch_tpu.obs.explain; env KBT_EXPLAIN is the process-wide
    equivalent, empty defers to it). Hot-reloadable like ``trace``::

        explain: on

    and ``fleet``: comma-separated peer base URLs for fleet-wide SLO
    aggregation (kube_batch_tpu.obs.fleet; env KBT_FLEET is the
    process-wide equivalent, empty defers to it). Hot-reloadable like
    ``trace`` — a conf push turns a live scheduler into an aggregator::

        fleet: "http://shard0:8080, http://shard1:8080"
    """

    actions: str = ""
    tiers: list[Tier] = field(default_factory=list)
    action_arguments: dict[str, dict[str, str]] = field(default_factory=dict)
    faults: str = ""
    streaming: bool = False
    trace: str = ""
    explain: str = ""
    fleet: str = ""


# Default conf (reference util.go:31-42).
DEFAULT_SCHEDULER_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def apply_plugin_conf_defaults(option: PluginOption) -> None:
    """Unset enable flags default to True (reference defaults.go:22-52)."""
    for flag in _ENABLE_FLAGS:
        if getattr(option, flag) is None:
            setattr(option, flag, True)


def parse_scheduler_conf(conf_str: str) -> SchedulerConfiguration:
    """YAML string -> SchedulerConfiguration with plugin defaults applied
    (reference util.go:44-63)."""
    data = yaml.safe_load(conf_str) or {}
    conf = SchedulerConfiguration(
        actions=str(data.get("actions", "")),
        faults=str(data.get("faults") or ""),
        streaming=bool(data.get("streaming", False)),
        trace=str(data.get("trace") if data.get("trace") is not None else ""),
        explain=str(data.get("explain") if data.get("explain") is not None else ""),
        fleet=str(data.get("fleet") if data.get("fleet") is not None else ""),
    )
    for action_name, args in (data.get("actionArguments") or {}).items():
        conf.action_arguments[str(action_name)] = {
            str(k): str(v) for k, v in (args or {}).items()
        }
    for tier_data in data.get("tiers") or []:
        tier = Tier()
        for plugin_data in tier_data.get("plugins") or []:
            option = PluginOption(name=str(plugin_data.get("name", "")))
            for yaml_key, attr in _YAML_FLAG_KEYS.items():
                if yaml_key in plugin_data:
                    setattr(option, attr, bool(plugin_data[yaml_key]))
            option.arguments = {
                str(k): str(v) for k, v in (plugin_data.get("arguments") or {}).items()
            }
            apply_plugin_conf_defaults(option)
            tier.plugins.append(option)
        conf.tiers.append(tier)
    return conf


def load_scheduler_conf(
    conf_str: str,
) -> tuple[list["Action"], list[Tier], dict[str, dict[str, str]]]:
    """YAML -> ([Action], [Tier], action_arguments); unknown action names
    raise (reference util.go:44-73). Imported lazily to avoid a framework
    import cycle."""
    from kube_batch_tpu.framework import get_action

    conf = parse_scheduler_conf(conf_str)
    actions: list["Action"] = []
    for action_name in conf.actions.split(","):
        name = action_name.strip()
        if not name:
            continue
        action = get_action(name)
        if action is None:
            raise ValueError(f"failed to find Action {name!r}")
        actions.append(action)
    return actions, conf.tiers, conf.action_arguments


def read_scheduler_conf(conf_path: str) -> str:
    """reference util.go:75-81."""
    with open(conf_path, "r", encoding="utf-8") as f:
        return f.read()
