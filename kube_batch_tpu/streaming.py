"""Streaming mode: event-driven micro-cycles on the resident world.

The classic loop (scheduler.py ``run``) sleeps out ``schedule_period``
between full cycles, so a pod that arrives right after a cycle closes
waits a whole period before anyone looks at it — the reference behaves
the same way (scheduler.go:63-86). Streaming mode replaces the sleep
with an event trigger fed by the cache's dirty feed (the same
``_notify_encode_cache`` hook that drives the incremental encoder):
when pods, podgroups, queues or nodes churn, the loop wakes immediately
and runs a **micro-cycle** — the ordinary action pipeline over a
restricted session whose

- jobs are only the dirty gangs (``cache.clone_jobs_for_stream``),
- nodes are the **resident table** harvested from the last full cycle
  (the same ``NodeInfo`` objects the session just allocated against,
  kept alive because ``close_session`` rebinds rather than clears), and
- queues are a fresh clone.

Binds dispatch through the existing statement/journal machinery, so
crash consistency (recovery/) and the cache-mutation detector hold
unchanged. Fairness plugins with cluster-wide ``on_session_open``
sweeps (drf, proportion) are filtered out of micro tiers — periodic
full cycles remain the fairness/preemption backstop, and the pinned
invariant is that micro-cycle drain + full cycles produce bind-for-bind
the same placements as full cycles alone (tests/test_streaming.py).

Failure is always degrade-never-drop: a stale resident table, an
injected ``stream.micro_cycle`` fault, or any micro error invalidates
the resident state and falls back to an immediate full cycle; the
backlog persists in the trigger until gangs actually bind.

Opt in per process with ``KBT_STREAMING=1`` or per conf file with the
``streaming: true`` key; default off.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from kube_batch_tpu import metrics, obs
from kube_batch_tpu.api.job_info import get_job_id, job_key
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.cache.store import NODES, POD_GROUPS, PODS, QUEUES
from kube_batch_tpu.conf import Tier
from kube_batch_tpu.framework import open_session

__all__ = [
    "ENV",
    "enabled",
    "MICRO_EXCLUDED_PLUGINS",
    "micro_tiers",
    "gang_key_of",
    "StreamWork",
    "StreamTrigger",
    "StreamState",
    "open_micro_session",
]

ENV = "KBT_STREAMING"

# Plugins whose on_session_open does an O(cluster) sweep to build
# fairness state (drf totals, proportion queue deserving). A micro-cycle
# solves a handful of gangs against the resident slab; recomputing
# cluster-wide share state per arrival would erase the latency win, and
# fairness/preemption corrections belong to the periodic full cycle
# anyway. Parity tests therefore compare conf files without these two.
MICRO_EXCLUDED_PLUGINS = frozenset({"drf", "proportion"})


def enabled() -> bool:
    """Process-wide streaming switch (the conf ``streaming:`` key is the
    per-file equivalent; scheduler.py honors either)."""
    return os.environ.get(ENV, "") not in ("", "0")


def micro_tiers(tiers: list[Tier]) -> list[Tier]:
    """The conf tiers minus MICRO_EXCLUDED_PLUGINS, empty tiers dropped."""
    out: list[Tier] = []
    for tier in tiers:
        kept = [p for p in tier.plugins if p.name not in MICRO_EXCLUDED_PLUGINS]
        if kept:
            out.append(Tier(plugins=kept))
    return out


def gang_key_of(pod) -> str:
    """The JobInfo uid a pod's arrival dirties: the annotated gang id,
    or the shadow-job key the cache derives for podgroup-less pods
    (cache.py ``_resolve_shadow_job``)."""
    jid = get_job_id(pod)
    if jid:
        return jid
    return job_key(pod.namespace, pod.metadata.owner_job or pod.metadata.uid)


@dataclass
class StreamWork:
    """One drained batch of churn: the dirty gang backlog (a *copy* —
    the trigger keeps gangs until they bind), pending node patches
    (latest object wins, None = deleted), bound-pod occupancy patches
    (federated absorb mode: peer binds/releases arriving through the
    shard filter as adds/deletes), and whether churn arrived that the
    resident table cannot absorb (bound-pod add/delete from outside our
    own dispatch path, when absorb mode is off)."""

    gangs: set[str] = field(default_factory=set)
    node_patches: dict[str, Optional[object]] = field(default_factory=dict)
    bound_patches: list = field(default_factory=list)
    stale: bool = False
    stale_reason: str = ""


class StreamTrigger:
    """Store-event listener + wakeup condition for the streaming loop.

    Registered on the encode-cache dirty feed (ops/encode_cache.py
    ``add_store_listener``), which cache.py calls after releasing the
    mirror mutex — handlers here may take the trigger lock safely.
    Event rules:

    - pending-pod add: stamp arrival time, dirty the gang, wake;
    - pod bind echo (node_name "" -> set): our own dispatch coming back
      through the store — close the ``time_to_bind_seconds`` loop, no
      wake (nothing new to solve);
    - pod unbind echo (set -> ""): the pod is pending again (our evict,
      or an external controller) — it is a fresh arrival;
    - pending->pending / bound->bound updates: condition/status echoes;
      the gang is already in the backlog, and waking on them would loop
      micro-cycles against an unchanged world (the unschedulable
      condition write after every failed solve would self-trigger);
    - bound-pod add or delete: capacity changed outside any session —
      the resident table is stale, force a full cycle. In **absorb
      mode** (``absorb_external=True``, federated streaming) these are
      instead recorded as bound-pod occupancy patches: a peer shard's
      bind crosses the federated pod filter as an *add* of a bound pod
      (the pending pod was a peer's, filtered out; client-go filtering
      semantics turn the transition into an add) and a peer's release
      as a *delete* — both are plain occupancy changes the resident
      ``NodeInfo`` table absorbs via add_task/remove_task, and the
      store's conditional binds remain the correctness backstop if the
      absorbed view ever lags;
    - node events: recorded as patches the next micro-cycle applies to
      the resident table; wake (new capacity can admit the backlog);
    - podgroup add or spec change: dirty the gang (min_member/queue
      edits change admission); status-only podgroup writes — every
      close_session emits one per session job — are ignored, or each
      full cycle would re-dirty the entire resident world; queue
      events: wake for re-admission.
    """

    def __init__(self, absorb_external: bool = False) -> None:
        # Federated streaming: peer shards' binds arrive as bound-pod
        # adds/deletes — absorb them as occupancy patches instead of
        # degrading to a full cycle per peer bind (which would serialize
        # every shard on everyone else's dispatch rate).
        self.absorb_external = bool(absorb_external)
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._gangs: set[str] = set()  #: guarded_by _lock
        self._bound_patches: list = []  #: guarded_by _lock
        self._node_patches: dict[str, Optional[object]] = {}  #: guarded_by _lock
        self._arrivals: dict[str, float] = {}  #: guarded_by _lock  (pod uid -> arrival stamp)
        self._queues: dict[str, str] = {}  #: guarded_by _lock  (gang key -> queue name)
        self._stale = False  #: guarded_by _lock
        self._stale_reason = ""  #: guarded_by _lock
        # _attached is loop-thread-confined (attach/detach both run on
        # the streaming loop thread), so it stays unguarded on purpose
        self._attached = False

    # -- lifecycle -----------------------------------------------------------

    def attach(self) -> None:
        from kube_batch_tpu.ops import encode_cache

        encode_cache.add_store_listener(self._on_event)
        self._attached = True

    def detach(self) -> None:
        if not self._attached:
            return
        from kube_batch_tpu.ops import encode_cache

        encode_cache.remove_store_listener(self._on_event)
        self._attached = False

    # -- the loop's side -----------------------------------------------------

    def wait(self, timeout: float) -> bool:
        return self._event.wait(timeout)

    def wake(self) -> None:
        self._event.set()

    def backlog_pods(self) -> int:
        with self._lock:
            return len(self._arrivals)

    def drain(self) -> StreamWork:
        """Snapshot the pending churn and clear the wake flag. Gangs are
        copied, not removed — only ``prune`` (called with the gangs a
        micro-cycle finished or found gone) shrinks the backlog, so a
        failed micro-cycle never loses an arrival."""
        with self._lock:
            self._event.clear()
            work = StreamWork(
                gangs=set(self._gangs),
                node_patches=self._node_patches,
                bound_patches=self._bound_patches,
                stale=self._stale,
                stale_reason=self._stale_reason,
            )
            self._node_patches = {}
            self._bound_patches = []
            self._stale = False
            self._stale_reason = ""
        return work

    def prune(self, done) -> None:
        if not done:
            return
        with self._lock:
            self._gangs.difference_update(done)

    def seed(self, keys) -> None:
        """Dirty gang keys from OUTSIDE the event feed and wake the
        loop. Shard-slot adoption uses this: an adopted slot's backlog
        arrived while another scheduler owned it, so the arrival events
        either predate this trigger or were dropped by the old filter —
        seeding makes the next micro-cycle solve exactly the adopted
        keys against the still-valid resident node table (no full-table
        invalidate, no full cycle)."""
        if not keys:
            return
        with self._lock:
            self._gangs.update(keys)
        self._event.set()

    # -- the store's side ----------------------------------------------------

    def _mark_stale(self, reason: str) -> None:
        with self._lock:
            self._stale = True
            self._stale_reason = reason
        self._event.set()

    def _on_event(self, kind: str, key: str, obj, old) -> None:
        if kind == PODS:
            self._on_pod(key, obj, old)
        elif kind == NODES:
            with self._lock:
                self._node_patches[key] = obj  # None on delete
            self._event.set()
        elif kind == POD_GROUPS:
            if obj is None:
                with self._lock:
                    self._queues.pop(key, None)
                return  # deletes resolve via clone_jobs_for_stream's missing set
            # Remember the gang's queue (key is "ns/name" == job uid) so
            # the bind echo can attribute time-to-bind to the right
            # per-queue SLO window even before any recording below.
            queue = getattr(getattr(obj, "spec", None), "queue", "") or "default"
            with self._lock:
                self._queues[key] = queue
            if old is not None and getattr(obj, "spec", None) == getattr(
                old, "spec", None
            ):
                # status-only write (phase/conditions): every cycle's
                # close_session emits these for every session job — if
                # they dirtied gangs, each full cycle would re-dirty the
                # whole resident world and the first micro after it
                # would redo a near-full solve
                return
            with self._lock:
                self._gangs.add(key)  # key is "ns/name" == job uid
            self._event.set()
        elif kind == QUEUES:
            self._event.set()

    def _on_pod(self, key: str, obj, old) -> None:
        now = time.perf_counter()
        if obj is not None and old is None:  # add
            if obj.node_name:
                if self.absorb_external:
                    # a peer shard's bind crossing the federated filter:
                    # occupancy the next micro-cycle charges to the
                    # resident table — no wake (consumed capacity admits
                    # nothing new)
                    with self._lock:
                        self._bound_patches.append(("add", key, obj))
                    return
                self._mark_stale(f"bound pod {key} appeared outside a cycle")
                return
            with self._lock:
                self._gangs.add(gang_key_of(obj))
                self._arrivals.setdefault(key, now)
                backlog = len(self._arrivals)
            metrics.set_streaming_backlog(backlog)
            self._event.set()
        elif obj is not None and old is not None:  # update
            if not old.node_name and obj.node_name:
                with self._lock:
                    t0 = self._arrivals.pop(key, None)
                    backlog = len(self._arrivals)
                    queue = self._queues.get(gang_key_of(obj), "default")
                metrics.set_streaming_backlog(backlog)
                if t0 is not None:
                    # exemplar (KBT_METRICS_EXEMPLARS): the ambient trace
                    # id links this latency sample to the micro-cycle
                    # that bound the pod ("" when tracing is off — not
                    # stored)
                    metrics.observe_time_to_bind(
                        now - t0, exemplar=obs.current_trace_id()
                    )
                    obs.slo.observe("time_to_bind", queue, now - t0)
                    # Synthetic span: the arrival->bind interval was
                    # measured between two watch events, not inside a
                    # ``with`` — emit it post-hoc onto the ambient trace
                    # (the dispatching micro-cycle when the echo arrives
                    # on the loop thread, else its own root).
                    obs.emit(
                        "time_to_bind", t0, now, queue=queue, pod=key,
                    )
            elif old.node_name and not obj.node_name:
                with self._lock:
                    self._gangs.add(gang_key_of(obj))
                    self._arrivals[key] = now
                    backlog = len(self._arrivals)
                metrics.set_streaming_backlog(backlog)
                self._event.set()
        else:  # delete
            if old is not None and old.node_name:
                if self.absorb_external:
                    # a peer's release (or a finished pod leaving the
                    # store): freed capacity can admit the backlog — wake
                    with self._lock:
                        self._bound_patches.append(("remove", key, old))
                    self._event.set()
                    return
                self._mark_stale(f"bound pod {key} deleted outside a cycle")
                return
            with self._lock:
                self._arrivals.pop(key, None)
                backlog = len(self._arrivals)
            metrics.set_streaming_backlog(backlog)


class StreamState:
    """The resident world micro-cycles solve against: the node table of
    the last completed full cycle. ``adopt_full_cycle`` must run in
    run_once's finally *before* close_session (close rebinds
    ``ssn.nodes`` to a fresh dict; grabbing the reference first keeps
    the post-bind state). Any doubt about the table — an aborted cycle,
    a failed micro, external bound-pod churn — invalidates it, and the
    next full cycle rebuilds from a clean snapshot."""

    def __init__(self) -> None:
        self.nodes: Optional[dict[str, NodeInfo]] = None
        self.valid = False
        self.reason = "no full cycle adopted yet"

    def invalidate(self, reason: str = "invalidated") -> None:
        self.nodes = None
        self.valid = False
        self.reason = reason

    def adopt_full_cycle(self, ssn, aborted: bool = False) -> None:
        if aborted:
            self.invalidate("full cycle aborted")
            return
        self.nodes = ssn.nodes
        self.valid = True
        self.reason = ""

    def apply_node_patches(self, patches: dict[str, Optional[object]]) -> None:
        for name, node in patches.items():
            if node is None:
                self.nodes.pop(name, None)
                continue
            ni = self.nodes.get(name)
            if ni is None:
                self.nodes[name] = NodeInfo(node)
            else:
                ni.set_node(node)

    def apply_bound_patches(self, patches) -> bool:
        """Absorb peer-shard occupancy churn (federated streaming) into
        the resident table. Duplicates are benign no-ops — a patch
        recorded just before a backstop full cycle is already reflected
        in the adopted snapshot, and ``add_task``/``remove_task`` key by
        pod, so re-applying it raises KeyError and is skipped. Anything
        else (unknown node, resource underflow) means the resident view
        genuinely diverged: invalidate and let the full cycle rebuild.
        Returns False when invalidated."""
        from kube_batch_tpu.api.job_info import TaskInfo

        for op, key, pod in patches:
            try:
                ni = self.nodes.get(pod.node_name)
                if ni is None:
                    raise ValueError(f"node {pod.node_name!r} not resident")
                if op == "add":
                    ni.add_task(TaskInfo(pod))
                else:
                    ni.remove_task(TaskInfo(pod))
            except KeyError:
                # already absorbed (add) / already gone (remove): the
                # adopted snapshot beat the patch to it
                continue
            except Exception as e:  # noqa: BLE001 - degrade, never guess
                self.invalidate(
                    f"bound-pod churn not absorbable for {key}: {e}"
                )
                return False
        return True


def open_micro_session(cache, tiers, action_arguments, jobs, nodes, queues):
    """A session over the restricted streaming world: dirty-gang jobs,
    the resident node table, cloned queues. Plugin registration, the
    JobValid gate and close_session's status write-back are byte-for-
    byte the full-cycle path — only the snapshot is skipped."""
    binder = getattr(cache, "volume_binder", None)
    reset = getattr(binder, "reset", None)
    if reset is not None:
        reset()  # per-session provisional PV state, same as snapshot()
    return open_session(
        cache, micro_tiers(tiers), action_arguments, world=(jobs, nodes, queues)
    )


# -- smoke -------------------------------------------------------------------


SMOKE_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
streaming: {streaming}
"""


def smoke(gangs: int = 4, members: int = 4, nodes: int = 6) -> dict:
    """End-to-end proof on the in-process store, runnable standalone
    (``python -m kube_batch_tpu.streaming``) and from hack/verify.py:

    1. streaming run: seed nodes/queue, start a Scheduler whose conf
       says ``streaming: true`` with a long (5s) full-cycle period, feed
       gangs one at a time and wait for each to bind — with the period
       that long, everything after the initial full cycle binds through
       micro-cycles;
    2. full-cycle replay: identical arrivals against ``streaming:
       false`` with a short period;
    3. assert bind-for-bind placement parity and that the streaming run
       actually took the micro path.
    """
    import tempfile
    import threading as _threading

    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.testing import (
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    def bound(store, gang: str) -> bool:
        pods = [p for p in store.list(PODS) if p.name.startswith(f"{gang}-")]
        return len(pods) == members and all(p.node_name for p in pods)

    def run_mode(streaming: bool) -> tuple[dict, dict]:
        store = ClusterStore()
        store.create_queue(build_queue("default"))
        for i in range(nodes):
            store.create_node(
                build_node(f"n{i}", build_resource_list(cpu=16, memory="16Gi", pods=64))
            )
        cache = SchedulerCache(store)
        with tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", delete=False
        ) as fh:
            fh.write(SMOKE_CONF.format(streaming=str(streaming).lower()))
            conf_path = fh.name
        period = 5.0 if streaming else 0.05
        sched = Scheduler(cache, scheduler_conf=conf_path, schedule_period=period)
        stop = _threading.Event()
        t = _threading.Thread(target=sched.run, args=(stop,), daemon=True)
        t.start()
        latencies: list[float] = []
        try:
            for g in range(gangs):
                name = f"sg{g}"
                store.create_pod_group(build_pod_group(name, min_member=members))
                for m in range(members):
                    store.create_pod(
                        build_pod(
                            name=f"{name}-p{m}", group_name=name,
                            req=build_resource_list(cpu=1, memory="512Mi"),
                        )
                    )
                t0 = time.perf_counter()
                deadline = time.monotonic() + 30.0
                while not bound(store, name):
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"gang {name} not bound within 30s "
                            f"(streaming={streaming})"
                        )
                    time.sleep(0.001)
                latencies.append(time.perf_counter() - t0)
        finally:
            stop.set()
            t.join(timeout=10.0)
            os.unlink(conf_path)
        placed = {f"{p.namespace}/{p.name}": p.node_name for p in store.list(PODS)}
        stats = {
            "latencies_ms": [round(x * 1e3, 3) for x in latencies],
            "micro_cycles": getattr(sched, "micro_cycles_run", 0),
        }
        return placed, stats

    stream_placed, stream_stats = run_mode(True)
    full_placed, full_stats = run_mode(False)
    lat = sorted(stream_stats["latencies_ms"])
    out = {
        "gangs": gangs,
        "pods": gangs * members,
        "bound": sum(1 for v in stream_placed.values() if v),
        "micro_cycles": stream_stats["micro_cycles"],
        "p50_bind_ms": lat[len(lat) // 2] if lat else None,
        "max_bind_ms": lat[-1] if lat else None,
        "parity": stream_placed == full_placed,
        "full_cycle_micro_cycles": full_stats["micro_cycles"],
    }
    out["ok"] = bool(
        out["parity"]
        and out["bound"] == out["pods"]
        and out["micro_cycles"] > 0
        and out["full_cycle_micro_cycles"] == 0
    )
    return out


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="streaming-mode smoke: micro-cycle binds + parity vs full cycles"
    )
    parser.add_argument("--gangs", type=int, default=4)
    parser.add_argument("--members", type=int, default=4)
    parser.add_argument("--json", action="store_true", help="print the result dict as JSON")
    args = parser.parse_args(argv)
    result = smoke(gangs=args.gangs, members=args.members)
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"streaming smoke: {status} ({result['bound']}/{result['pods']} pods "
            f"bound, {result['micro_cycles']} micro-cycles, "
            f"p50 bind {result['p50_bind_ms']}ms, parity={result['parity']})"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level state would otherwise be
    # distinct from the one scheduler.py imports
    from kube_batch_tpu.streaming import main as _canonical_main

    raise SystemExit(_canonical_main())
