"""Blocked sharded-Pallas solver: the fused solve, one node block per chip.

The single-chip fused Pallas kernel (ops/pallas_solve) wins by holding
the whole snapshot in VMEM; its envelope is therefore one chip's VMEM
budget. The GSPMD-sharded XLA twin (parallel/sharded) scales capacity
but pays ~70us of per-HLO dispatch per gang iteration. This module is
the missing rung between them: each device runs the **fused block-local
kernel** — feasibility + score + block argmax over its own 128-lane
node blocks, every node array resident in VMEM — inside one
`jax.shard_map` SPMD program, and the only cross-device traffic is a
**per-gang-iteration argmax exchange**: one small all-gather of each
shard's (best score, global node index, fits-idle bit) triple over the
mesh axis, after which every shard deterministically agrees on the
winner and only the owning shard applies the capacity update to its
block. Queue/job selection and the task/job/queue bookkeeping are tiny
and run replicated (identical inputs -> identical results on every
shard), sharing `ops.kernels.select_queue_job` with the XLA twin so the
paths cannot drift on selection numerics.

Capacity therefore scales with mesh size: the per-shard VMEM claim is
the node block only (`ops.pallas_solve.block_vmem_bytes`), so a
snapshot that overflows `vmem_budget()` on one chip stays on the Pallas
rung when `node_block_bytes / mesh_size` fits — instead of falling to
the XLA twin (the 4.5s-vs-0.5s cliff BENCH_r05 measured at 50k x 5k).

Block backends (``KBT_MESH_PALLAS`` or the ``block_impl`` argument):

- ``mosaic`` — the real TPU kernel (auto-selected on TPU meshes);
- ``interpret`` — the same kernel through the Pallas interpreter
  (traceable, so it compiles inside the SPMD program; how the CPU
  parity tests execute the kernel code bit-for-bit);
- ``jnp`` — a plain-XLA twin of the block step (the fast path on
  virtual-CPU meshes and the oracle the kernel is pinned against).

Speaks the same `SolveState` resume protocol as `ShardedSolver`, so the
action's segmented pod-affinity pause/resume hybrid works unchanged,
including the live InterPodAffinity re-fold between segments.

K-deep batched exchange (``KBT_EXCHANGE_BATCH``, pipelined mode only):
at mesh 8 the per-iteration all-gather dispatch is the floor — the
block kernel itself runs exchange-free at ~1/3 of the measured
per-iteration cost. With ``exchange_batch = K > 1`` each shard first
**speculates** K gang iterations against a throwaway copy of the state,
assuming its own candidate wins every round (losers' blocks are
untouched by a loss, so a shard's speculative slab stays exact for as
long as its recorded candidates keep being used), recording per depth
the (score, global node index, fits-idle) triple plus the task fields
that fully determine the block step (gid, has-sc, ports mask, req8,
res8). One all-gather then ships the whole [K, record] buffer, and a
collective-free **replay** loop re-runs the true replicated
bookkeeping, taking each shard's candidate from its record at a
per-shard depth pointer that advances only when that shard wins (or on
a global abandon, which every speculative world agreed on because all
recorded scores are -inf). A record is used only if its task fields
equal the true current task's — the first mismatch ends the replay and
the next outer iteration re-speculates from the authoritative state, so
the batched program is bind-for-bind identical to the per-iteration
exchange; gang members are near-identical pods, so in the common case
all K iterations commit off a single exchange.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kube_batch_tpu.ops import pallas_solve as ps
from kube_batch_tpu.ops.kernels import (
    KIND_ALLOCATED,
    KIND_PIPELINED,
    SolveState,
    init_state,
    select_queue_job,
)
from kube_batch_tpu.parallel.sharded import AXIS_NAME, NODE_AXIS_ARRAYS

LANES = ps.LANES
R8 = ps.R8

# Arrays the replicated loop body never reads (node-axis arrays travel
# folded+sharded; affinity/compat are pre-folded into cnode/affw).
_DROP = frozenset(NODE_AXIS_ARRAYS) | {"pod_sc", "aff_sc", "compat"}


def _default_exchange_batch() -> int:
    """K for the K-deep batched argmax exchange (``KBT_EXCHANGE_BATCH``).

    Batching only pays when the dispatch it amortizes is overlapped
    work, so K > 1 requires the pipelined-cycles gate (``KBT_PIPELINE``)
    — without it the env knob is inert and the per-iteration exchange
    runs unchanged. Tests and benches pass ``exchange_batch`` to the
    solver explicitly to exercise the batched program in isolation.
    """
    from kube_batch_tpu import pipeline

    if not pipeline.env_on():
        return 1
    raw = os.environ.get("KBT_EXCHANGE_BATCH", "").strip()
    try:
        k = int(raw) if raw else 4
    except ValueError:
        from kube_batch_tpu import log

        log.errorf("bad KBT_EXCHANGE_BATCH=%r; using 4", raw)
        k = 4
    return max(1, min(k, 64))


def _resolve_block_impl(spec: Optional[str], mesh: Mesh) -> str:
    if spec is None:
        spec = os.environ.get("KBT_MESH_PALLAS", "auto")
    spec = (spec or "auto").strip().lower()
    if spec not in ("auto", "mosaic", "interpret", "jnp"):
        raise ValueError(f"unknown block impl {spec!r}")
    if spec == "auto":
        plat = next(iter(mesh.devices.flat)).platform
        return "mosaic" if plat == "tpu" else "jnp"
    return spec


class ShardedPallasSolver:
    """Per-execute driver for the blocked sharded solve: fold the node
    statics once, then solve / resume through the cached SPMD program."""

    def __init__(
        self,
        arrays: dict,
        mesh: Mesh,
        enable_drf: bool = False,
        enable_proportion: bool = False,
        axis_name: str = AXIS_NAME,
        block_impl: Optional[str] = None,
        exchange_batch: Optional[int] = None,
    ) -> None:
        # Arena handles (ops/encode_cache.TensorArena device arrays) are
        # accepted: the block path folds its statics host-side, so any
        # device-resident inputs are gathered to host numpy once here
        # instead of syncing per fold.
        if any(
            not isinstance(v, (np.ndarray, np.generic, float, int, bool))
            for v in arrays.values()
        ):
            arrays = {k: np.asarray(v) for k, v in arrays.items()}
        if np.dtype(np.asarray(arrays["task_req"]).dtype) != np.float32:
            raise ValueError(
                "blocked sharded-Pallas solve is float32-only (like the "
                "single-chip fused kernel); encode with dtype=float32"
            )
        self.a = arrays
        self.mesh = mesh
        self.axis_name = axis_name
        m = mesh.devices.size
        n_nodes = arrays["node_idle"].shape[0]
        nr = ps._rows(n_nodes)
        # The folded row axis pads up to a multiple of the mesh size so
        # shard_map divides it evenly; pad rows carry cnode=0/nmax=0 and
        # can never be candidates.
        self.nr_pad = -(-nr // m) * m
        self.block_impl = _resolve_block_impl(block_impl, mesh)
        self._statics = self._fold_statics(arrays)
        self._tports = ps._ports_mask(np.asarray(arrays["task_ports"]))
        self._pod_sc = arrays.get("pod_sc")  # identity marker for refresh
        self.exchange_batch = (
            _default_exchange_batch()
            if exchange_batch is None
            else max(1, int(exchange_batch))
        )
        # Gang iterations committed straight from a K-deep batched
        # exchange (accumulated across solve/resume calls; the action
        # meters the delta into exchange_batched_iters_total).
        self.batched_iters = 0
        self._fresh, self._resume = _blocked_programs(
            tuple(mesh.devices.flat),
            axis_name,
            enable_drf,
            enable_proportion,
            self.block_impl,
            self.exchange_batch,
        )

    def _fold_statics(self, a: dict) -> dict:
        f32, i32 = np.float32, np.int32
        node_gid = np.asarray(a["node_gid"], np.int64)
        okv = np.asarray(a["node_ok"] & a["node_valid"])
        cnode_full = np.asarray(a["compat"])[:, node_gid] & okv[None, :]
        gt, n = cnode_full.shape
        cnode = np.zeros((gt, self.nr_pad, LANES), i32)
        cnode[:, : (n + LANES - 1) // LANES, :].reshape(gt, -1)[:, :n] = cnode_full
        return {
            "cnode": cnode,
            "affw": ps.fold_affinity_scores(a, self.nr_pad),
            "nalloc": ps._fold2(np.asarray(a["node_alloc"], f32), self.nr_pad, f32),
            "nmax": ps._fold1(np.asarray(a["node_max_tasks"], i32), self.nr_pad, i32),
            "nihs": ps._fold1(np.asarray(a["node_idle_has_sc"], i32), self.nr_pad, i32),
            "nrhs": ps._fold1(np.asarray(a["node_rel_has_sc"], i32), self.nr_pad, i32),
        }

    def solve(self, state: Optional[SolveState]) -> SolveState:
        if self.a.get("pod_sc") is not self._pod_sc:
            # The action recomputed live InterPodAffinity scores after a
            # host-stepped pod landed: re-fold just the affinity static
            # and resume with fresh scores (same contract as the
            # single-chip PallasSolver).
            self._pod_sc = self.a.get("pod_sc")
            self._statics["affw"] = ps.fold_affinity_scores(self.a, self.nr_pad)
        a_call = dict(self.a)
        a_call["_tports"] = self._tports
        if state is None:
            out = self._fresh(a_call, self._statics)
        else:
            out = self._resume(a_call, self._statics, state)
        if self.exchange_batch > 1:
            out, n_batched = out
            self.batched_iters += int(n_batched)
        return out


@lru_cache(maxsize=16)
def _blocked_programs(
    devices: tuple,
    axis_name: str,
    enable_drf: bool,
    enable_proportion: bool,
    block_impl: str,
    exchange_batch: int = 1,
):
    """(fresh, resume) jitted SPMD programs for a mesh + block backend.
    Keyed on the device tuple and static flags; shapes (and the derived
    Nr_pad/Nr_loc/GT block geometry) are left to jit's per-signature
    cache, so stable encode buckets hit the compiled program across
    cycles. With ``exchange_batch > 1`` the programs return
    ``(SolveState, n_batched_iters)`` — the gang loop speculates K
    iterations per shard, ships one [K, record] all-gather, and replays
    validated records collective-free (module docstring has the full
    scheme); the SolveState itself keeps the exact per-iteration
    signature so the cross-tier resume protocol cannot drift."""
    import jax.numpy as jnp
    from jax import lax

    try:  # jax >= 0.6 exports shard_map at the top level
        from jax import shard_map  # type: ignore[attr-defined]
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.asarray(devices), (axis_name,))
    m = len(devices)
    spec3 = P(None, axis_name, None)
    spec2 = P(axis_name, None)
    sh_specs = {
        "cnode": spec3, "affw": spec3, "nalloc": spec3,
        "nmax": spec2, "nihs": spec2, "nrhs": spec2,
        "idle": spec3, "rel": spec3, "used": spec3,
        "ntasks": spec2, "nports": spec2,
    }
    out_sh_specs = {
        "idle": spec3, "rel": spec3, "used": spec3,
        "ntasks": spec2, "nports": spec2,
    }
    INT_MAX = ps.INT_MAX
    NINF = float("-inf")

    def local(rep, a, sh):
        """One shard's SPMD body: the full gang loop over the local node
        block, replicated selection/bookkeeping, one argmax exchange per
        gang iteration — or per K-iteration speculate/replay batch when
        ``exchange_batch > 1``."""
        i32, f32 = jnp.int32, jnp.float32
        T, R = a["task_req"].shape
        J = a["job_min"].shape[0]
        Q = a["queue_rank"].shape[0]
        gt = sh["cnode"].shape[0]
        nr_loc = sh["cnode"].shape[1]
        sent = nr_loc * m * LANES  # global padded N: "no candidate"
        axis_idx = lax.axis_index(axis_name).astype(i32)
        off = axis_idx * (nr_loc * LANES)

        if block_impl == "jnp":
            block = ps.block_step_jnp
        else:
            block = ps._build_block_step(nr_loc, gt, block_impl == "interpret")

        eps8 = jnp.concatenate(
            [jnp.asarray(a["eps"], f32), jnp.ones(R8 - R, f32)]
        )
        wvec = jnp.stack(
            [jnp.asarray(a["w_least"], f32), jnp.asarray(a["w_balanced"], f32)]
        )
        fpad = jnp.zeros(ps.FVEC_LEN - 3 * R8 - 2, f32)
        host_only = a["task_host_only"]
        max_iter = jnp.int32(T + J + Q + 1) + jnp.sum(host_only).astype(i32)
        lane1 = lax.broadcasted_iota(i32, (1, LANES), 1)

        # The loop body is factored into prefix (replicated selection +
        # task pop), taskvec (the fields that fully determine a task's
        # block step — also the speculative-record validity key), the
        # block call, and commit (everything after the winner is known),
        # so the per-iteration exchange and the K-deep batched program
        # share every line of bookkeeping and cannot drift.

        def prefix(s: SolveState):
            # -- replicated queue + job selection (shared with the XLA twin)
            need_sel = s.cur < 0
            qsel, q_any, overused, jsel, j_any = select_queue_job(
                a, s, enable_drf, enable_proportion
            )
            drop_q = need_sel & q_any & overused
            sel_ok = q_any & ~overused & j_any
            cur = jnp.where(need_sel, jnp.where(sel_ok, jsel, -1), s.cur)
            job_active = jnp.where(
                drop_q, s.job_active & (a["job_queue"] != qsel), s.job_active
            )
            q_dropped = s.q_dropped.at[qsel].set(drop_q | s.q_dropped[qsel])

            # -- pop the current job's next pending task (O(1) pointer) ----
            cur_c = jnp.maximum(cur, 0)
            t = s.ptr[cur_c]
            t_any = (cur >= 0) & (t < a["job_end"][cur_c])
            t = jnp.minimum(t, T - 1)
            drop = (cur >= 0) & ~t_any
            pause = t_any & host_only[t]
            proc = t_any & ~pause
            return cur, cur_c, t, drop, pause, proc, job_active, q_dropped

        def taskvec(t):
            req8 = jnp.concatenate(
                [jnp.asarray(a["task_req"][t], f32), jnp.zeros(R8 - R, f32)]
            )
            res8 = jnp.concatenate(
                [jnp.asarray(a["task_res"][t], f32), jnp.zeros(R8 - R, f32)]
            )
            gid = jnp.clip(a["task_gid"][t], 0, gt - 1).astype(i32)
            tports = a["_tports"][t].astype(i32)
            has_sc = a["task_has_sc"][t].astype(i32)
            return req8, res8, gid, tports, has_sc

        def run_block(s, req8, res8, gid, tports, has_sc):
            # -- fused block-local feasibility + score + argmax ------------
            fvec = jnp.concatenate([req8, res8, eps8, wvec, fpad])
            ivec = jnp.stack(
                [
                    gid,
                    has_sc,
                    tports,
                    off,
                    jnp.int32(sent),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                ]
            )
            return block(
                ivec, fvec,
                sh["cnode"], sh["affw"], sh["nalloc"],
                sh["nmax"], sh["nihs"], sh["nrhs"],
                s.idle, s.rel, s.used, s.ntasks, s.nports,
            )

        def winner(scores, idxs, fits):
            # Every shard derives the same winner (max score, min global
            # node index on ties — identical to the single-chip
            # tie-break); the winner's fits-idle bit comes from the
            # shard that owns it.
            big = jnp.max(scores)
            any_cand = big > NINF
            nb = jnp.min(jnp.where(scores == big, idxs, INT_MAX))
            nb = jnp.minimum(nb, sent - 1)
            fits_idle_nb = (
                jnp.sum(jnp.where((scores == big) & (idxs == nb), fits, 0)) > 0
            )
            return any_cand, nb, fits_idle_nb

        def commit(
            s, cur, cur_c, t, drop, pause, proc, job_active, q_dropped,
            req8, res8, tports, any_cand, nb, fits_idle_nb,
        ) -> SolveState:
            abandon = proc & ~any_cand
            assign = proc & any_cand
            do_alloc = assign & fits_idle_nb

            # -- capacity update: owning shard only, one 128-lane slab ----
            rloc = nb // LANES - axis_idx * nr_loc
            mine = (rloc >= 0) & (rloc < nr_loc)
            rc = jnp.clip(rloc, 0, nr_loc - 1)
            l = nb % LANES
            upd = assign & mine
            lmask = upd & (lane1 == l)  # [1, 128]
            lmask3 = lmask[None]  # [1, 1, 128]
            col_alloc = jnp.where(do_alloc, res8, 0.0)[:, None, None]
            col_pipe = jnp.where(do_alloc, 0.0, res8)[:, None, None]
            res3 = res8[:, None, None]

            z = jnp.int32(0)  # index literals pinned to rc's dtype (x64)

            def slab_update(arr, delta3):
                slab = lax.dynamic_slice(arr, (z, rc, z), (R8, 1, LANES))
                slab = slab + jnp.where(lmask3, delta3, 0.0)
                return lax.dynamic_update_slice(arr, slab, (z, rc, z))

            idle = slab_update(s.idle, -col_alloc)
            rel = slab_update(s.rel, -col_pipe)
            used = slab_update(s.used, res3)
            nt_row = lax.dynamic_slice(s.ntasks, (rc, z), (1, LANES))
            nt_row = nt_row + jnp.where(lmask, 1, 0)
            ntasks = lax.dynamic_update_slice(s.ntasks, nt_row, (rc, z))
            np_row = lax.dynamic_slice(s.nports, (rc, z), (1, LANES))
            np_row = np_row | jnp.where(lmask, tports, 0)
            nports = lax.dynamic_update_slice(s.nports, np_row, (rc, z))

            # -- replicated bookkeeping (identical on every shard) ---------
            ready_cnt = s.ready_cnt.at[cur_c].add(jnp.where(do_alloc, 1, 0))
            ptr = s.ptr.at[cur_c].add(jnp.where(proc, 1, 0))
            assigned_node = s.assigned_node.at[t].set(
                jnp.where(assign, nb, s.assigned_node[t])
            )
            kind = jnp.where(
                do_alloc, KIND_ALLOCATED, jnp.where(assign, KIND_PIPELINED, 0)
            )
            assigned_kind = s.assigned_kind.at[t].set(
                jnp.where(assign, kind, s.assigned_kind[t])
            )
            assign_pos = s.assign_pos.at[t].set(
                jnp.where(assign, s.step, s.assign_pos[t])
            )
            add_row = jnp.where(assign, a["task_res"][t], jnp.zeros(R, f32))
            job_alloc = (
                s.job_alloc.at[cur_c].add(add_row) if enable_drf else s.job_alloc
            )
            if enable_proportion:
                qcur = a["job_queue"][cur_c]
                q_alloc = s.q_alloc.at[qcur].add(add_row)
                q_alloc_has_sc = s.q_alloc_has_sc.at[qcur].set(
                    s.q_alloc_has_sc[qcur] | (assign & a["task_res_has_sc"][t])
                )
            else:
                q_alloc = s.q_alloc
                q_alloc_has_sc = s.q_alloc_has_sc

            job_active = job_active.at[cur_c].set(
                jnp.where(drop | abandon, False, job_active[cur_c])
            )
            ready_now = ready_cnt[cur_c] >= a["job_min"][cur_c]
            cur_next = jnp.where(drop | abandon | (proc & ready_now), -1, cur)

            return SolveState(
                it=s.it + 1,
                step=s.step + assign.astype(i32),
                cur=cur_next,
                ptr=ptr,
                assigned_node=assigned_node,
                assigned_kind=assigned_kind,
                assign_pos=assign_pos,
                idle=idle,
                rel=rel,
                used=used,
                ntasks=ntasks,
                nports=nports,
                ready_cnt=ready_cnt,
                job_active=job_active,
                q_dropped=q_dropped,
                job_alloc=job_alloc,
                q_alloc=q_alloc,
                q_alloc_has_sc=q_alloc_has_sc,
                paused_at=jnp.where(pause, t, jnp.int32(-1)),
            )

        def body(s: SolveState) -> SolveState:
            cur, cur_c, t, drop, pause, proc, job_active, q_dropped = prefix(s)
            req8, res8, gid, tports, has_sc = taskvec(t)
            bscore, bidx, bfits = run_block(s, req8, res8, gid, tports, has_sc)

            # -- the cross-chip argmax exchange: one packed all-gather per
            # gang iteration.
            packed = jnp.stack(
                [bscore, bidx.astype(f32), bfits.astype(f32)]
            )
            allp = lax.all_gather(packed, axis_name)  # [mesh, 3]
            any_cand, nb, fits_idle_nb = winner(
                allp[:, 0], allp[:, 1].astype(i32), allp[:, 2].astype(i32)
            )
            return commit(
                s, cur, cur_c, t, drop, pause, proc, job_active, q_dropped,
                req8, res8, tports, any_cand, nb, fits_idle_nb,
            )

        def cond(s: SolveState):
            return (
                ((s.cur >= 0) | jnp.any(s.job_active))
                & (s.it < max_iter)
                & (s.paused_at < 0)
            )

        # -- K-deep batched exchange: speculate, one gather, replay --------
        K = exchange_batch
        REC_F = 1 + 2 * R8  # score, req8, res8

        def spec_body(c):
            # One speculative gang iteration on a throwaway state: this
            # shard's own candidate is assumed to win, so its block stays
            # exact for its own chain; proc iterations append a record.
            s, w, rf, ri = c
            cur, cur_c, t, drop, pause, proc, job_active, q_dropped = prefix(s)
            req8, res8, gid, tports, has_sc = taskvec(t)
            bscore, bidx, bfits = run_block(s, req8, res8, gid, tports, has_sc)
            any_cand = bscore > NINF
            nb = jnp.minimum(bidx.astype(i32), sent - 1)
            fits_idle_nb = bfits.astype(i32) > 0
            s2 = commit(
                s, cur, cur_c, t, drop, pause, proc, job_active, q_dropped,
                req8, res8, tports, any_cand, nb, fits_idle_nb,
            )
            slot = jnp.where(proc, w, jnp.int32(K))  # K = out of bounds: drop
            rf = rf.at[slot].set(
                jnp.concatenate([bscore[None].astype(f32), req8, res8]),
                mode="drop",
            )
            ri = ri.at[slot].set(
                jnp.stack(
                    [bidx.astype(i32), bfits.astype(i32), gid, has_sc, tports]
                ),
                mode="drop",
            )
            return s2, w + proc.astype(i32), rf, ri

        def spec_cond(c):
            s, w, _, _ = c
            return (w < K) & cond(s)

        def replay_cond(c):
            s, _, live, _ = c
            return live & cond(s)

        def make_replay_body(allf, alli, nrec):
            shard_ids = jnp.arange(m, dtype=i32)

            def replay_body(c):
                # One true gang iteration, collective-free: candidates
                # come from the gathered records at each shard's depth
                # pointer. A record is usable only while its task fields
                # equal the true current task's; the first mismatch (or
                # an exhausted shard) ends the replay un-committed and
                # the outer loop re-speculates from the true state.
                s, d, live, nc = c
                cur, cur_c, t, drop, pause, proc, job_active, q_dropped = (
                    prefix(s)
                )
                req8, res8, gid, tports, has_sc = taskvec(t)
                dcl = jnp.minimum(d, K - 1)
                rowf = jnp.take_along_axis(
                    allf, dcl[:, None, None], axis=1
                )[:, 0]  # [mesh, REC_F]
                rowi = jnp.take_along_axis(
                    alli, dcl[:, None, None], axis=1
                )[:, 0]  # [mesh, 5]
                scores = rowf[:, 0]
                idxs = rowi[:, 0]
                fits = rowi[:, 1]
                valid = jnp.all(
                    (d < nrec)
                    & (rowi[:, 2] == gid)
                    & (rowi[:, 3] == has_sc)
                    & (rowi[:, 4] == tports)
                    & jnp.all(rowf[:, 1 : 1 + R8] == req8[None, :], axis=1)
                    & jnp.all(rowf[:, 1 + R8 :] == res8[None, :], axis=1)
                )
                any_cand, nb, fits_idle_nb = winner(scores, idxs, fits)
                s2 = commit(
                    s, cur, cur_c, t, drop, pause, proc, job_active,
                    q_dropped, req8, res8, tports, any_cand, nb, fits_idle_nb,
                )
                # Depth pointers: the winning shard consumed its record;
                # a global abandon consumed everyone's (all recorded
                # scores were -inf, so every speculative world abandoned
                # this task too, with no block change on either side).
                win_shard = (nb // (nr_loc * LANES)).astype(i32)
                d2 = jnp.where(
                    any_cand,
                    jnp.where(shard_ids == win_shard, d + 1, d),
                    d + 1,
                )
                d2 = jnp.where(proc, d2, d)
                ok = (~proc) | valid
                s3 = jax.tree_util.tree_map(
                    lambda nv, ov: jnp.where(ok, nv, ov), s2, s
                )
                return (
                    s3,
                    jnp.where(ok, d2, d),
                    live & ok,
                    nc + (proc & ok).astype(i32),
                )

            return replay_body

        def outer_cond(c):
            s, _ = c
            return cond(s)

        def outer_body(c):
            s, nb_tot = c
            rf0 = jnp.zeros((K, REC_F), f32)
            ri0 = jnp.zeros((K, 5), i32)
            _, w, rf, ri = lax.while_loop(
                spec_cond, spec_body, (s, jnp.int32(0), rf0, ri0)
            )
            allf = lax.all_gather(rf, axis_name)  # [mesh, K, REC_F]
            alli = lax.all_gather(ri, axis_name)  # [mesh, K, 5]
            nrec = lax.all_gather(w, axis_name)  # [mesh]
            # Replay iteration 0 is always committable: speculation and
            # replay both start from the true state, and the native
            # selection/drop steps before the first proc iteration are
            # replicated-deterministic — so depth-0 records are exact
            # and every outer iteration advances s.it by at least one.
            s2, _, _, nc = lax.while_loop(
                replay_cond,
                make_replay_body(allf, alli, nrec),
                (s, jnp.zeros(m, i32), jnp.bool_(True), jnp.int32(0)),
            )
            return s2, nb_tot + nc

        (
            it, step, cur, ptr, an, ak, ap,
            ready_cnt, job_active, q_dropped, job_alloc, q_alloc, qahs, paused,
        ) = rep
        state = SolveState(
            it=it, step=step, cur=cur, ptr=ptr,
            assigned_node=an, assigned_kind=ak, assign_pos=ap,
            idle=sh["idle"], rel=sh["rel"], used=sh["used"],
            ntasks=sh["ntasks"], nports=sh["nports"],
            ready_cnt=ready_cnt, job_active=job_active, q_dropped=q_dropped,
            job_alloc=job_alloc, q_alloc=q_alloc, q_alloc_has_sc=qahs,
            paused_at=paused,
        )
        if exchange_batch > 1:
            out, n_batched = lax.while_loop(
                outer_cond, outer_body, (state, jnp.int32(0))
            )
        else:
            out = lax.while_loop(cond, body, state)
            n_batched = None
        rep_out = (
            out.it, out.step, out.cur, out.ptr,
            out.assigned_node, out.assigned_kind, out.assign_pos,
            out.ready_cnt, out.job_active, out.q_dropped,
            out.job_alloc, out.q_alloc, out.q_alloc_has_sc, out.paused_at,
        )
        if n_batched is not None:
            rep_out = rep_out + (n_batched,)
        sh_out = {
            "idle": out.idle, "rel": out.rel, "used": out.used,
            "ntasks": out.ntasks, "nports": out.nports,
        }
        return rep_out, sh_out

    smapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), sh_specs),
        out_specs=(P(), out_sh_specs),
        check_rep=False,
    )

    def run(a: dict, statics: dict, state: Optional[SolveState]) -> SolveState:
        i32, f32 = jnp.int32, jnp.float32
        n = a["node_idle"].shape[0]
        R = a["task_req"].shape[1]
        p = a["task_ports"].shape[1]
        nr_pad = statics["cnode"].shape[1]
        nf = nr_pad * LANES

        if state is None:
            state = init_state(
                a, enable_drf=enable_drf, enable_proportion=enable_proportion
            )
        state = state._replace(paused_at=jnp.int32(-1))

        def fold2(x):
            xp = jnp.pad(
                jnp.asarray(x, f32), ((0, nf - n), (0, R8 - R))
            )
            return xp.reshape(nr_pad, LANES, R8).transpose(2, 0, 1)

        def fold1(x, dt):
            return jnp.pad(jnp.asarray(x, dt), (0, nf - n)).reshape(nr_pad, LANES)

        if p:
            bits = jnp.sum(
                jnp.asarray(state.nports, i32)
                * (jnp.int32(1) << jnp.arange(p, dtype=i32))[None, :],
                axis=1,
                dtype=i32,
            )
        else:
            bits = jnp.zeros(n, i32)

        sh_in = dict(statics)
        sh_in.update(
            idle=fold2(state.idle),
            rel=fold2(state.rel),
            used=fold2(state.used),
            ntasks=fold1(state.ntasks, i32),
            nports=fold1(bits, i32),
        )
        rep_in = (
            jnp.asarray(state.it, i32), jnp.asarray(state.step, i32),
            jnp.asarray(state.cur, i32), jnp.asarray(state.ptr, i32),
            jnp.asarray(state.assigned_node, i32),
            jnp.asarray(state.assigned_kind, i32),
            jnp.asarray(state.assign_pos, i32),
            jnp.asarray(state.ready_cnt, i32),
            jnp.asarray(state.job_active, bool),
            jnp.asarray(state.q_dropped, bool),
            jnp.asarray(state.job_alloc, f32),
            jnp.asarray(state.q_alloc, f32),
            jnp.asarray(state.q_alloc_has_sc, bool),
            state.paused_at,
        )
        a_rep = {k: v for k, v in a.items() if k not in _DROP}
        rep_out, sh_out = smapped(rep_in, a_rep, sh_in)
        n_batched = None
        if exchange_batch > 1:
            *rep_flat, n_batched = rep_out
            rep_out = tuple(rep_flat)

        def unfold2(x):
            return x.transpose(1, 2, 0).reshape(nf, R8)[:n, :R]

        def unfold1(x):
            return x.reshape(nf)[:n]

        obits = unfold1(sh_out["nports"])
        if p:
            nports_bool = (
                (obits[:, None] >> jnp.arange(p, dtype=i32)[None, :]) & 1
            ) != 0
        else:
            nports_bool = jnp.zeros((n, 0), bool)
        (
            it, step, cur, ptr, an, ak, ap,
            ready_cnt, job_active, q_dropped, job_alloc, q_alloc, qahs, paused,
        ) = rep_out
        final = SolveState(
            it=it, step=step, cur=cur, ptr=ptr,
            assigned_node=an, assigned_kind=ak, assign_pos=ap,
            idle=unfold2(sh_out["idle"]),
            rel=unfold2(sh_out["rel"]),
            used=unfold2(sh_out["used"]),
            ntasks=unfold1(sh_out["ntasks"]),
            nports=nports_bool,
            ready_cnt=ready_cnt, job_active=job_active, q_dropped=q_dropped,
            job_alloc=job_alloc, q_alloc=q_alloc, q_alloc_has_sc=qahs,
            paused_at=paused,
        )
        if n_batched is not None:
            return final, n_batched
        return final

    fresh = jax.jit(partial(run, state=None))
    resume = jax.jit(run)
    return fresh, resume
