"""Blocked sharded-Pallas solver: the fused solve, one node block per chip.

The single-chip fused Pallas kernel (ops/pallas_solve) wins by holding
the whole snapshot in VMEM; its envelope is therefore one chip's VMEM
budget. The GSPMD-sharded XLA twin (parallel/sharded) scales capacity
but pays ~70us of per-HLO dispatch per gang iteration. This module is
the missing rung between them: each device runs the **fused block-local
kernel** — feasibility + score + block argmax over its own 128-lane
node blocks, every node array resident in VMEM — inside one
`jax.shard_map` SPMD program, and the only cross-device traffic is a
**per-gang-iteration argmax exchange**: one small all-gather of each
shard's (best score, global node index, fits-idle bit) triple over the
mesh axis, after which every shard deterministically agrees on the
winner and only the owning shard applies the capacity update to its
block. Queue/job selection and the task/job/queue bookkeeping are tiny
and run replicated (identical inputs -> identical results on every
shard), sharing `ops.kernels.select_queue_job` with the XLA twin so the
paths cannot drift on selection numerics.

Capacity therefore scales with mesh size: the per-shard VMEM claim is
the node block only (`ops.pallas_solve.block_vmem_bytes`), so a
snapshot that overflows `vmem_budget()` on one chip stays on the Pallas
rung when `node_block_bytes / mesh_size` fits — instead of falling to
the XLA twin (the 4.5s-vs-0.5s cliff BENCH_r05 measured at 50k x 5k).

Block backends (``KBT_MESH_PALLAS`` or the ``block_impl`` argument):

- ``mosaic`` — the real TPU kernel (auto-selected on TPU meshes);
- ``interpret`` — the same kernel through the Pallas interpreter
  (traceable, so it compiles inside the SPMD program; how the CPU
  parity tests execute the kernel code bit-for-bit);
- ``jnp`` — a plain-XLA twin of the block step (the fast path on
  virtual-CPU meshes and the oracle the kernel is pinned against).

Speaks the same `SolveState` resume protocol as `ShardedSolver`, so the
action's segmented pod-affinity pause/resume hybrid works unchanged,
including the live InterPodAffinity re-fold between segments.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kube_batch_tpu.ops import pallas_solve as ps
from kube_batch_tpu.ops.kernels import (
    KIND_ALLOCATED,
    KIND_PIPELINED,
    SolveState,
    init_state,
    select_queue_job,
)
from kube_batch_tpu.parallel.sharded import AXIS_NAME, NODE_AXIS_ARRAYS

LANES = ps.LANES
R8 = ps.R8

# Arrays the replicated loop body never reads (node-axis arrays travel
# folded+sharded; affinity/compat are pre-folded into cnode/affw).
_DROP = frozenset(NODE_AXIS_ARRAYS) | {"pod_sc", "aff_sc", "compat"}


def _resolve_block_impl(spec: Optional[str], mesh: Mesh) -> str:
    if spec is None:
        spec = os.environ.get("KBT_MESH_PALLAS", "auto")
    spec = (spec or "auto").strip().lower()
    if spec not in ("auto", "mosaic", "interpret", "jnp"):
        raise ValueError(f"unknown block impl {spec!r}")
    if spec == "auto":
        plat = next(iter(mesh.devices.flat)).platform
        return "mosaic" if plat == "tpu" else "jnp"
    return spec


class ShardedPallasSolver:
    """Per-execute driver for the blocked sharded solve: fold the node
    statics once, then solve / resume through the cached SPMD program."""

    def __init__(
        self,
        arrays: dict,
        mesh: Mesh,
        enable_drf: bool = False,
        enable_proportion: bool = False,
        axis_name: str = AXIS_NAME,
        block_impl: Optional[str] = None,
    ) -> None:
        # Arena handles (ops/encode_cache.TensorArena device arrays) are
        # accepted: the block path folds its statics host-side, so any
        # device-resident inputs are gathered to host numpy once here
        # instead of syncing per fold.
        if any(
            not isinstance(v, (np.ndarray, np.generic, float, int, bool))
            for v in arrays.values()
        ):
            arrays = {k: np.asarray(v) for k, v in arrays.items()}
        if np.dtype(np.asarray(arrays["task_req"]).dtype) != np.float32:
            raise ValueError(
                "blocked sharded-Pallas solve is float32-only (like the "
                "single-chip fused kernel); encode with dtype=float32"
            )
        self.a = arrays
        self.mesh = mesh
        self.axis_name = axis_name
        m = mesh.devices.size
        n_nodes = arrays["node_idle"].shape[0]
        nr = ps._rows(n_nodes)
        # The folded row axis pads up to a multiple of the mesh size so
        # shard_map divides it evenly; pad rows carry cnode=0/nmax=0 and
        # can never be candidates.
        self.nr_pad = -(-nr // m) * m
        self.block_impl = _resolve_block_impl(block_impl, mesh)
        self._statics = self._fold_statics(arrays)
        self._tports = ps._ports_mask(np.asarray(arrays["task_ports"]))
        self._pod_sc = arrays.get("pod_sc")  # identity marker for refresh
        self._fresh, self._resume = _blocked_programs(
            tuple(mesh.devices.flat),
            axis_name,
            enable_drf,
            enable_proportion,
            self.block_impl,
        )

    def _fold_statics(self, a: dict) -> dict:
        f32, i32 = np.float32, np.int32
        node_gid = np.asarray(a["node_gid"], np.int64)
        okv = np.asarray(a["node_ok"] & a["node_valid"])
        cnode_full = np.asarray(a["compat"])[:, node_gid] & okv[None, :]
        gt, n = cnode_full.shape
        cnode = np.zeros((gt, self.nr_pad, LANES), i32)
        cnode[:, : (n + LANES - 1) // LANES, :].reshape(gt, -1)[:, :n] = cnode_full
        return {
            "cnode": cnode,
            "affw": ps.fold_affinity_scores(a, self.nr_pad),
            "nalloc": ps._fold2(np.asarray(a["node_alloc"], f32), self.nr_pad, f32),
            "nmax": ps._fold1(np.asarray(a["node_max_tasks"], i32), self.nr_pad, i32),
            "nihs": ps._fold1(np.asarray(a["node_idle_has_sc"], i32), self.nr_pad, i32),
            "nrhs": ps._fold1(np.asarray(a["node_rel_has_sc"], i32), self.nr_pad, i32),
        }

    def solve(self, state: Optional[SolveState]) -> SolveState:
        if self.a.get("pod_sc") is not self._pod_sc:
            # The action recomputed live InterPodAffinity scores after a
            # host-stepped pod landed: re-fold just the affinity static
            # and resume with fresh scores (same contract as the
            # single-chip PallasSolver).
            self._pod_sc = self.a.get("pod_sc")
            self._statics["affw"] = ps.fold_affinity_scores(self.a, self.nr_pad)
        a_call = dict(self.a)
        a_call["_tports"] = self._tports
        if state is None:
            return self._fresh(a_call, self._statics)
        return self._resume(a_call, self._statics, state)


@lru_cache(maxsize=16)
def _blocked_programs(
    devices: tuple,
    axis_name: str,
    enable_drf: bool,
    enable_proportion: bool,
    block_impl: str,
):
    """(fresh, resume) jitted SPMD programs for a mesh + block backend.
    Keyed on the device tuple and static flags; shapes (and the derived
    Nr_pad/Nr_loc/GT block geometry) are left to jit's per-signature
    cache, so stable encode buckets hit the compiled program across
    cycles."""
    import jax.numpy as jnp
    from jax import lax

    try:  # jax >= 0.6 exports shard_map at the top level
        from jax import shard_map  # type: ignore[attr-defined]
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.asarray(devices), (axis_name,))
    m = len(devices)
    spec3 = P(None, axis_name, None)
    spec2 = P(axis_name, None)
    sh_specs = {
        "cnode": spec3, "affw": spec3, "nalloc": spec3,
        "nmax": spec2, "nihs": spec2, "nrhs": spec2,
        "idle": spec3, "rel": spec3, "used": spec3,
        "ntasks": spec2, "nports": spec2,
    }
    out_sh_specs = {
        "idle": spec3, "rel": spec3, "used": spec3,
        "ntasks": spec2, "nports": spec2,
    }
    INT_MAX = ps.INT_MAX
    NINF = float("-inf")

    def local(rep, a, sh):
        """One shard's SPMD body: the full gang loop over the local node
        block, replicated selection/bookkeeping, one argmax exchange per
        iteration."""
        i32, f32 = jnp.int32, jnp.float32
        T, R = a["task_req"].shape
        J = a["job_min"].shape[0]
        Q = a["queue_rank"].shape[0]
        gt = sh["cnode"].shape[0]
        nr_loc = sh["cnode"].shape[1]
        sent = nr_loc * m * LANES  # global padded N: "no candidate"
        axis_idx = lax.axis_index(axis_name).astype(i32)
        off = axis_idx * (nr_loc * LANES)

        if block_impl == "jnp":
            block = ps.block_step_jnp
        else:
            block = ps._build_block_step(nr_loc, gt, block_impl == "interpret")

        eps8 = jnp.concatenate(
            [jnp.asarray(a["eps"], f32), jnp.ones(R8 - R, f32)]
        )
        wvec = jnp.stack(
            [jnp.asarray(a["w_least"], f32), jnp.asarray(a["w_balanced"], f32)]
        )
        fpad = jnp.zeros(ps.FVEC_LEN - 3 * R8 - 2, f32)
        host_only = a["task_host_only"]
        max_iter = jnp.int32(T + J + Q + 1) + jnp.sum(host_only).astype(i32)
        lane1 = lax.broadcasted_iota(i32, (1, LANES), 1)

        def body(s: SolveState) -> SolveState:
            # -- replicated queue + job selection (shared with the XLA twin)
            need_sel = s.cur < 0
            qsel, q_any, overused, jsel, j_any = select_queue_job(
                a, s, enable_drf, enable_proportion
            )
            drop_q = need_sel & q_any & overused
            sel_ok = q_any & ~overused & j_any
            cur = jnp.where(need_sel, jnp.where(sel_ok, jsel, -1), s.cur)
            job_active = jnp.where(
                drop_q, s.job_active & (a["job_queue"] != qsel), s.job_active
            )
            q_dropped = s.q_dropped.at[qsel].set(drop_q | s.q_dropped[qsel])

            # -- pop the current job's next pending task (O(1) pointer) ----
            cur_c = jnp.maximum(cur, 0)
            t = s.ptr[cur_c]
            t_any = (cur >= 0) & (t < a["job_end"][cur_c])
            t = jnp.minimum(t, T - 1)
            drop = (cur >= 0) & ~t_any
            pause = t_any & host_only[t]
            proc = t_any & ~pause

            # -- fused block-local feasibility + score + argmax ------------
            req8 = jnp.concatenate(
                [jnp.asarray(a["task_req"][t], f32), jnp.zeros(R8 - R, f32)]
            )
            res8 = jnp.concatenate(
                [jnp.asarray(a["task_res"][t], f32), jnp.zeros(R8 - R, f32)]
            )
            gid = jnp.clip(a["task_gid"][t], 0, gt - 1).astype(i32)
            tports = a["_tports"][t]
            fvec = jnp.concatenate([req8, res8, eps8, wvec, fpad])
            ivec = jnp.stack(
                [
                    gid,
                    a["task_has_sc"][t].astype(i32),
                    tports,
                    off,
                    jnp.int32(sent),
                    jnp.int32(0), jnp.int32(0), jnp.int32(0),
                ]
            )
            bscore, bidx, bfits = block(
                ivec, fvec,
                sh["cnode"], sh["affw"], sh["nalloc"],
                sh["nmax"], sh["nihs"], sh["nrhs"],
                s.idle, s.rel, s.used, s.ntasks, s.nports,
            )

            # -- the cross-chip argmax exchange: one packed all-gather per
            # gang iteration; every shard then derives the same winner
            # (max score, min global node index on ties — identical to
            # the single-chip tie-break) and the winner's fits-idle bit
            # comes from the shard that owns it.
            packed = jnp.stack(
                [bscore, bidx.astype(f32), bfits.astype(f32)]
            )
            allp = lax.all_gather(packed, axis_name)  # [mesh, 3]
            scores = allp[:, 0]
            idxs = allp[:, 1].astype(i32)
            fits = allp[:, 2].astype(i32)
            big = jnp.max(scores)
            any_cand = big > NINF
            nb = jnp.min(jnp.where(scores == big, idxs, INT_MAX))
            nb = jnp.minimum(nb, sent - 1)
            fits_idle_nb = (
                jnp.sum(jnp.where((scores == big) & (idxs == nb), fits, 0)) > 0
            )

            abandon = proc & ~any_cand
            assign = proc & any_cand
            do_alloc = assign & fits_idle_nb

            # -- capacity update: owning shard only, one 128-lane slab ----
            rloc = nb // LANES - axis_idx * nr_loc
            mine = (rloc >= 0) & (rloc < nr_loc)
            rc = jnp.clip(rloc, 0, nr_loc - 1)
            l = nb % LANES
            upd = assign & mine
            lmask = upd & (lane1 == l)  # [1, 128]
            lmask3 = lmask[None]  # [1, 1, 128]
            col_alloc = jnp.where(do_alloc, res8, 0.0)[:, None, None]
            col_pipe = jnp.where(do_alloc, 0.0, res8)[:, None, None]
            res3 = res8[:, None, None]

            z = jnp.int32(0)  # index literals pinned to rc's dtype (x64)

            def slab_update(arr, delta3):
                slab = lax.dynamic_slice(arr, (z, rc, z), (R8, 1, LANES))
                slab = slab + jnp.where(lmask3, delta3, 0.0)
                return lax.dynamic_update_slice(arr, slab, (z, rc, z))

            idle = slab_update(s.idle, -col_alloc)
            rel = slab_update(s.rel, -col_pipe)
            used = slab_update(s.used, res3)
            nt_row = lax.dynamic_slice(s.ntasks, (rc, z), (1, LANES))
            nt_row = nt_row + jnp.where(lmask, 1, 0)
            ntasks = lax.dynamic_update_slice(s.ntasks, nt_row, (rc, z))
            np_row = lax.dynamic_slice(s.nports, (rc, z), (1, LANES))
            np_row = np_row | jnp.where(lmask, tports, 0)
            nports = lax.dynamic_update_slice(s.nports, np_row, (rc, z))

            # -- replicated bookkeeping (identical on every shard) ---------
            ready_cnt = s.ready_cnt.at[cur_c].add(jnp.where(do_alloc, 1, 0))
            ptr = s.ptr.at[cur_c].add(jnp.where(proc, 1, 0))
            assigned_node = s.assigned_node.at[t].set(
                jnp.where(assign, nb, s.assigned_node[t])
            )
            kind = jnp.where(
                do_alloc, KIND_ALLOCATED, jnp.where(assign, KIND_PIPELINED, 0)
            )
            assigned_kind = s.assigned_kind.at[t].set(
                jnp.where(assign, kind, s.assigned_kind[t])
            )
            assign_pos = s.assign_pos.at[t].set(
                jnp.where(assign, s.step, s.assign_pos[t])
            )
            add_row = jnp.where(assign, a["task_res"][t], jnp.zeros(R, f32))
            job_alloc = (
                s.job_alloc.at[cur_c].add(add_row) if enable_drf else s.job_alloc
            )
            if enable_proportion:
                qcur = a["job_queue"][cur_c]
                q_alloc = s.q_alloc.at[qcur].add(add_row)
                q_alloc_has_sc = s.q_alloc_has_sc.at[qcur].set(
                    s.q_alloc_has_sc[qcur] | (assign & a["task_res_has_sc"][t])
                )
            else:
                q_alloc = s.q_alloc
                q_alloc_has_sc = s.q_alloc_has_sc

            job_active = job_active.at[cur_c].set(
                jnp.where(drop | abandon, False, job_active[cur_c])
            )
            ready_now = ready_cnt[cur_c] >= a["job_min"][cur_c]
            cur_next = jnp.where(drop | abandon | (proc & ready_now), -1, cur)

            return SolveState(
                it=s.it + 1,
                step=s.step + assign.astype(i32),
                cur=cur_next,
                ptr=ptr,
                assigned_node=assigned_node,
                assigned_kind=assigned_kind,
                assign_pos=assign_pos,
                idle=idle,
                rel=rel,
                used=used,
                ntasks=ntasks,
                nports=nports,
                ready_cnt=ready_cnt,
                job_active=job_active,
                q_dropped=q_dropped,
                job_alloc=job_alloc,
                q_alloc=q_alloc,
                q_alloc_has_sc=q_alloc_has_sc,
                paused_at=jnp.where(pause, t, jnp.int32(-1)),
            )

        def cond(s: SolveState):
            return (
                ((s.cur >= 0) | jnp.any(s.job_active))
                & (s.it < max_iter)
                & (s.paused_at < 0)
            )

        (
            it, step, cur, ptr, an, ak, ap,
            ready_cnt, job_active, q_dropped, job_alloc, q_alloc, qahs, paused,
        ) = rep
        state = SolveState(
            it=it, step=step, cur=cur, ptr=ptr,
            assigned_node=an, assigned_kind=ak, assign_pos=ap,
            idle=sh["idle"], rel=sh["rel"], used=sh["used"],
            ntasks=sh["ntasks"], nports=sh["nports"],
            ready_cnt=ready_cnt, job_active=job_active, q_dropped=q_dropped,
            job_alloc=job_alloc, q_alloc=q_alloc, q_alloc_has_sc=qahs,
            paused_at=paused,
        )
        out = lax.while_loop(cond, body, state)
        rep_out = (
            out.it, out.step, out.cur, out.ptr,
            out.assigned_node, out.assigned_kind, out.assign_pos,
            out.ready_cnt, out.job_active, out.q_dropped,
            out.job_alloc, out.q_alloc, out.q_alloc_has_sc, out.paused_at,
        )
        sh_out = {
            "idle": out.idle, "rel": out.rel, "used": out.used,
            "ntasks": out.ntasks, "nports": out.nports,
        }
        return rep_out, sh_out

    smapped = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), sh_specs),
        out_specs=(P(), out_sh_specs),
        check_rep=False,
    )

    def run(a: dict, statics: dict, state: Optional[SolveState]) -> SolveState:
        i32, f32 = jnp.int32, jnp.float32
        n = a["node_idle"].shape[0]
        R = a["task_req"].shape[1]
        p = a["task_ports"].shape[1]
        nr_pad = statics["cnode"].shape[1]
        nf = nr_pad * LANES

        if state is None:
            state = init_state(
                a, enable_drf=enable_drf, enable_proportion=enable_proportion
            )
        state = state._replace(paused_at=jnp.int32(-1))

        def fold2(x):
            xp = jnp.pad(
                jnp.asarray(x, f32), ((0, nf - n), (0, R8 - R))
            )
            return xp.reshape(nr_pad, LANES, R8).transpose(2, 0, 1)

        def fold1(x, dt):
            return jnp.pad(jnp.asarray(x, dt), (0, nf - n)).reshape(nr_pad, LANES)

        if p:
            bits = jnp.sum(
                jnp.asarray(state.nports, i32)
                * (jnp.int32(1) << jnp.arange(p, dtype=i32))[None, :],
                axis=1,
                dtype=i32,
            )
        else:
            bits = jnp.zeros(n, i32)

        sh_in = dict(statics)
        sh_in.update(
            idle=fold2(state.idle),
            rel=fold2(state.rel),
            used=fold2(state.used),
            ntasks=fold1(state.ntasks, i32),
            nports=fold1(bits, i32),
        )
        rep_in = (
            jnp.asarray(state.it, i32), jnp.asarray(state.step, i32),
            jnp.asarray(state.cur, i32), jnp.asarray(state.ptr, i32),
            jnp.asarray(state.assigned_node, i32),
            jnp.asarray(state.assigned_kind, i32),
            jnp.asarray(state.assign_pos, i32),
            jnp.asarray(state.ready_cnt, i32),
            jnp.asarray(state.job_active, bool),
            jnp.asarray(state.q_dropped, bool),
            jnp.asarray(state.job_alloc, f32),
            jnp.asarray(state.q_alloc, f32),
            jnp.asarray(state.q_alloc_has_sc, bool),
            state.paused_at,
        )
        a_rep = {k: v for k, v in a.items() if k not in _DROP}
        rep_out, sh_out = smapped(rep_in, a_rep, sh_in)

        def unfold2(x):
            return x.transpose(1, 2, 0).reshape(nf, R8)[:n, :R]

        def unfold1(x):
            return x.reshape(nf)[:n]

        obits = unfold1(sh_out["nports"])
        if p:
            nports_bool = (
                (obits[:, None] >> jnp.arange(p, dtype=i32)[None, :]) & 1
            ) != 0
        else:
            nports_bool = jnp.zeros((n, 0), bool)
        (
            it, step, cur, ptr, an, ak, ap,
            ready_cnt, job_active, q_dropped, job_alloc, q_alloc, qahs, paused,
        ) = rep_out
        return SolveState(
            it=it, step=step, cur=cur, ptr=ptr,
            assigned_node=an, assigned_kind=ak, assign_pos=ap,
            idle=unfold2(sh_out["idle"]),
            rel=unfold2(sh_out["rel"]),
            used=unfold2(sh_out["used"]),
            ntasks=unfold1(sh_out["ntasks"]),
            nports=nports_bool,
            ready_cnt=ready_cnt, job_active=job_active, q_dropped=q_dropped,
            job_alloc=job_alloc, q_alloc=q_alloc, q_alloc_has_sc=qahs,
            paused_at=paused,
        )

    fresh = jax.jit(partial(run, state=None))
    resume = jax.jit(run)
    return fresh, resume
