"""Multi-chip scaling for the solve kernels.

The reference's only scale axis is a 16-goroutine fan-out per task
(reference util/scheduler_helper.go:34-109) inside one process; the
communication fabric is the Kubernetes API server (SURVEY.md section
2.7). TPU-native, the scale axis is the **node dimension of the cluster
snapshot sharded over a `jax.sharding.Mesh`**: every per-node block of
the solve (feasibility masks, score rows, capacity updates) lives on the
shard that owns those nodes, and XLA's GSPMD partitioner inserts the
collectives (all-reduce argmax for best-node selection, all-gathers for
the scattered capacity updates) over ICI — no hand-written NCCL/MPI
equivalent, per the scaling-book recipe: pick a mesh, annotate shardings,
let XLA place collectives.

`sharded_solve_allocate(arrays, mesh)` is the multi-chip twin of
`ops.solve_allocate`; blockwise node-axis scaling means a 5k-node
snapshot occupies 5k/n_devices rows per chip.

Two rungs share that mesh: `ShardedPallasSolver` (sharded_pallas.py) —
the blocked sharded-Pallas solver, the fused block kernel per shard
with one argmax exchange per gang iteration and a per-shard VMEM gate —
and `ShardedSolver` (sharded.py), the GSPMD-sharded XLA while-loop
twin it degrades to.
"""

from kube_batch_tpu.parallel.sharded import (
    NODE_AXIS_ARRAYS,
    ShardedSolver,
    make_mesh,
    node_shardings,
    sharded_solve_allocate,
    state_shardings,
)
from kube_batch_tpu.parallel.sharded_pallas import ShardedPallasSolver

__all__ = [
    "NODE_AXIS_ARRAYS",
    "ShardedPallasSolver",
    "ShardedSolver",
    "make_mesh",
    "node_shardings",
    "sharded_solve_allocate",
    "state_shardings",
]
