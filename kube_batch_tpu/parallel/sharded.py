"""Node-axis sharding of the allocate solve over a device mesh.

The encoded snapshot's node-axis arrays are partitioned across the mesh's
``nodes`` axis (task/job/queue state is small and replicated); the jitted
while-loop kernel then runs SPMD: each device evaluates feasibility and
scores for its node block, GSPMD reduces the argmax across blocks and
broadcasts the winning assignment's capacity update. Static shapes are
guaranteed by encode.py's power-of-two padding, so any mesh size that
divides the node bucket (8 >= any pow2 mesh) shards cleanly.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kube_batch_tpu.ops.kernels import SolveResult, result_of, solve_allocate_step

# Arrays carrying the node dimension first (see ops/encode.py).
NODE_AXIS_ARRAYS = frozenset(
    {
        "node_idle",
        "node_rel",
        "node_used",
        "node_alloc",
        "node_ok",
        "node_valid",
        "node_max_tasks",
        "node_ntasks",
        "node_idle_has_sc",
        "node_rel_has_sc",
        "node_gid",
        "node_ports",
    }
)

AXIS_NAME = "nodes"


def make_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = AXIS_NAME,
    devices: Optional[list] = None,
) -> Mesh:
    """1-D device mesh over the node axis. Defaults to every visible
    device (ICI within a slice; DCN across slices is the same mesh with
    more devices — XLA picks the transport)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def node_shardings(arrays: dict, mesh: Mesh, axis_name: str = AXIS_NAME) -> dict:
    """PartitionSpec per array: node-axis arrays sharded, rest replicated.
    pod_sc is [task-groups, nodes] — node axis second."""
    out = {}
    for k in arrays:
        if k in NODE_AXIS_ARRAYS:
            spec = P(axis_name)
        elif k == "pod_sc":
            spec = P(None, axis_name)
        else:
            spec = P()
        out[k] = NamedSharding(mesh, spec)
    return out


def sharded_solve_allocate(
    arrays: dict,
    mesh: Mesh,
    axis_name: str = AXIS_NAME,
    enable_drf: bool = False,
    enable_proportion: bool = False,
) -> SolveResult:
    """Run the allocate solve with the node axis sharded over ``mesh``.

    The result arrays (task-axis) come back replicated. jit caches per
    (mesh, shapes), so repeated cycles at stable bucket sizes reuse the
    compiled SPMD program.
    """
    n = mesh.devices.size
    n_nodes = arrays["node_idle"].shape[0]
    if n_nodes % n != 0:
        raise ValueError(
            f"node bucket {n_nodes} not divisible by mesh size {n}; "
            "encode with pad=True (power-of-two buckets)"
        )
    shardings = node_shardings(arrays, mesh, axis_name)
    fn = jax.jit(
        partial(
            solve_allocate_step,
            enable_drf=enable_drf,
            enable_proportion=enable_proportion,
        ),
        in_shardings=(shardings,),
    )
    return result_of(fn(arrays))
