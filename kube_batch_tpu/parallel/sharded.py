"""Node-axis sharding of the allocate solve over a device mesh.

The encoded snapshot's node-axis arrays are partitioned across the mesh's
``nodes`` axis (task/job/queue state is small and replicated); the jitted
while-loop kernel then runs SPMD: each device evaluates feasibility and
scores for its node block, GSPMD reduces the argmax across blocks and
broadcasts the winning assignment's capacity update. Static shapes are
guaranteed by encode.py's bucketing — the node axis pads to multiples of
128 (one lane row), so any power-of-two mesh size up to 128 divides the
bucket and shards cleanly (the action clamps larger meshes).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kube_batch_tpu.ops.kernels import (
    SolveResult,
    SolveState,
    result_of,
    solve_allocate_step,
)

# Arrays carrying the node dimension first (see ops/encode.py).
NODE_AXIS_ARRAYS = frozenset(
    {
        "node_idle",
        "node_rel",
        "node_used",
        "node_alloc",
        "node_ok",
        "node_valid",
        "node_max_tasks",
        "node_ntasks",
        "node_idle_has_sc",
        "node_rel_has_sc",
        "node_gid",
        "node_ports",
    }
)

AXIS_NAME = "nodes"


def make_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = AXIS_NAME,
    devices: Optional[list] = None,
) -> Mesh:
    """1-D device mesh over the node axis. Defaults to every visible
    device (ICI within a slice; DCN across slices is the same mesh with
    more devices — XLA picks the transport)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def node_shardings(arrays: dict, mesh: Mesh, axis_name: str = AXIS_NAME) -> dict:
    """PartitionSpec per array: node-axis arrays sharded, rest replicated.
    pod_sc is [task-groups, nodes] — node axis second."""
    out = {}
    for k in arrays:
        if k in NODE_AXIS_ARRAYS:
            spec = P(axis_name)
        elif k == "pod_sc":
            spec = P(None, axis_name)
        else:
            spec = P()
        out[k] = NamedSharding(mesh, spec)
    return out


def state_shardings(mesh: Mesh, axis_name: str = AXIS_NAME) -> SolveState:
    """Sharding per SolveState field: the node-axis state (idle, rel,
    used, ntasks, nports) follows the array sharding; everything else —
    scalars, task-axis assignment log, job/queue vectors — is replicated
    (tiny next to [N,R])."""
    rep = NamedSharding(mesh, P())
    sh = NamedSharding(mesh, P(axis_name))
    return SolveState(
        it=rep, step=rep, cur=rep, ptr=rep,
        assigned_node=rep, assigned_kind=rep, assign_pos=rep,
        idle=sh, rel=sh, used=sh, ntasks=sh, nports=sh,
        ready_cnt=rep, job_active=rep, q_dropped=rep,
        job_alloc=rep, q_alloc=rep, q_alloc_has_sc=rep,
        paused_at=rep,
    )


class ShardedSolver:
    """The multi-chip production solver behind xla_allocate (conf
    ``actionArguments: {xla_allocate: {mesh: auto}}``): the XLA
    while-loop kernel with its node axis GSPMD-sharded over the mesh.

    Speaks the same SolveState protocol as the single-chip solvers —
    `solve(None)` starts fresh, `solve(state)` resumes after the action's
    host-side pod-affinity step — so the segmented hybrid works
    unchanged: state comes back with node-axis fields sharded, the action
    gathers it to host (`np.array`), patches it, and re-enters; the jit's
    in_shardings scatter it again.

    ``arrays`` may be host numpy or arena handles
    (ops/encode_cache.TensorArena device arrays placed with this mesh's
    shardings): pre-placed arrays already match ``in_shardings``, so
    warm cycles skip the full host->mesh scatter and upload only the
    rows the arena found changed.

    Each loop iteration evaluates feasibility + scores on the local node
    block and GSPMD inserts the cross-device argmax/select for the
    winning node (psum-style reduction over the lone sharded axis riding
    ICI); the capacity update touches one node row, which XLA turns into
    a masked local update. See `sharded_solve_allocate` for the one-shot
    form; bench.py's `xla` twin measures the same program single-chip,
    which is the per-chip price floor of this path.
    """

    def __init__(
        self,
        arrays: dict,
        mesh: Mesh,
        enable_drf: bool = False,
        enable_proportion: bool = False,
        axis_name: str = AXIS_NAME,
    ) -> None:
        n = mesh.devices.size
        n_nodes = arrays["node_idle"].shape[0]
        if n_nodes % n != 0:
            raise ValueError(
                f"node bucket {n_nodes} not divisible by mesh size {n}; "
                "encode with pad=True (node buckets are multiples of 128; meshes up to 128 divide them)"
            )
        self.arrays = arrays
        self.mesh = mesh
        # jitted programs are cached per (devices, axis, key set, flags) —
        # a fresh ShardedSolver every cycle must NOT discard the trace/
        # compile cache (the jit wrappers re-trace per shape bucket
        # internally, so stable buckets hit the compiled program).
        self._fresh, self._resume = _sharded_programs(
            tuple(mesh.devices.flat),
            axis_name,
            frozenset(arrays),
            enable_drf,
            enable_proportion,
        )

    def solve(self, state: Optional[SolveState]) -> SolveState:
        if state is None:
            return self._fresh(self.arrays)
        return self._resume(self.arrays, state)


@lru_cache(maxsize=16)
def _sharded_programs(
    devices: tuple,
    axis_name: str,
    array_keys: frozenset,
    enable_drf: bool,
    enable_proportion: bool,
):
    """(fresh, resume) jitted SPMD programs for a mesh + snapshot layout.
    Keyed on the device tuple (jax Device objects are process singletons),
    the array key set (determines the in_shardings pytree), and the
    static kernel flags; shapes are left to jit's own per-signature
    cache."""
    mesh = Mesh(np.asarray(devices), (axis_name,))
    in_sh = node_shardings(dict.fromkeys(array_keys), mesh, axis_name)
    st_sh = state_shardings(mesh, axis_name)
    step = partial(
        solve_allocate_step,
        enable_drf=enable_drf,
        enable_proportion=enable_proportion,
    )
    fresh = jax.jit(
        lambda a: step(a, None), in_shardings=(in_sh,), out_shardings=st_sh
    )
    resume = jax.jit(step, in_shardings=(in_sh, st_sh), out_shardings=st_sh)
    return fresh, resume


def sharded_solve_allocate(
    arrays: dict,
    mesh: Mesh,
    axis_name: str = AXIS_NAME,
    enable_drf: bool = False,
    enable_proportion: bool = False,
) -> SolveResult:
    """Run the allocate solve with the node axis sharded over ``mesh``.

    The result arrays (task-axis) come back replicated. jit caches per
    (mesh, shapes), so repeated cycles at stable bucket sizes reuse the
    compiled SPMD program.
    """
    n = mesh.devices.size
    n_nodes = arrays["node_idle"].shape[0]
    if n_nodes % n != 0:
        raise ValueError(
            f"node bucket {n_nodes} not divisible by mesh size {n}; "
            "encode with pad=True (node buckets are multiples of 128; meshes up to 128 divide them)"
        )
    shardings = node_shardings(arrays, mesh, axis_name)
    fn = jax.jit(
        partial(
            solve_allocate_step,
            enable_drf=enable_drf,
            enable_proportion=enable_proportion,
        ),
        in_shardings=(shardings,),
    )
    return result_of(fn(arrays))
