"""Lock-discipline markers consumed by the static analyzer
(kube_batch_tpu.analysis.lock_discipline).

The threaded layers (cache, store, workqueue, journal, watch hub)
follow a clone-under-mutex discipline: every attribute declared guarded
must only be touched lexically inside ``with self.<lock>`` or in a
method the caller promises to invoke with the lock held. Two ways to
make that promise, both checked statically:

- name the method with a ``_locked`` suffix (the convention
  ``WatchHub._activate_locked`` already uses), or
- decorate it with :func:`assume_locked`.

``assume_locked`` is a runtime no-op — it exists so the promise is
visible at the definition site and greppable, and so the analyzer can
tell a deliberate lock-held helper from a forgotten ``with``.
"""

from __future__ import annotations

from typing import Callable, TypeVar

_F = TypeVar("_F", bound=Callable)


def assume_locked(fn: _F) -> _F:
    """Mark ``fn`` as called only with its owner's lock already held.

    The lock-discipline analyzer (KBT-L001) exempts the body; the
    caller side remains checked — a call from an unlocked context still
    trips on whatever guarded attribute the helper touches transitively
    only if that caller touches one itself, so keep these helpers small
    and truly internal (leading underscore)."""
    fn.__assume_locked__ = True  # type: ignore[attr-defined]
    return fn
