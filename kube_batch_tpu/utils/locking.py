"""Lock-discipline markers consumed by the static analyzer
(kube_batch_tpu.analysis.lock_discipline).

The threaded layers (cache, store, workqueue, journal, watch hub)
follow a clone-under-mutex discipline: every attribute declared guarded
must only be touched lexically inside ``with self.<lock>`` or in a
method the caller promises to invoke with the lock held. Two ways to
make that promise, both checked statically:

- name the method with a ``_locked`` suffix (the convention
  ``WatchHub._activate_locked`` already uses), or
- decorate it with :func:`assume_locked`.

``assume_locked`` is a runtime no-op — it exists so the promise is
visible at the definition site and greppable, and so the analyzer can
tell a deliberate lock-held helper from a forgotten ``with``.
"""

from __future__ import annotations

import threading
from typing import Callable, TypeVar

_F = TypeVar("_F", bound=Callable)


def assume_locked(fn: _F) -> _F:
    """Mark ``fn`` as called only with its owner's lock already held.

    The lock-discipline analyzer (KBT-L001) exempts the body; the
    caller side remains checked — a call from an unlocked context still
    trips on whatever guarded attribute the helper touches transitively
    only if that caller touches one itself, so keep these helpers small
    and truly internal (leading underscore)."""
    fn.__assume_locked__ = True  # type: ignore[attr-defined]
    return fn


class _WitnessedLock:
    """Context-manager proxy delegating to the wrapped lock while
    reporting acquire/release to the witness. Passes through the
    Condition surface (wait/notify/...) untouched."""

    def __init__(self, witness: "LockOrderWitness", name: str, lock) -> None:
        self._witness = witness
        self._name = name
        self._lock = lock

    def __enter__(self):
        self._lock.acquire()
        self._witness._note_acquire(self._name)
        return self

    def __exit__(self, *exc):
        self._witness._note_release(self._name)
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        got = self._lock.acquire(*a, **kw)
        if got:
            self._witness._note_acquire(self._name)
        return got

    def release(self):
        self._witness._note_release(self._name)
        return self._lock.release()

    def __getattr__(self, attr):  # wait/notify/notify_all/locked/...
        return getattr(self._lock, attr)


class LockOrderWitness:
    """Runtime half of the KBT-D001 lock-order analysis: record the
    observed acquisition order as directed edges (held -> acquired, per
    thread-local held stack) and flag the first reversal.

    The static analyzer (kube_batch_tpu.analysis.lock_order) sees the
    lexical graph; this witness sees the dynamic one — event handlers,
    plugin callbacks, anything dispatched through indirection. Wrap the
    locks under test (``obj._mutex = witness.wrap("SchedulerCache._mutex",
    obj._mutex)``), drive the workload, then assert ``violations == []``
    (the chaos suite does exactly this).

    A violation records both edge sites: the pair was acquired A-then-B
    on one path and B-then-A on another — the classic ABBA interleaving
    that deadlocks under load without ever deadlocking in the test."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._edges: dict[tuple[str, str], str] = {}  #: guarded_by _mu
        self.violations: list[str] = []  #: guarded_by _mu
        # Optional observer called as on_acquire(name) after each
        # acquisition is recorded. The interleaving model checker
        # (kube_batch_tpu.analysis.interleave) hangs its step-footprint
        # recorder here; None costs one attribute read per acquire.
        self.on_acquire: Callable[[str], None] | None = None

    def wrap(self, name: str, lock) -> _WitnessedLock:
        return _WitnessedLock(self, name, lock)

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _note_acquire(self, name: str) -> None:
        held = self._held()
        if held:
            where = threading.current_thread().name
            with self._mu:
                for h in held:
                    if h == name:
                        continue
                    self._edges.setdefault((h, name), where)
                    rev = self._edges.get((name, h))
                    if rev is not None:
                        msg = (
                            f"lock-order reversal: {h} -> {name} "
                            f"(thread {where}) vs {name} -> {h} "
                            f"(thread {rev})"
                        )
                        if msg not in self.violations:
                            self.violations.append(msg)
        held.append(name)
        if self.on_acquire is not None:
            self.on_acquire(name)

    def _note_release(self, name: str) -> None:
        held = self._held()
        if name in held:
            # remove the innermost occurrence (non-LIFO release is legal
            # for plain Locks)
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    def assert_clean(self) -> None:
        with self._mu:
            if self.violations:
                raise AssertionError(
                    "lock-order witness recorded reversals:\n  "
                    + "\n  ".join(self.violations)
                )
