"""Runtime happens-before race witness (the dynamic half of the
KBT-T thread analysis, as :class:`~kube_batch_tpu.utils.locking.
LockOrderWitness` is the dynamic half of KBT-D).

:class:`RaceWitness` is a vector-clock data-race detector in the
Djit+/FastTrack family, sized for drills rather than production:

- ``wrap(name, lock)`` proxies a live lock (same surface as
  ``LockOrderWitness.wrap``); acquire joins the acquirer's clock with
  the lock's clock, release publishes the holder's clock into the lock
  — so two critical sections on one lock are always ordered.
- ``spawn(target)`` returns a thread whose start inherits the parent's
  clock (fork edge) and whose ``join()`` merges the child's final clock
  back (join edge) — so start/join-ordered accesses are ordered.
- ``watch(obj, fields)`` instruments declared hot fields (lane token
  buckets, resident-table patches, mirror entries, lease slot maps,
  fence state) with a lightweight data descriptor: every read/write
  records ``(thread epoch, lock-set, seq)``. Fields holding containers
  mutated in place should be declared ``"touch"`` — a bare attribute
  read is then treated as a potential mutation.

Two accesses to one field conflict when they are not both reads, come
from different threads, share no lock, and neither happens-before the
other under the vector clocks. Each report carries a deterministic
access-trace id (``field:seqA-seqB`` — seq numbers are assigned in
access order, so a deterministic drive reproduces them exactly, the way
KBT-I counterexamples replay under the ``VirtualClock``).

``KBT_RACE_WITNESS=1`` arms the witness inside the smokes/drills that
support it (the streaming chaos drive, the thread-analysis CLI); it is
never on in production paths.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Union

__all__ = [
    "ENV",
    "enabled",
    "RaceWitness",
    "thread_snapshot",
    "leaked_threads",
    "assert_no_leaked_threads",
]

ENV = "KBT_RACE_WITNESS"

_SLOT_PREFIX = "_race_witness$"


def enabled() -> bool:
    """The ``KBT_RACE_WITNESS`` env gate for drives that can arm a
    witness over their hot fields (off by default: instrumented reads
    cost a descriptor call each)."""
    return (os.environ.get(ENV, "") or "").strip().lower() in (
        "1", "true", "on", "yes"
    )


def _join_into(dst: dict, src: dict) -> None:
    for k, v in src.items():
        if v > dst.get(k, 0):
            dst[k] = v


@dataclass(frozen=True)
class _Access:
    token: str  # logical thread id ("T0", "T1", ... in first-seen order)
    thread: str  # OS thread name at access time (for the report only)
    kind: str  # "r" read, "w" write, "t" touch (read of an in-place-mutable)
    stamp: int  # the issuing thread's own clock component at access time
    lockset: frozenset
    seq: int  # global deterministic access sequence number


class _WatchedField:
    """Data descriptor installed on a dynamic subclass by
    :meth:`RaceWitness.watch`. The value lives in the instance dict
    under a mangled slot so the descriptor always wins the lookup."""

    def __init__(self, witness: "RaceWitness", field: str, token: str, mode: str) -> None:
        self._witness = witness
        self._field = field
        self._token = token  # reported name (may alias several fields)
        self._slot = _SLOT_PREFIX + field
        self._mode = mode  # "rw" or "touch"

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._witness._access(self._token, "t" if self._mode == "touch" else "r")
        try:
            return obj.__dict__[self._slot]
        except KeyError:
            raise AttributeError(self._field) from None

    def __set__(self, obj, value) -> None:
        self._witness._access(self._token, "w")
        obj.__dict__[self._slot] = value


class _RaceLock:
    """Context-manager proxy: delegates to the wrapped lock while
    feeding acquire/release sync edges (and the thread's lock-set) to
    the witness. The Condition surface passes through untouched."""

    def __init__(self, witness: "RaceWitness", name: str, lock) -> None:
        self._witness = witness
        self._name = name
        self._lock = lock

    def __enter__(self):
        self._lock.acquire()
        self._witness._note_acquire(self._name)
        return self

    def __exit__(self, *exc):
        self._witness._note_release(self._name)
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        got = self._lock.acquire(*a, **kw)
        if got:
            self._witness._note_acquire(self._name)
        return got

    def release(self):
        self._witness._note_release(self._name)
        return self._lock.release()

    def __getattr__(self, attr):  # wait/notify/notify_all/locked/...
        return getattr(self._lock, attr)


class _WitnessedThread(threading.Thread):
    """Thread whose start is a fork edge and whose join is a join edge."""

    def __init__(
        self, witness: "RaceWitness", snapshot: dict, token: str, *a, **kw
    ) -> None:
        super().__init__(*a, **kw)
        self._race_witness = witness
        self._race_snapshot = snapshot
        self._race_token = token

    def run(self) -> None:
        self._race_witness._thread_begin(self._race_snapshot, self._race_token)
        try:
            super().run()
        finally:
            self._race_witness._thread_end(self)

    def join(self, timeout: Optional[float] = None) -> None:
        super().join(timeout)
        if not self.is_alive():
            self._race_witness._join_edge(self)


class RaceWitness:
    """Vector-clock happens-before detector over wrapped locks,
    witnessed threads and watched fields. Drive the workload, then
    ``assert_clean()`` (or read ``reports``)."""

    # bounded per-field access history: old entries age out FIFO — long
    # drives stay O(1) per field, at the cost of missing races more
    # than HISTORY accesses apart (fine for drill-sized workloads)
    HISTORY = 128

    def __init__(self, clock: Optional[object] = None) -> None:
        self._mu = threading.Lock()
        self._clock = clock  # optional VirtualClock for report stamps
        self._tokens: dict[int, str] = {}  #: guarded_by _mu  (ident -> Tn)
        self._clocks: dict[str, dict] = {}  #: guarded_by _mu  (Tn -> VC)
        self._lock_clocks: dict[str, dict] = {}  #: guarded_by _mu
        self._locksets: dict[int, list] = {}  #: guarded_by _mu  (ident -> held)
        self._accesses: dict[str, list] = {}  #: guarded_by _mu  (field -> [_Access])
        self._final: dict[int, dict] = {}  #: guarded_by _mu  (thread id() -> VC)
        self._reported: set = set()  #: guarded_by _mu
        self._watched_classes: dict = {}  #: guarded_by _mu
        self._seq = 0  #: guarded_by _mu
        self._ntok = 0  #: guarded_by _mu
        self.reports: list[str] = []  #: guarded_by _mu
        # Optional observer called as on_access(name) after each watched
        # access. The interleaving model checker hangs its step-footprint
        # recorder here (field-level KBT-I002); None costs one attribute
        # read per access.
        self.on_access: Callable[[str], None] | None = None

    # -- clock plumbing ------------------------------------------------------

    def _token_locked(self, ident: int) -> str:
        tok = self._tokens.get(ident)
        if tok is None:
            tok = self._new_token_locked()
            self._tokens[ident] = tok
            self._clocks[tok] = {tok: 1}
        return tok

    def _new_token_locked(self) -> str:
        tok = f"T{self._ntok}"
        self._ntok += 1
        return tok

    def _note_acquire(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            tok = self._token_locked(ident)
            _join_into(self._clocks[tok], self._lock_clocks.get(name, {}))
            self._locksets.setdefault(ident, []).append(name)

    def _note_release(self, name: str) -> None:
        ident = threading.get_ident()
        with self._mu:
            tok = self._token_locked(ident)
            vc = self._clocks[tok]
            lc = self._lock_clocks.setdefault(name, {})
            _join_into(lc, vc)
            vc[tok] = vc.get(tok, 0) + 1
            held = self._locksets.get(ident, [])
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    def _thread_begin(self, snapshot: dict, tok: str) -> None:
        # the token was allocated at spawn() time (deterministic spawn
        # order), NOT derived from the OS ident — idents are recycled,
        # and a recycled ident must not inherit a dead thread's clock
        ident = threading.get_ident()
        with self._mu:
            self._tokens[ident] = tok
            vc = dict(snapshot)
            vc[tok] = vc.get(tok, 0) + 1
            self._clocks[tok] = vc

    def _thread_end(self, thread: threading.Thread) -> None:
        ident = threading.get_ident()
        with self._mu:
            tok = self._token_locked(ident)
            self._final[id(thread)] = dict(self._clocks[tok])
            self._tokens.pop(ident, None)  # the ident may be recycled
            self._locksets.pop(ident, None)

    def _join_edge(self, thread: threading.Thread) -> None:
        ident = threading.get_ident()
        with self._mu:
            final = self._final.get(id(thread))
            if final is not None:
                tok = self._token_locked(ident)
                _join_into(self._clocks[tok], final)

    # -- public wiring -------------------------------------------------------

    def wrap(self, name: str, lock) -> _RaceLock:
        return _RaceLock(self, name, lock)

    def spawn(
        self,
        target: Callable,
        *,
        name: Optional[str] = None,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        daemon: bool = True,
    ) -> _WitnessedThread:
        """A thread carrying fork/join happens-before edges. Not
        started; the caller starts and (bounded-)joins it."""
        ident = threading.get_ident()
        with self._mu:
            tok = self._token_locked(ident)
            vc = self._clocks[tok]
            snapshot = dict(vc)
            vc[tok] = vc.get(tok, 0) + 1
            child_tok = self._new_token_locked()
        return _WitnessedThread(
            self, snapshot, child_tok,
            target=target, name=name, args=args, kwargs=kwargs or {},
            daemon=daemon,
        )

    def watch(
        self,
        obj,
        fields: Union[Iterable[str], dict],
        token: Optional[str] = None,
    ):
        """Instrument ``obj``'s listed fields in place (the instance is
        moved onto a dynamic subclass carrying the descriptors).
        ``fields`` is an iterable (read/write semantics) or a
        ``{field: "rw" | "touch"}`` dict — declare ``"touch"`` for
        containers mutated in place, so a bare read counts as a
        potential write. ``token`` aliases every field to one reported
        name (the interleave footprint tokens); default is
        ``ClassName.field``. Returns ``obj``."""
        modes = dict(fields) if isinstance(fields, dict) else {
            f: "rw" for f in fields
        }
        cls = type(obj)
        key = (cls, tuple(sorted(modes.items())), token)
        with self._mu:
            sub = self._watched_classes.get(key)
            if sub is None:
                ns = {
                    f: _WatchedField(
                        self, f, token or f"{cls.__name__}.{f}", mode
                    )
                    for f, mode in modes.items()
                }
                ns["_race_witness_base"] = cls
                sub = type(cls.__name__, (cls,), ns)
                self._watched_classes[key] = sub
        for f in modes:
            if f in obj.__dict__:
                obj.__dict__[_SLOT_PREFIX + f] = obj.__dict__.pop(f)
        obj.__class__ = sub
        return obj

    @staticmethod
    def unwatch(obj):
        """Restore a watched instance to its original class (teardown
        hygiene so witness-free asserts see plain attributes)."""
        base = getattr(type(obj), "_race_witness_base", None)
        if base is None:
            return obj
        for slot in [k for k in obj.__dict__ if k.startswith(_SLOT_PREFIX)]:
            obj.__dict__[slot[len(_SLOT_PREFIX):]] = obj.__dict__.pop(slot)
        obj.__class__ = base
        return obj

    # -- detection -----------------------------------------------------------

    def _access(self, field: str, kind: str) -> None:
        ident = threading.get_ident()
        observer = self.on_access
        with self._mu:
            tok = self._token_locked(ident)
            vc = self._clocks[tok]
            seq = self._seq
            self._seq += 1
            lockset = frozenset(self._locksets.get(ident, ()))
            cur = _Access(
                tok, threading.current_thread().name, kind,
                vc.get(tok, 0), lockset, seq,
            )
            hist = self._accesses.setdefault(field, [])
            for prior in hist:
                if prior.token == tok:
                    continue
                if prior.kind == "r" and kind == "r":
                    continue
                if prior.stamp <= vc.get(prior.token, 0):
                    continue  # ordered by happens-before
                if prior.lockset & lockset:
                    continue  # a common lock orders them (defensive)
                dedup = (field, prior.token, tok, prior.kind, kind)
                if dedup in self._reported:
                    continue
                self._reported.add(dedup)
                stamp = (
                    f" t={self._clock.now():g}"
                    if self._clock is not None and hasattr(self._clock, "now")
                    else ""
                )
                self.reports.append(
                    f"race on {field}:{stamp} {_KINDS[kind]} by {tok} "
                    f"({cur.thread}, locks={sorted(lockset) or '{}'}) is "
                    f"unordered with {_KINDS[prior.kind]} by {prior.token} "
                    f"({prior.thread}, locks={sorted(prior.lockset) or '{}'}) "
                    f"[trace {field}:{prior.seq}-{seq}]"
                )
            hist.append(cur)
            if len(hist) > self.HISTORY:
                del hist[: len(hist) - self.HISTORY]
        if observer is not None:
            observer(field)

    def assert_clean(self) -> None:
        with self._mu:
            if self.reports:
                raise AssertionError(
                    "race witness recorded unordered conflicting accesses:\n  "
                    + "\n  ".join(self.reports)
                )


_KINDS = {"r": "read", "w": "write", "t": "touch"}


# -- leaked-thread teardown helper --------------------------------------------


def thread_snapshot() -> set:
    """idents of currently-alive threads (take before the code under
    test starts any)."""
    return {t.ident for t in threading.enumerate()}


def leaked_threads(
    before: set,
    *,
    grace_s: float = 2.0,
    include_daemon: bool = False,
) -> list:
    """Threads alive now that were not in ``before``, after a bounded
    grace join. Non-daemon leaks hang interpreter shutdown and always
    count; daemon leaks (a pump whose ``stop()`` was never called)
    count only with ``include_daemon`` — prefixes ``kb-``/``kbt-`` name
    this package's own thread roots in the report."""
    fresh = [
        t for t in threading.enumerate()
        if t.ident not in before and t is not threading.current_thread()
    ]
    deadline = time.monotonic() + grace_s
    for t in fresh:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        t.join(timeout=remaining)
    return [
        t for t in fresh
        if t.is_alive() and (include_daemon or not t.daemon)
    ]


def assert_no_leaked_threads(before: set, **kw) -> None:
    leaked = leaked_threads(before, **kw)
    if leaked:
        raise AssertionError(
            "leaked thread(s) past teardown: "
            + ", ".join(
                f"{t.name}{' (daemon)' if t.daemon else ''}" for t in leaked
            )
            + " — every start() needs a reachable bounded join/stop path"
        )
