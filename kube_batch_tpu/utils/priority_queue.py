"""Heap-backed scheduling queue ordered by a caller-supplied less-fn
(reference pkg/scheduler/util/priority_queue.go:26-100).

The less-fn returns True when the left item should pop before the right
item, exactly like the reference's ``api.LessFn``. The item that the
less-fn ranks first pops first; ties keep insertion order (the Go heap
does not guarantee tie stability, but determinism here makes the serial
path reproducible, which the XLA-equivalence property tests rely on).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, Optional

LessFn = Callable[[Any, Any], bool]


class _Item:
    __slots__ = ("value", "seq", "less_fn")

    def __init__(self, value: Any, seq: int, less_fn: Optional[LessFn]) -> None:
        self.value = value
        self.seq = seq
        self.less_fn = less_fn

    def __lt__(self, other: "_Item") -> bool:
        if self.less_fn is not None:
            if self.less_fn(self.value, other.value):
                return True
            if self.less_fn(other.value, self.value):
                return False
        # Stable tie-break by insertion order (deterministic pops).
        return self.seq < other.seq


class PriorityQueue:
    """reference priority_queue.go:26-67."""

    def __init__(self, less_fn: Optional[LessFn] = None) -> None:
        self._less_fn = less_fn
        self._heap: list[_Item] = []
        self._seq = itertools.count()

    def push(self, value: Any) -> None:
        heapq.heappush(self._heap, _Item(value, next(self._seq), self._less_fn))

    def pop(self) -> Any:
        if not self._heap:
            return None
        return heapq.heappop(self._heap).value

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)
