"""Rate-limited retry queue (the shape of client-go's
``workqueue.RateLimitingInterface`` as the cache uses it:
``cache/cache.go:103-106`` — errTasks / deletedJobs).

Semantics kept from the reference's DefaultControllerRateLimiter usage:

- ``add_rate_limited(item)`` enqueues after a per-item exponential
  backoff (base 5ms doubling to a 1s cap — client-go's
  ItemExponentialFailureRateLimiter defaults, scaled for an in-process
  store where there is no network RTT to hide);
- duplicate adds of an item already waiting or queued coalesce;
- ``get(timeout)`` blocks for a ready item (None on timeout/shutdown);
- ``done(item)`` must follow every successful ``get`` before the item
  can be re-added (mirrors workqueue's processing-set semantics);
- ``forget(item)`` resets the item's failure count.

Items are identified by a caller-supplied key function (defaults to the
item itself) so mutable TaskInfo/JobInfo objects can ride the queue.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Callable, Optional

from kube_batch_tpu.utils.locking import assume_locked

_BASE_DELAY = 0.005
_MAX_DELAY = 1.0


class RateLimitingQueue:
    def __init__(self, key_fn: Optional[Callable[[Any], Any]] = None) -> None:
        self._key = key_fn or (lambda item: item)
        self._cond = threading.Condition()
        self._heap: list[tuple[float, int, Any]] = []  # (ready_at, seq, key)
        self._items: dict[Any, Any] = {}  # key -> newest item payload
        self._pending: set = set()  # keys waiting or queued
        self._processing: set = set()
        self._dirty: dict[Any, float] = {}  # re-added while processing -> ready_at
        self._failures: dict[Any, int] = {}
        self._seq = 0
        self._shutdown = False

    @assume_locked
    def _delay(self, key: Any) -> float:
        n = self._failures.get(key, 0)
        self._failures[key] = n + 1
        return min(_BASE_DELAY * (2**n), _MAX_DELAY)

    def add(self, item: Any) -> None:
        self._add(item, 0.0)

    def add_rate_limited(self, item: Any) -> None:
        with self._cond:
            delay = self._delay(self._key(item))
        self._add(item, delay)

    def _add(self, item: Any, delay: float) -> None:
        key = self._key(item)
        with self._cond:
            if self._shutdown:
                return
            self._items[key] = item
            ready_at = time.monotonic() + delay
            if key in self._processing:
                # Keep the earliest requested ready time; done() requeues
                # at it so the rate-limit delay is not discarded.
                self._dirty[key] = min(self._dirty.get(key, ready_at), ready_at)
                return
            if key in self._pending:
                return
            self._pending.add(key)
            self._seq += 1
            heapq.heappush(self._heap, (ready_at, self._seq, key))
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._shutdown:
                    return None
                now = time.monotonic()
                if self._heap and self._heap[0][0] <= now:
                    _, _, key = heapq.heappop(self._heap)
                    self._pending.discard(key)
                    self._processing.add(key)
                    return self._items[key]
                if self._heap:
                    wait = self._heap[0][0] - now
                else:
                    wait = None
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, item: Any) -> None:
        key = self._key(item)
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                ready_at = self._dirty.pop(key)
                self._pending.add(key)
                self._seq += 1
                heapq.heappush(self._heap, (ready_at, self._seq, key))
                self._cond.notify()
            elif key not in self._pending:
                self._items.pop(key, None)

    def forget(self, item: Any) -> None:
        with self._cond:
            self._failures.pop(self._key(item), None)

    def failures(self, item: Any) -> int:
        """Rate-limited adds recorded for this item since the last
        forget (client-go's NumRequeues) — what a caller's terminal-drop
        budget compares against."""
        with self._cond:
            return self._failures.get(self._key(item), 0)

    def __len__(self) -> int:
        with self._cond:
            return len(self._pending) + len(self._processing)

    def shut_down(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def restart(self) -> None:
        """Reopen after shut_down (queued items survive); lets an owner
        stop() and later run() again without hot-spinning its workers
        on a permanently shut queue."""
        with self._cond:
            self._shutdown = False
