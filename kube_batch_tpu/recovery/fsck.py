"""Offline journal checker: ``python -m kube_batch_tpu.recovery.fsck``.

Reads a write-intent journal (no store needed, no locks taken) and
reports what a takeover would find: total intents, confirmed, orphaned
(in flight at crash time), the gang statements those orphans belong to,
and corrupt lines (torn tail). The operator's first move after an
unclean leader death — before deciding whether to let reconciliation
run or to intervene.

Exit codes: 0 = journal readable (orphans are *normal* after a crash
and reported, not fatal); 1 = unreadable/corrupt beyond the tolerated
torn tail, or orphans present under ``--strict``.

Usage::

    python -m kube_batch_tpu.recovery.fsck /var/lib/kbt/journal.wal
    python -m kube_batch_tpu.recovery.fsck --json journal.wal   # machine-readable
    python -m kube_batch_tpu.recovery.fsck --strict journal.wal # orphans -> rc 1
"""

from __future__ import annotations

import argparse
import json
import sys

from kube_batch_tpu.recovery.journal import WriteIntentJournal


def fsck(path: str) -> dict:
    """Journal health summary (the --json payload)."""
    replay = WriteIntentJournal.replay(path)
    orphans = replay.orphans
    gangs: dict[str, int] = {}
    for intent in orphans:
        key = f"cycle={intent.cycle} gang={intent.gang or '<none>'}"
        gangs[key] = gangs.get(key, 0) + 1
    return {
        "path": path,
        "intents": len(replay.intents),
        "confirmed": len(replay.confirmed),
        "orphaned": len(orphans),
        "corrupt_lines": replay.corrupt,
        "orphaned_gangs": gangs,
        "orphans": [
            {
                "seq": i.seq,
                "cycle": i.cycle,
                "op": i.op,
                "gang": i.gang,
                "pod": i.pod,
                "node": i.node,
            }
            for i in orphans
        ],
    }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="kube_batch_tpu.recovery.fsck",
        description="check a bind-intent journal for in-flight writes",
    )
    p.add_argument("journal", help="journal file path (KBT_JOURNAL of the dead leader)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument(
        "--strict", action="store_true",
        help="exit 1 when orphaned intents exist (CI gates on a clean journal)",
    )
    opt = p.parse_args(argv)
    try:
        summary = fsck(opt.journal)
    except Exception as e:  # noqa: BLE001 - CLI boundary
        print(f"fsck: {opt.journal}: unreadable: {e}", file=sys.stderr)
        return 1
    if opt.json:
        print(json.dumps(summary))
    else:
        print(
            f"fsck: {summary['path']}: {summary['intents']} intent(s), "
            f"{summary['confirmed']} confirmed, {summary['orphaned']} orphaned, "
            f"{summary['corrupt_lines']} corrupt line(s)"
        )
        for gang, n in sorted(summary["orphaned_gangs"].items()):
            print(f"fsck:   in-flight statement: {gang} ({n} intent(s))")
        for o in summary["orphans"]:
            print(
                f"fsck:   seq={o['seq']} {o['op']} {o['pod']}"
                + (f" -> {o['node']}" if o["node"] else "")
            )
    if opt.strict and summary["orphaned"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
