"""Takeover reconciliation: journal vs ClusterStore truth.

Run on lease acquire and on process restart (server.py wires both
through ``SchedulerServer.start``), before the scheduling loop touches
the world. For every orphaned intent (appended, never confirmed — the
in-flight set when the previous leader died):

- **landed** — the store already shows the write (bind: pod bound to
  the intended node; evict: pod gone): confirm it in the journal;
- **orphaned** — the write never reached the store: re-dispatch it
  idempotently through the store (a bind writes ``node_name``, an evict
  deletes the pod), exactly what the dead leader's write pool would
  have done;
- **conflicted** — the store moved on (pod bound elsewhere, or already
  Running under another binder's authority): leave it alone and count
  it; store truth wins, the Omega rule.

Gang atomicity: intents are grouped by (cycle, gang). If any member of
a gang cannot be completed (its pod or target node vanished while the
leader was down), the whole gang rolls back — every member bind this
takeover re-dispatched is undone in reverse order, and every
already-landed member bind of the same gang statement is unbound (only
while the pod is still Pending: a pod the kubelet-equivalent already
started running is past the point of cheap rollback and is left to the
eviction machinery). This is the Statement discipline
(framework/statement.py: op log, commit forward, reverse-order
discard) applied at the store level, so a leader crash mid-bulk-bind
can never strand a half-bound gang below its min_member barrier.

The ``reconcile.scan`` fault point aborts the scan mid-way (takeover
under a corrupted journal / injected failure): reconciliation logs and
returns partial — the standby's normal scheduling loop then self-heals
the still-pending pods, slower but never wrong.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import TYPE_CHECKING

from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.recovery.journal import Intent, WriteIntentJournal

if TYPE_CHECKING:
    from kube_batch_tpu.apis.types import Pod
    from kube_batch_tpu.cache.store import ClusterStore


@dataclass
class ReconcileReport:
    """What a takeover scan found and did (the glog summary's data)."""

    scanned: int = 0
    confirmed: int = 0  # landed writes, confirmed in the journal
    redispatched: int = 0  # orphaned writes re-driven through the store
    conflicts: int = 0  # store truth diverged; left alone
    rolled_back: int = 0  # binds undone for gang atomicity
    gangs_rolled_back: list[str] = field(default_factory=list)
    aborted: bool = False  # scan died mid-way (journal.replay / reconcile.scan)

    def as_dict(self) -> dict:
        return {
            "scanned": self.scanned,
            "confirmed": self.confirmed,
            "redispatched": self.redispatched,
            "conflicts": self.conflicts,
            "rolled_back": self.rolled_back,
            "gangs_rolled_back": list(self.gangs_rolled_back),
            "aborted": self.aborted,
        }


class _GangStatement:
    """Store-level statement for one gang's reconciliation: forward ops
    append to the log; ``discard`` undoes them in reverse order
    (framework/statement.py's contract against the store instead of a
    session)."""

    def __init__(self, store: "ClusterStore") -> None:
        self._store = store
        self._ops: list[tuple[str, str]] = []  # (op, pod_key)

    def bind(self, pod: "Pod", node: str) -> None:
        self._store.update_pod(dataclasses.replace(pod, node_name=node))
        self._ops.append(("bind", f"{pod.namespace}/{pod.name}"))

    def evict(self, pod: "Pod") -> None:
        self._store.delete_pod(pod.namespace, pod.name)
        self._ops.append(("evict", f"{pod.namespace}/{pod.name}"))

    def __len__(self) -> int:
        return len(self._ops)

    def discard(self) -> int:
        """Undo in reverse order; returns ops undone. Evicts are not
        recreated (the pod object is gone — an evict that should not
        have happened is re-ingested by the owner, as in the reference);
        binds are unbound while the pod is still Pending."""
        undone = 0
        for op, pod_key in reversed(self._ops):
            if op != "bind":
                continue
            ns, _, name = pod_key.partition("/")
            pod = self._store.get_pod(ns, name)
            if pod is not None and pod.phase == PodPhase.PENDING and pod.node_name:
                self._store.update_pod(dataclasses.replace(pod, node_name=""))
                undone += 1
        self._ops.clear()
        return undone


def _unbind_landed(store: "ClusterStore", intents: list[Intent]) -> int:
    """Roll back the already-landed binds of a gang statement (the ones
    the dead leader's write pool completed before the crash)."""
    undone = 0
    for intent in intents:
        if intent.op != "bind":
            continue
        ns, _, name = intent.pod.partition("/")
        pod = store.get_pod(ns, name)
        if (
            pod is not None
            and pod.phase == PodPhase.PENDING
            and pod.node_name == intent.node
        ):
            store.update_pod(dataclasses.replace(pod, node_name=""))
            undone += 1
    return undone


def reconcile_journal(
    journal: WriteIntentJournal, store: "ClusterStore"
) -> ReconcileReport:
    """Scan the journal against store truth; see module docstring.
    Never raises: a takeover must proceed (degraded, loudly) even when
    reconciliation cannot."""
    report = ReconcileReport()
    try:
        replay = WriteIntentJournal.replay(journal.path)
    except Exception as e:  # noqa: BLE001 - unreadable journal degrades
        log.errorf(
            "journal %s unreadable at takeover (%s); relying on resync self-heal",
            journal.path, e,
        )
        metrics.register_reconcile_op("aborted")
        report.aborted = True
        return report
    orphans = replay.orphans
    if replay.corrupt:
        log.warningf(
            "journal %s: %d corrupt line(s) (torn tail?) skipped",
            journal.path, replay.corrupt,
        )
    if not orphans:
        return report

    # Group the in-flight set by gang statement; members of one
    # statement commit or roll back together.
    by_gang: dict[tuple[int, str], list[Intent]] = {}
    for intent in orphans:
        by_gang.setdefault((intent.cycle, intent.gang), []).append(intent)

    try:
        for (cycle, gang), members in sorted(by_gang.items()):
            stmt = _GangStatement(store)
            landed: list[Intent] = []
            confirm_seqs: list[int] = []
            failed_member = None
            for intent in members:
                if faults.should_fire("reconcile.scan"):
                    raise faults.FaultInjected("reconcile.scan: injected scan failure")
                report.scanned += 1
                ns, _, name = intent.pod.partition("/")
                pod = store.get_pod(ns, name)
                if intent.op == "evict":
                    if pod is None:
                        confirm_seqs.append(intent.seq)  # landed
                        report.confirmed += 1
                    else:
                        stmt.evict(pod)
                        confirm_seqs.append(intent.seq)
                        report.redispatched += 1
                    continue
                # bind intent
                if pod is None or store.get("nodes", intent.node) is None:
                    failed_member = intent  # gang cannot complete
                    break
                if pod.node_name == intent.node:
                    landed.append(intent)
                    confirm_seqs.append(intent.seq)
                    report.confirmed += 1
                elif pod.node_name:
                    # bound elsewhere meanwhile: store truth wins
                    confirm_seqs.append(intent.seq)
                    report.conflicts += 1
                else:
                    stmt.bind(pod, intent.node)
                    confirm_seqs.append(intent.seq)
                    report.redispatched += 1
            if failed_member is not None:
                undone = stmt.discard() + _unbind_landed(store, landed)
                report.rolled_back += undone
                report.gangs_rolled_back.append(gang)
                metrics.register_reconcile_op("rolled_back", max(1, undone))
                log.errorf(
                    "reconcile: gang %s (cycle %d) cannot complete "
                    "(%s unfixable: pod or node vanished); rolled back %d "
                    "bind(s) to preserve gang atomicity",
                    gang or "<none>", cycle, failed_member.pod, undone,
                )
                # The gang's intents are resolved either way: confirm
                # them so the next takeover does not re-litigate a
                # statement this one already rolled back.
                for intent in members:
                    journal.confirm(intent.seq)
                continue
            for seq in confirm_seqs:
                journal.confirm(seq)
    except Exception as e:  # noqa: BLE001 - takeover proceeds degraded
        log.errorf(
            "reconciliation aborted mid-scan (%s); remaining orphans left "
            "to the resync/rescheduling self-heal", e,
        )
        metrics.register_reconcile_op("aborted")
        report.aborted = True
        return report
    journal.compact()
    for op, n in (
        ("confirmed", report.confirmed),
        ("redispatched", report.redispatched),
        ("conflict", report.conflicts),
    ):
        if n:
            metrics.register_reconcile_op(op, n)
    log.infof(
        "reconcile: scanned %d in-flight intent(s): %d landed, %d "
        "re-dispatched, %d conflict(s), %d bind(s) rolled back",
        report.scanned, report.confirmed, report.redispatched,
        report.conflicts, report.rolled_back,
    )
    return report
