"""Write-intent journal: the bind/evict write-ahead log.

Protocol (the Omega-style optimistic-transaction discipline applied to
our async write pool, cache/cache.py):

1. **append-before-dispatch** — the cache appends one ``intent`` record
   per bind/evict (cycle id, gang id, pod key, target node, statement
   kind) and flushes it to disk *before* submitting the store write to
   the pool;
2. **confirm-after-ack** — once the store write acks, the cache appends
   a ``confirm`` record for that intent's sequence number.

A leader killed between (1) and (2) leaves *orphaned* intents: the
journal knows exactly which writes were in flight, so a standby (or the
restarted process) can reconcile them against store truth instead of
guessing (recovery/reconcile.py). An intent whose write failed and fell
to the errTasks resync queue also stays orphaned — reconciliation at
the next takeover confirms or re-dispatches it, which is idempotent
with the resync path.

Format: JSON lines, append-only. ``{"rec": "intent", "seq": N,
"cycle": C, "op": "bind"|"evict", "gang": job_uid, "pod": "ns/name",
"node": host}`` and ``{"rec": "confirm", "seq": N}``. Torn tails (a
crash mid-append) are tolerated: replay stops parsing a malformed last
line and reports it, matching WAL practice.

Durability: records are flushed (``flush`` + optional ``fsync``) before
dispatch. The default is flush-only — the failure model is process
death (SIGKILL, OOM), where OS-buffered data survives; ``fsync=True``
extends coverage to host power loss at a per-batch fsync cost.

Availability over protection: a journal append failure (disk full,
injected ``journal.append`` fault) must not brick the scheduler — the
cache logs, meters, and dispatches the write *unjournaled* for that
batch. Degraded crash-consistency is loud, never a wedged write side.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Optional

from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.utils.locking import assume_locked


@dataclass(frozen=True)
class Intent:
    """One journaled write intent (the parsed ``intent`` record)."""

    seq: int
    cycle: int
    op: str  # statement kind: "bind" | "evict"
    gang: str  # job uid the task belongs to ("" for gang-less writes)
    pod: str  # "ns/name"
    node: str  # target host for binds; "" for evicts


@dataclass
class ReplayResult:
    """What a journal file says happened (fsck + reconciliation input)."""

    intents: dict[int, Intent]  # every intent record, by seq
    confirmed: set[int]  # seqs with a confirm record
    corrupt: int  # unparseable lines (torn tail, bit rot)

    @property
    def orphans(self) -> list[Intent]:
        """Intents with no confirm — the in-flight set at crash time."""
        return [i for s, i in sorted(self.intents.items()) if s not in self.confirmed]


class WriteIntentJournal:
    """Append-only WAL over one file; thread-safe (the cache's write
    pool confirms from multiple threads)."""

    # Confirmed records are dead weight; once this many have
    # accumulated, the next append rewrites the file with only the
    # outstanding intents (atomic tmp+rename), bounding journal growth
    # on a long-lived leader.
    COMPACT_THRESHOLD = 4096

    def __init__(self, path: str, fsync: bool = False) -> None:
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        self._outstanding: dict[int, Intent] = {}
        self._confirmed_since_compact = 0
        self._next_seq = 1
        # Resume from an existing journal (restart without takeover —
        # the owner is expected to reconcile, but seq numbering must be
        # monotonic regardless).
        if os.path.exists(path):
            replay = self.replay(path)
            self._outstanding = {i.seq: i for i in replay.orphans}
            if replay.intents:
                self._next_seq = max(replay.intents) + 1
        self._fh = open(path, "a", encoding="utf-8")  # noqa: SIM115 - journal lifetime

    # -- write side ---------------------------------------------------------

    def append_intents(
        self,
        op: str,
        entries: list[tuple[str, str, str]],
        cycle: int = 0,
        trace: str = "",
        explain: dict | None = None,
    ) -> list[int]:
        """Append one ``intent`` record per (gang, pod_key, node) entry
        as a single flushed write; returns the assigned seqs (parallel
        to ``entries``). Raises on I/O failure or the ``journal.append``
        fault — the caller decides whether to dispatch unprotected.

        ``trace`` is the dispatching cycle's trace id (kube_batch_tpu.obs);
        when set it rides each intent record so a takeover post-mortem
        can join the journal against a flight-recorder dump. ``explain``
        maps gang uid -> compact forensics payload (obs.explain
        intent_payload); when the dispatching gang has one it rides the
        intent record, giving the journal labeled (state, decision,
        reason) tuples. ``replay`` ignores unknown keys, so old journals
        and traceless/explainless writers stay fully compatible."""
        if not entries:
            return []
        if faults.should_fire("journal.append"):
            raise faults.FaultInjected("journal.append: injected journal I/O failure")
        with self._lock:
            seqs = list(range(self._next_seq, self._next_seq + len(entries)))
            self._next_seq += len(entries)
            lines = []
            for seq, (gang, pod, node) in zip(seqs, entries):
                intent = Intent(
                    seq=seq, cycle=cycle, op=op, gang=gang, pod=pod, node=node
                )
                self._outstanding[seq] = intent
                rec = {
                    "rec": "intent",
                    "seq": seq,
                    "cycle": cycle,
                    "op": op,
                    "gang": gang,
                    "pod": pod,
                    "node": node,
                }
                if trace:
                    rec["trace"] = trace
                if explain and gang in explain:
                    rec["explain"] = explain[gang]
                lines.append(json.dumps(rec, separators=(",", ":")))
            self._write("\n".join(lines) + "\n")
        metrics.register_journal_records("intent", len(entries))
        return seqs

    def confirm(self, seq: int) -> None:
        """The store write for ``seq`` acked; the intent is no longer in
        flight. Unknown/already-confirmed seqs are no-ops (idempotent —
        reconciliation and the write pool may both confirm)."""
        with self._lock:
            if self._outstanding.pop(seq, None) is None:
                return
            self._write(
                json.dumps({"rec": "confirm", "seq": seq}, separators=(",", ":"))
                + "\n"
            )
            self._confirmed_since_compact += 1
            compact = self._confirmed_since_compact >= self.COMPACT_THRESHOLD
        metrics.register_journal_records("confirm", 1)
        if compact:
            self.compact()

    @assume_locked
    def _write(self, data: str) -> None:
        self._fh.write(data)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    # -- maintenance --------------------------------------------------------

    def outstanding(self) -> list[Intent]:
        with self._lock:
            return [self._outstanding[s] for s in sorted(self._outstanding)]

    def compact(self) -> None:
        """Rewrite the file with only the outstanding intents (atomic
        tmp+rename); confirmed history is dropped."""
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as out:
                for seq in sorted(self._outstanding):
                    i = self._outstanding[seq]
                    out.write(
                        json.dumps(
                            {
                                "rec": "intent",
                                "seq": i.seq,
                                "cycle": i.cycle,
                                "op": i.op,
                                "gang": i.gang,
                                "pod": i.pod,
                                "node": i.node,
                            },
                            separators=(",", ":"),
                        )
                        + "\n"
                    )
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")  # noqa: SIM115
            self._confirmed_since_compact = 0
            outstanding = len(self._outstanding)
        log.V(3).infof("journal %s compacted (%d outstanding)", self.path, outstanding)

    def close(self) -> None:
        with self._lock:
            self._fh.close()

    # -- read side ----------------------------------------------------------

    @staticmethod
    def replay(path: str) -> ReplayResult:
        """Parse a journal file into intents + confirms. Malformed lines
        (torn tail) are counted, not fatal. The ``journal.replay`` fault
        point simulates an unreadable journal at takeover."""
        if faults.should_fire("journal.replay"):
            raise faults.FaultInjected("journal.replay: injected replay failure")
        intents: dict[int, Intent] = {}
        confirmed: set[int] = set()
        corrupt = 0
        if not os.path.exists(path):
            return ReplayResult(intents, confirmed, 0)
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    kind = rec["rec"]
                    if kind == "intent":
                        intent = Intent(
                            seq=int(rec["seq"]),
                            cycle=int(rec.get("cycle", 0)),
                            op=str(rec["op"]),
                            gang=str(rec.get("gang", "")),
                            pod=str(rec["pod"]),
                            node=str(rec.get("node", "")),
                        )
                        intents[intent.seq] = intent
                    elif kind == "confirm":
                        confirmed.add(int(rec["seq"]))
                    else:
                        corrupt += 1
                except (ValueError, KeyError, TypeError):
                    corrupt += 1
        return ReplayResult(intents, confirmed, corrupt)


def journal_from_env() -> Optional[WriteIntentJournal]:
    """The ``KBT_JOURNAL`` env path, or None (journaling off)."""
    path = os.environ.get("KBT_JOURNAL", "").strip()
    return WriteIntentJournal(path) if path else None
