"""Crash-consistent failover for the bind/evict write side.

The reference scheduler gets recovery for free: the Kubernetes
apiserver is the durable source of truth and informers resync the world
after a restart (SURVEY §2.2, cache.go:187-300). Our in-process
ClusterStore + lease elector reproduce *election* but, before this
package, not *recovery*: a leader killed mid-``bind_many`` left
assumed-but-unconfirmed binds that the standby neither replayed nor
reconciled. Omega/Borg-class schedulers treat optimistic transactions
plus conflict reconciliation as the core robustness mechanism (PAPERS:
Omega; Borg) — election alone is not an HA story.

The pieces:

- ``journal.WriteIntentJournal`` — an append-before-dispatch,
  confirm-after-ack write-ahead log wrapped around the cache's async
  write pool: every bind/evict statement lands in the journal (cycle
  id, gang id, task→node intent, statement kind) *before* the store
  write is dispatched, and is confirmed *after* the write acks.
- ``reconcile.reconcile_journal`` — takeover reconciliation: on lease
  acquire and on process restart, scan the journal against ClusterStore
  truth — confirm writes that landed, re-dispatch orphaned intents
  idempotently, and roll back half-bound gangs (statement-style op log
  with reverse-order undo) so gang atomicity survives a leader crash
  mid-bulk-bind.
- ``budget.CycleBudget`` — the scheduling cycle's deadline budget: a
  soft deadline arms a solver-ladder tier downgrade, a hard deadline
  aborts the cycle pre-dispatch (cache byte-identical; the next cycle
  reschedules) and meters ``cycle.overrun``.
- ``watch_client.ResilientWatcher`` — bounded-staleness list+watch
  client: reconnect with jittered exponential backoff, 410-Gone
  relist-storm coalescing, and a snapshot-age gauge feeding the
  scheduler's refuse-to-schedule staleness guard.
- ``fsck`` — offline journal checker
  (``python -m kube_batch_tpu.recovery.fsck``).

Fault points ``journal.append``, ``journal.replay``, ``reconcile.scan``
and ``cycle.overrun`` plug into the PR 1 fault registry, so every
recovery path is drillable in production.
"""

from __future__ import annotations

from kube_batch_tpu.recovery.budget import CycleBudget, CycleDeadlineExceeded
from kube_batch_tpu.recovery.journal import WriteIntentJournal
from kube_batch_tpu.recovery.reconcile import reconcile_journal
from kube_batch_tpu.recovery.watch_client import ResilientWatcher

__all__ = [
    "CycleBudget",
    "CycleDeadlineExceeded",
    "WriteIntentJournal",
    "reconcile_journal",
    "ResilientWatcher",
]
