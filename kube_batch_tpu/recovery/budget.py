"""Cycle deadline budget: one wedged solve must not stall the loop past
the lease window.

The failure this closes: ``scheduler.run_once`` had no deadline, so a
solve that wedged (pathological snapshot, device hang, compile storm)
stalled the cycle indefinitely — past the lease renew deadline, which
the elector's watchdog then read as *leader death* and triggered a
spurious failover of a perfectly healthy process.

Two deadlines, both measured from cycle start:

- **soft** (``KBT_CYCLE_SOFT_DEADLINE_S``): the cycle finishing late is
  evidence against the solver tier that ran it — the scheduler records
  a failure against that tier's circuit breaker (faults/ladder.py), so
  repeated overruns *arm a tier downgrade* through the existing
  breaker automaton instead of a bespoke mechanism;
- **hard** (``KBT_CYCLE_HARD_DEADLINE_S``): the cycle aborts. The abort
  point is always *pre-dispatch* (between actions, between solve
  segments, and at the dispatch barrier before any ``cache.bind``), so
  aborting rolls back to a byte-identical cache — the session snapshot
  is simply discarded, the Statement discipline's ``discard`` at cycle
  granularity — and the next cycle reschedules the aborted gangs from
  Pending. Metered as ``cycle.overrun``.

The ``cycle.overrun`` fault point makes a hard overrun injectable: it
is consulted only at the *dispatch-barrier* check (``inject=True`` —
the last pre-dispatch gate, after encode+solve+replay have done maximal
discardable work), so a drill deterministically exercises the
worst-case abort without a real multi-second stall.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from kube_batch_tpu import faults


class CycleDeadlineExceeded(RuntimeError):
    """Raised at a pre-dispatch check when the hard budget is gone; the
    scheduler catches it, meters cycle.overrun and discards the cycle."""


class CycleBudget:
    """Deadline state for one scheduling cycle."""

    def __init__(
        self,
        soft_s: Optional[float] = None,
        hard_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.soft_s = soft_s if soft_s and soft_s > 0 else None
        self.hard_s = hard_s if hard_s and hard_s > 0 else None
        self._clock = clock
        self._start = clock()

    def elapsed(self) -> float:
        return self._clock() - self._start

    def remaining(self) -> float:
        """Seconds left in the hard budget (inf when no hard deadline):
        the remaining-budget argument solver entry receives."""
        if self.hard_s is None:
            return float("inf")
        return self.hard_s - self.elapsed()

    def soft_exceeded(self) -> bool:
        return self.soft_s is not None and self.elapsed() > self.soft_s

    def hard_exceeded(self, inject: bool = False) -> bool:
        """True when the hard deadline passed — or, at the dispatch
        barrier (``inject=True``), when the ``cycle.overrun`` fault
        point fires (an injected wedged-solve drill)."""
        if inject and faults.should_fire("cycle.overrun"):
            return True
        return self.hard_s is not None and self.elapsed() > self.hard_s

    def check(self, where: str, inject: bool = False) -> None:
        """Raise CycleDeadlineExceeded when the hard budget is gone.
        Call sites are all pre-dispatch (see module docstring)."""
        if self.hard_exceeded(inject=inject):
            raise CycleDeadlineExceeded(
                f"cycle hard deadline exceeded at {where} "
                f"({self.elapsed():.3f}s elapsed, budget "
                f"{self.hard_s if self.hard_s is not None else 'injected'})"
            )
