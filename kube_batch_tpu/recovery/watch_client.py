"""Bounded-staleness watch client over the scheduler's HTTP list+watch
API (server.py WatchHub).

The role of client-go's Reflector against our watch surface: maintain a
local mirror of one or more kinds, and *know how stale it is*. External
consumers (a control-plane bridge, a second scheduler reading a remote
store, dashboards) previously had to hand-roll the k8s watch contract;
this client implements it hardened:

- **reconnect with jittered exponential backoff**: a connection error
  (arbiter restart, network blip) retries at ``min_backoff`` doubling
  to ``max_backoff``, with a uniform jitter factor so a fleet of
  watchers does not reconnect in lockstep (thundering herd);
- **410-Gone relist-storm coalescing**: a Gone means re-list — but under
  event churn a slow watcher can be Gone'd every poll, and naive
  re-listing turns the recovery path into a full-list DoS of the
  server. Relists per kind are coalesced to at most one per
  ``relist_min_interval`` seconds; Gones inside the window wait it out;
- **snapshot-age gauge**: seconds since the mirror was last known
  current (successful list or poll), exported as
  ``kube_batch_tpu_watch_snapshot_age_seconds`` — the number the
  refuse-to-schedule staleness guard (scheduler.py,
  ``KBT_MAX_SNAPSHOT_AGE_S``) compares against.

Use ``start()``/``stop()`` for one background thread per kind, or drive
``list_kind``/``poll_once`` directly (tests do).
"""

from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from kube_batch_tpu import log, metrics


def _obj_key(body: dict) -> str:
    if "namespace" in body:
        return f"{body['namespace']}/{body['name']}"
    return str(body.get("name"))


class ResilientWatcher:
    """Hardened list+watch mirror of ``kinds`` at ``base_url``."""

    def __init__(
        self,
        base_url: str,
        kinds: tuple[str, ...],
        poll_timeout: float = 5.0,
        min_backoff: float = 0.05,
        max_backoff: float = 5.0,
        relist_min_interval: float = 1.0,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.kinds = tuple(kinds)
        self.poll_timeout = poll_timeout
        self.min_backoff = min_backoff
        self.max_backoff = max_backoff
        self.relist_min_interval = relist_min_interval
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        # kind -> {obj_key: serialized object} — the mirror
        self.mirror: dict[str, dict[str, dict]] = {k: {} for k in self.kinds}  #: guarded_by _lock
        self._rv: dict[str, int] = {k: 0 for k in self.kinds}  #: guarded_by _lock
        self._last_sync: dict[str, Optional[float]] = {k: None for k in self.kinds}  #: guarded_by _lock
        self._last_relist: dict[str, float] = {k: 0.0 for k in self.kinds}  #: guarded_by _lock
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- one round-trip each ------------------------------------------------

    def _get(self, path: str, timeout: float) -> dict:
        with urllib.request.urlopen(f"{self.base_url}{path}", timeout=timeout) as r:
            return json.loads(r.read())

    def list_kind(self, kind: str) -> None:
        """Full re-list: replace the kind's mirror and resume the watch
        from the returned resourceVersion. Coalesced: inside the
        relist_min_interval window the call waits for the window to
        close first (the storm damper)."""
        now = time.monotonic()
        with self._lock:
            wait = self._last_relist[kind] + self.relist_min_interval - now
        if wait > 0:
            if self._stop.wait(wait):  # blocking wait stays outside the lock
                return
        with self._lock:
            # max(): a concurrent direct list_kind call may have stamped
            # the window while we waited — never move the window backwards
            self._last_relist[kind] = max(
                self._last_relist[kind], time.monotonic()
            )
        payload = self._get(f"/apis/v1alpha1/{kind}", timeout=self.poll_timeout + 5)
        with self._lock:
            self.mirror[kind] = {_obj_key(o): o for o in payload["items"]}
            self._rv[kind] = payload["resourceVersion"]
        self._mark_sync(kind)
        metrics.register_watch_relist(kind)

    def poll_once(self, kind: str) -> str:
        """One watch long-poll; applies events. Returns "ok" | "gone"
        (410: the caller must re-list; the thread loop does)."""
        with self._lock:
            since = self._rv[kind]
        try:
            payload = self._get(
                f"/apis/v1alpha1/watch/{kind}"
                f"?since={since}&timeout={self.poll_timeout}",
                timeout=self.poll_timeout + 5,
            )
        except urllib.error.HTTPError as e:
            if e.code == 410:
                body = json.loads(e.read() or b"{}")
                with self._lock:
                    # absolute resume point dictated by the server's 410
                    # body, NOT derived from the rv we polled with — a
                    # compaction may legitimately move it backwards
                    self._rv[kind] = int(body.get("resourceVersion", 0))  # noqa: KBT-T003
                return "gone"
            raise
        with self._lock:
            m = self.mirror[kind]
            for ev in payload["events"]:
                key = _obj_key(ev["object"])
                if ev["type"] == "DELETED":
                    m.pop(key, None)
                else:
                    m[key] = ev["object"]
            # absolute server-issued rv; one watch thread per kind
            self._rv[kind] = payload["resourceVersion"]  # noqa: KBT-T003
        self._mark_sync(kind)
        return "ok"

    # -- staleness ----------------------------------------------------------

    def _mark_sync(self, kind: str) -> None:
        with self._lock:
            self._last_sync[kind] = time.monotonic()
        metrics.set_watch_snapshot_age(self.snapshot_age())

    def snapshot_age(self) -> float:
        """Seconds since the *oldest* kind was last known current (inf
        before the first successful list). This is the guard's input:
        one stalled kind makes the whole snapshot stale."""
        with self._lock:
            ages = []
            now = time.monotonic()
            for kind in self.kinds:
                t = self._last_sync[kind]
                if t is None:
                    return float("inf")
                ages.append(now - t)
        age = max(ages) if ages else float("inf")
        metrics.set_watch_snapshot_age(age)
        return age

    def stale(self, threshold: float) -> bool:
        return self.snapshot_age() > threshold

    # -- lifecycle ----------------------------------------------------------

    def _loop(self, kind: str) -> None:
        backoff = self.min_backoff
        listed = False
        while not self._stop.is_set():
            try:
                if not listed:
                    self.list_kind(kind)
                    listed = True
                status = self.poll_once(kind)
                if status == "gone":
                    listed = False  # re-list (coalesced) next iteration
                    continue
                backoff = self.min_backoff  # healthy round-trip
            except Exception as e:  # noqa: BLE001 - reconnect path
                # jittered exponential backoff: 0.5-1.5x the nominal
                # delay so restarting fleets fan out
                delay = backoff * (0.5 + self._rng.random())
                log.V(3).infof(
                    "watch %s: %s; reconnecting in %.2fs", kind, e, delay
                )
                backoff = min(backoff * 2.0, self.max_backoff)
                self._stop.wait(delay)

    def start(self) -> None:
        if self._threads:  # idempotent: a second start must not double
            return         # the watcher population
        self._stop.clear()
        for kind in self.kinds:
            t = threading.Thread(
                target=self._loop, args=(kind,), name=f"kb-watch-{kind}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.poll_timeout + 6)
        self._threads.clear()
