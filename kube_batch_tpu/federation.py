"""Sharded multi-scheduler federation: Omega-style shared state.

PR 10 tentpole (ISSUE.md). Instead of one scheduler process owning the
whole cluster, N schedulers run concurrently against ONE store, each
responsible for a shard of the pending workload (partitioned by queue,
namespace, or gang — ``KBT_SHARD_KEY``). There is no pessimistic
partitioning of *nodes*: every scheduler sees full cluster state and
solves over all capacity, and correctness comes from optimistic
concurrency at dispatch time (Omega, Schwarzkopf et al., EuroSys'13):

- every ``bind_many``/evict transaction carries the store version the
  scheduler's snapshot was taken at (``SchedulerCache.snapshot()``
  stamps it);
- the store commits a gang all-or-nothing and rejects the transaction
  with a typed ``StaleWrite`` when any target node took a placement
  write the snapshot never saw, the pod was already placed, or
  store-side admission says the requests no longer fit
  (``ClusterStore.conditional_bind_many``);
- the loser refreshes its version and retries with jittered backoff up
  to ``KBT_CONFLICT_MAX_RETRIES`` times; a terminal loser accepts store
  truth — its journal intent is confirmed (store truth IS the outcome)
  and the gang resyncs through the ordinary errTasks machinery
  (``SchedulerCache._do_bind_gang``).

Shards are about *work division*, not safety: two schedulers
accidentally configured with the same shard stay correct (every
double-place loses its conflict), they just waste solves. Gangs never
split across shards — all three shard keys are gang-stable (a gang's
pods share a podgroup, hence a queue and a namespace).

Deployment shapes:

- in-process (bench, interleave explorer): N ``FederatedCache`` over
  one ``InProcessBackend``;
- networked (docker-compose topology in deployment/): N scheduler
  processes, each a ``LoopbackBackend`` speaking ``/backend/v1/`` to
  one store process (a SchedulerServer whose own loop is idled by an
  unmatched scheduler name).

Env surface: ``KBT_FEDERATION`` (shard spec ``i/N``, or any non-empty
value to force conditional dispatch on), ``KBT_SHARD_KEY`` (``queue`` |
``namespace`` | ``gang``; default ``queue``),
``KBT_CONFLICT_MAX_RETRIES`` (cache.py; default 3).
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Optional

from kube_batch_tpu import log
from kube_batch_tpu.api.job_info import get_job_id, job_key
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cache.store import NODES, POD_GROUPS, PODS

__all__ = [
    "ENV",
    "SHARD_KEY_ENV",
    "SHARD_KEYS",
    "enabled",
    "parse_shard_spec",
    "shard_key_mode",
    "shard_key_of",
    "shard_index",
    "FederatedCache",
    "fsck",
    "smoke",
]

ENV = "KBT_FEDERATION"
SHARD_KEY_ENV = "KBT_SHARD_KEY"
SHARD_KEYS = ("queue", "namespace", "gang")


def enabled() -> bool:
    """Process-wide federation switch; also flips SchedulerCache into
    conditional (optimistic) dispatch by default (cache.py)."""
    return os.environ.get(ENV, "") not in ("", "0")


def parse_shard_spec(value: str) -> tuple[int, int]:
    """``"i/N"`` -> (i, N); a bare ``"N"`` or truthy flag -> (0, 1)
    (conditional dispatch on, no workload partition)."""
    value = value.strip()
    if "/" in value:
        i_s, n_s = value.split("/", 1)
        shard, shards = int(i_s), int(n_s)
        if shards < 1 or not (0 <= shard < shards):
            raise ValueError(f"bad shard spec {value!r}: want i/N with 0 <= i < N")
        return shard, shards
    return 0, 1


def shard_key_mode() -> str:
    mode = os.environ.get(SHARD_KEY_ENV, "queue").strip() or "queue"
    if mode not in SHARD_KEYS:
        log.errorf(
            "%s=%r is not one of %s; using 'queue'", SHARD_KEY_ENV, mode, SHARD_KEYS
        )
        return "queue"
    return mode


def _gang_key(pod) -> str:
    jid = get_job_id(pod)
    if jid:
        return jid
    return job_key(pod.namespace, pod.metadata.owner_job or pod.metadata.uid)


def shard_key_of(pod, store=None, mode: str = "queue") -> str:
    """The stable string a pod shards on. All modes are gang-stable: a
    gang's pods share a podgroup, hence one queue and one namespace, so
    a gang never splits across schedulers (min_member gating would see
    partial gangs otherwise)."""
    if mode == "namespace":
        return pod.namespace
    if mode == "gang":
        return _gang_key(pod)
    # queue: resolve through the podgroup; a pod whose group has not
    # arrived yet (or a shadow gang) falls back to its gang key — still
    # gang-stable, just spread differently until the group lands.
    jid = get_job_id(pod)
    if store is not None and jid:
        pg = store.get(POD_GROUPS, jid)
        if pg is not None and pg.spec.queue:
            return pg.spec.queue
    return _gang_key(pod)


def shard_index(key: str, shards: int) -> int:
    """crc32-based bucket: stable across processes (``hash()`` is salted
    per interpreter and would shard each process differently)."""
    if shards <= 1:
        return 0
    return zlib.crc32(key.encode()) % shards


class FederatedCache(SchedulerCache):
    """A SchedulerCache owning one shard of the pending workload.

    The pod filter narrows the base rule ("my pending pods + every
    non-pending pod") to "my pending pods *in my shard* + every
    non-pending pod" — full cluster capacity stays visible, only the
    work divides. Conditional (optimistic) dispatch is forced on."""

    def __init__(
        self,
        store,
        shard: int = 0,
        shards: int = 1,
        shard_key: Optional[str] = None,
        **kwargs,
    ) -> None:
        if not (0 <= shard < max(1, shards)):
            raise ValueError(f"shard {shard} out of range for {shards} shards")
        self.shard = shard
        self.shards = max(1, shards)
        self.shard_key = shard_key or shard_key_mode()
        if self.shard_key not in SHARD_KEYS:
            raise ValueError(f"shard_key must be one of {SHARD_KEYS}")
        kwargs["conditional_binds"] = True
        super().__init__(store, **kwargs)

    def _pod_filter(self, pod) -> bool:
        # Only UNBOUND pending pods shard: a bound pod — even one still
        # phase-Pending, and even another shard's — holds node capacity
        # this scheduler must account for, or its snapshots would
        # over-place and every dispatch under contention would lose its
        # store-side admission check forever (conflict livelock).
        if pod.phase == PodPhase.PENDING and not pod.node_name:
            return (
                pod.scheduler_name == self.scheduler_name
                and shard_index(
                    shard_key_of(pod, self.store, self.shard_key), self.shards
                )
                == self.shard
            )
        return True  # bound/terminal pods hold capacity for everyone


# -- fsck --------------------------------------------------------------------


def fsck(store, epsilon: float = 1e-6) -> list[str]:
    """Cross-scheduler consistency check over store truth; returns
    violations (empty = clean). Invariants:

    - every bound, non-terminal pod names an existing node;
    - per node, the sum of bound non-terminal requests fits allocatable;
    - the store's incremental allocation ledger (``node_allocated``)
      agrees with that recomputed sum — a drifted ledger means a
      conditional admission decision was made against wrong state."""
    from kube_batch_tpu.api.helpers import get_pod_resource_request
    from kube_batch_tpu.api.resource_info import Resource

    out: list[str] = []
    nodes = {n.name: n for n in store.list(NODES)}
    per_node: dict[str, Resource] = {}
    for pod in store.list(PODS):
        if not pod.node_name or pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            continue
        if pod.node_name not in nodes:
            out.append(
                f"pod {pod.namespace}/{pod.name} bound to missing node "
                f"{pod.node_name!r}"
            )
            continue
        per_node.setdefault(pod.node_name, Resource.empty()).add(
            get_pod_resource_request(pod)
        )
    for name, used in per_node.items():
        cap = Resource.from_resource_list(nodes[name].allocatable)
        if not used.less_equal(cap):
            out.append(f"node {name} over capacity: used {used} > allocatable {cap}")
    ledger = getattr(store, "node_allocated", None)
    if ledger is not None:
        for name in nodes:
            have = ledger(name)
            want = per_node.get(name, Resource.empty())
            if abs(have.milli_cpu - want.milli_cpu) > epsilon or abs(
                have.memory - want.memory
            ) > epsilon:
                out.append(
                    f"node {name} allocation ledger drift: ledger {have} vs "
                    f"recomputed {want}"
                )
    return out


# -- smoke -------------------------------------------------------------------


def _seed_world(store, gangs: int, members: int, nodes: int) -> None:
    from kube_batch_tpu.testing import (
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    if store.get("queues", "default") is None:
        store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=32))
        )
    for g in range(gangs):
        name = f"fg{g}"
        store.create_pod_group(build_pod_group(name, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{name}-p{m}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def _wait_all_bound(store, total: int, deadline_s: float) -> bool:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        pods = store.list(PODS)
        if len(pods) >= total and all(p.node_name for p in pods):
            return True
        time.sleep(0.005)
    return False


def smoke(shards: int = 2, gangs: int = 6, members: int = 3, nodes: int = 8) -> dict:
    """End-to-end federation proof, runnable standalone
    (``python -m kube_batch_tpu.federation``) and from hack/verify.py:

    1. start a real SchedulerServer on loopback whose own loop is idled
       (unmatched scheduler name) — it is the store process;
    2. run ``shards`` FederatedCache+Scheduler pairs against it, each
       over its own LoopbackBackend (the full wire path: list+watch,
       conditional binds, 409 conflicts);
    3. assert every pod bound exactly once (a store-side handler counts
       ""->node transitions per pod), the union placement is
       capacity-valid (fsck clean), and the *set* of bound pods matches
       a single-scheduler twin on an identical world (which pods bind
       is deterministic; which node wins a race is not).
    """
    import threading

    from kube_batch_tpu.cache import EventHandler, LoopbackBackend
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer

    total = gangs * members
    server = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
    )
    server.start()
    bind_counts: dict[str, int] = {}
    counts_lock = threading.Lock()

    def _count_bind(old, new) -> None:
        if not old.node_name and new.node_name:
            with counts_lock:
                key = f"{new.namespace}/{new.name}"
                bind_counts[key] = bind_counts.get(key, 0) + 1

    server.store.add_event_handler(PODS, EventHandler(on_update=_count_bind))
    backends: list[LoopbackBackend] = []
    scheds: list[tuple[Scheduler, threading.Thread]] = []
    stop = threading.Event()
    try:
        _seed_world(server.store, gangs, members, nodes)
        base = f"http://127.0.0.1:{server.listen_port}"
        for i in range(shards):
            backend = LoopbackBackend(base)
            cache = FederatedCache(
                backend, shard=i, shards=shards, shard_key="gang",
                staleness_fn=backend.snapshot_age,
            )
            cache.run()
            backend.start(period=0.02)
            backends.append(backend)
            sched = Scheduler(cache, schedule_period=0.05)
            t = threading.Thread(
                target=sched.run, args=(stop,), name=f"kb-fed-{i}", daemon=True
            )
            t.start()
            scheds.append((sched, t))
        all_bound = _wait_all_bound(server.store, total, deadline_s=60.0)
    finally:
        stop.set()
        for _, t in scheds:
            t.join(timeout=10.0)
        for backend in backends:
            backend.stop()
        for sched, _ in scheds:
            sched.cache.stop()
        server.stop()

    violations = fsck(server.store)
    counts = dict(bind_counts)
    exactly_once = all_bound and sorted(counts.values()) == [1] * total

    # single-scheduler twin: same world, one cache, in-process
    from kube_batch_tpu.cache import ClusterStore

    twin = ClusterStore()
    _seed_world(twin, gangs, members, nodes)
    twin_cache = SchedulerCache(twin)
    twin_cache.run()
    twin_sched = Scheduler(twin_cache, schedule_period=0.02)
    twin_stop = threading.Event()
    t = threading.Thread(target=twin_sched.run, args=(twin_stop,), daemon=True)
    t.start()
    try:
        _wait_all_bound(twin, total, deadline_s=30.0)
    finally:
        twin_stop.set()
        t.join(timeout=10.0)
        twin_cache.stop()
    fed_bound = {
        f"{p.namespace}/{p.name}"
        for p in server.store.list(PODS)
        if p.node_name
    }
    twin_bound = {
        f"{p.namespace}/{p.name}" for p in twin.list(PODS) if p.node_name
    }

    out = {
        "shards": shards,
        "pods": total,
        "bound": len(fed_bound),
        "exactly_once": exactly_once,
        "double_binds": sum(1 for v in counts.values() if v > 1),
        "fsck_violations": violations,
        "union_parity": fed_bound == twin_bound,
    }
    out["ok"] = bool(
        all_bound
        and exactly_once
        and not violations
        and out["union_parity"]
        and out["bound"] == total
    )
    return out


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="federation smoke: N schedulers over one loopback store, "
        "optimistic conflicts, exactly-once binds"
    )
    parser.add_argument("--shards", type=int, default=2)
    parser.add_argument("--gangs", type=int, default=6)
    parser.add_argument("--members", type=int, default=3)
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    result = smoke(shards=args.shards, gangs=args.gangs, members=args.members)
    if args.json:
        print(json.dumps(result, sort_keys=True))
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"federation smoke: {status} ({result['bound']}/{result['pods']} pods "
            f"bound across {result['shards']} schedulers, exactly_once="
            f"{result['exactly_once']}, union_parity={result['union_parity']}, "
            f"fsck={'clean' if not result['fsck_violations'] else result['fsck_violations']})"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level state would otherwise be
    # distinct from the one other modules import
    from kube_batch_tpu.federation import main as _canonical_main

    raise SystemExit(_canonical_main())
