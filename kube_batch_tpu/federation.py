"""Sharded multi-scheduler federation: Omega-style shared state.

PR 10 tentpole (ISSUE.md). Instead of one scheduler process owning the
whole cluster, N schedulers run concurrently against ONE store, each
responsible for a shard of the pending workload (partitioned by queue,
namespace, or gang — ``KBT_SHARD_KEY``). There is no pessimistic
partitioning of *nodes*: every scheduler sees full cluster state and
solves over all capacity, and correctness comes from optimistic
concurrency at dispatch time (Omega, Schwarzkopf et al., EuroSys'13):

- every ``bind_many``/evict transaction carries the store version the
  scheduler's snapshot was taken at (``SchedulerCache.snapshot()``
  stamps it);
- the store commits a gang all-or-nothing and rejects the transaction
  with a typed ``StaleWrite`` when any target node took a placement
  write the snapshot never saw, the pod was already placed, or
  store-side admission says the requests no longer fit
  (``ClusterStore.conditional_bind_many``);
- the loser refreshes its version and retries with jittered backoff up
  to ``KBT_CONFLICT_MAX_RETRIES`` times; a terminal loser accepts store
  truth — its journal intent is confirmed (store truth IS the outcome)
  and the gang resyncs through the ordinary errTasks machinery
  (``SchedulerCache._do_bind_gang``).

Shards are about *work division*, not safety: two schedulers
accidentally configured with the same shard stay correct (every
double-place loses its conflict), they just waste solves. Gangs never
split across shards — all three shard keys are gang-stable (a gang's
pods share a podgroup, hence a queue and a namespace).

Deployment shapes:

- in-process (bench, interleave explorer): N ``FederatedCache`` over
  one ``InProcessBackend``;
- networked (docker-compose topology in deployment/): N scheduler
  processes, each a ``LoopbackBackend`` speaking ``/backend/v1/`` to
  one store process (a SchedulerServer whose own loop is idled by an
  unmatched scheduler name).

Leased shard slots (PR 16 tentpole): the ``i`` in ``i/N`` is no longer
a static assignment but this scheduler's *primary slot* — each of the N
shard slots is a store lease (``shard-slot-{i}``, arbitrated by
``ClusterStore.try_acquire_lease`` under the arbiter's clock), held and
renewed by a ``ShardSlotManager``. When a slot's lease expires (its
owner died) survivors race to adopt it: the winner reconciles the dead
shard's write-intent journal against store truth
(``recovery.reconcile_journal``), widens its ``FederatedCache`` owned
set, and schedules the orphaned backlog. A graceful ``handoff`` (stop
dispatching, drain in-flight intents, release the lease) supports
planned moves, which conflict-aware rebalancing drives off the
conflict counters when ``KBT_SHARD_REBALANCE`` is set.

Env surface: ``KBT_FEDERATION`` (shard spec ``i/N``, or any non-empty
value to force conditional dispatch on), ``KBT_SHARD_KEY`` (``queue`` |
``namespace`` | ``gang``; default ``queue``),
``KBT_CONFLICT_MAX_RETRIES`` (cache.py; default 3),
``KBT_SHARD_ADOPT`` (default on), ``KBT_SHARD_LEASE_S`` /
``KBT_SHARD_RENEW_S`` (slot lease TTL / renew cadence),
``KBT_SHARD_REBALANCE`` (conflict delta per probe round that sheds an
adopted slot; 0 = off), ``KBT_SHARD_JOURNAL_DIR`` (shared directory of
per-slot journals, ``shard-{i}.wal`` — what adoption reconciles).
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Callable, Optional

from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.api.job_info import get_job_id, job_key
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cache.store import LEASES, NODES, POD_GROUPS, PODS, EventHandler

__all__ = [
    "ENV",
    "SHARD_KEY_ENV",
    "SHARD_KEYS",
    "ADOPT_ENV",
    "LEASE_ENV",
    "RENEW_ENV",
    "REBALANCE_ENV",
    "JOURNAL_DIR_ENV",
    "SLOT_LEASE_PREFIX",
    "enabled",
    "parse_shard_spec",
    "shard_key_mode",
    "shard_key_of",
    "shard_index",
    "slot_lease_name",
    "parse_slot_lease_name",
    "reclaim_lease_name",
    "adopt_enabled",
    "slot_lease_seconds",
    "slot_renew_seconds",
    "shard_journal_dir",
    "shard_journal_path",
    "rebalance_threshold",
    "plan_rebalance",
    "ShardSlotManager",
    "FederatedCache",
    "fsck",
    "smoke",
    "smoke_streaming",
    "smoke_kill_one",
]

ENV = "KBT_FEDERATION"
SHARD_KEY_ENV = "KBT_SHARD_KEY"
SHARD_KEYS = ("queue", "namespace", "gang")

# -- leased shard slots: env surface -----------------------------------------
ADOPT_ENV = "KBT_SHARD_ADOPT"  # default on; 0/false/no/off disables adoption
LEASE_ENV = "KBT_SHARD_LEASE_S"  # slot lease TTL (default 15.0)
RENEW_ENV = "KBT_SHARD_RENEW_S"  # renew/probe cadence (default lease/3)
REBALANCE_ENV = "KBT_SHARD_REBALANCE"  # conflict delta/round that sheds a slot
JOURNAL_DIR_ENV = "KBT_SHARD_JOURNAL_DIR"  # shared dir of shard-{i}.wal journals

SLOT_LEASE_PREFIX = "shard-slot-"
_RECLAIM_SUFFIX = "-reclaim"
_OFF_WORDS = ("0", "false", "no", "off")


def enabled() -> bool:
    """Process-wide federation switch; also flips SchedulerCache into
    conditional (optimistic) dispatch by default (cache.py)."""
    return os.environ.get(ENV, "") not in ("", "0")


def parse_shard_spec(value: str) -> tuple[int, int]:
    """``"i/N"`` -> (i, N); a bare ``"N"`` or truthy flag -> (0, 1)
    (conditional dispatch on, no workload partition)."""
    value = value.strip()
    if "/" in value:
        i_s, n_s = value.split("/", 1)
        shard, shards = int(i_s), int(n_s)
        if shards < 1 or not (0 <= shard < shards):
            raise ValueError(f"bad shard spec {value!r}: want i/N with 0 <= i < N")
        return shard, shards
    return 0, 1


def shard_key_mode() -> str:
    mode = os.environ.get(SHARD_KEY_ENV, "queue").strip() or "queue"
    if mode not in SHARD_KEYS:
        log.errorf(
            "%s=%r is not one of %s; using 'queue'", SHARD_KEY_ENV, mode, SHARD_KEYS
        )
        return "queue"
    return mode


def _gang_key(pod) -> str:
    jid = get_job_id(pod)
    if jid:
        return jid
    return job_key(pod.namespace, pod.metadata.owner_job or pod.metadata.uid)


def shard_key_of(pod, store=None, mode: str = "queue") -> str:
    """The stable string a pod shards on. All modes are gang-stable: a
    gang's pods share a podgroup, hence one queue and one namespace, so
    a gang never splits across schedulers (min_member gating would see
    partial gangs otherwise)."""
    if mode == "namespace":
        return pod.namespace
    if mode == "gang":
        return _gang_key(pod)
    # queue: resolve through the podgroup; a pod whose group has not
    # arrived yet (or a shadow gang) falls back to its gang key — still
    # gang-stable, just spread differently until the group lands.
    jid = get_job_id(pod)
    if store is not None and jid:
        pg = store.get(POD_GROUPS, jid)
        if pg is not None and pg.spec.queue:
            return pg.spec.queue
    return _gang_key(pod)


def shard_index(key: str, shards: int) -> int:
    """crc32-based bucket: stable across processes (``hash()`` is salted
    per interpreter and would shard each process differently)."""
    if shards <= 1:
        return 0
    return zlib.crc32(key.encode()) % shards


# -- leased shard slots ------------------------------------------------------


def slot_lease_name(slot: int) -> str:
    return f"{SLOT_LEASE_PREFIX}{slot}"


def reclaim_lease_name(slot: int) -> str:
    """The store-mediated 'please hand slot N back' request: a joining
    scheduler whose primary slot is held by a survivor acquires this
    lease; the survivor's probe loop sees a live reclaim holder and
    gracefully hands the slot off."""
    return f"{SLOT_LEASE_PREFIX}{slot}{_RECLAIM_SUFFIX}"


def parse_slot_lease_name(name: str) -> Optional[int]:
    """The slot index a lease name arbitrates, or None for non-slot
    leases (elector leases, reclaim requests)."""
    if not name.startswith(SLOT_LEASE_PREFIX) or name.endswith(_RECLAIM_SUFFIX):
        return None
    try:
        return int(name[len(SLOT_LEASE_PREFIX):])
    except ValueError:
        return None


def adopt_enabled() -> bool:
    return os.environ.get(ADOPT_ENV, "1").strip().lower() not in _OFF_WORDS


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.errorf("%s=%r is not a number; using %s", name, raw, default)
        return default


def slot_lease_seconds() -> float:
    return max(0.1, _env_float(LEASE_ENV, 15.0))


def slot_renew_seconds(lease_s: Optional[float] = None) -> float:
    lease_s = slot_lease_seconds() if lease_s is None else lease_s
    return max(0.02, _env_float(RENEW_ENV, lease_s / 3.0))


def rebalance_threshold() -> float:
    return max(0.0, _env_float(REBALANCE_ENV, 0.0))


def shard_journal_dir() -> str:
    return os.environ.get(JOURNAL_DIR_ENV, "").strip()


def shard_journal_path(journal_dir: str, slot: int) -> str:
    return os.path.join(journal_dir, f"shard-{slot}.wal")


def plan_rebalance(
    owned: set,
    primary: int,
    adoption_order: list,
    conflicts_delta: float,
    threshold: float,
) -> Optional[int]:
    """Pure rebalance policy: when this scheduler is conflict-hot
    (``conflicts_delta`` since the last probe round >= ``threshold``)
    and owns more than its primary, shed the most recently adopted
    non-primary slot — the gang keys it picked up last are the ones a
    less contended peer should own. Returns the slot to hand off, or
    None."""
    if threshold <= 0 or conflicts_delta < threshold:
        return None
    candidates = [s for s in adoption_order if s in owned and s != primary]
    if not candidates:
        return None
    return candidates[-1]


class ShardSlotManager:
    """Leased ownership of shard slots for one ``FederatedCache``.

    Each of the N shard slots is a store lease named ``shard-slot-{i}``
    (arbitrated by the store's ``try_acquire_lease`` ladder — the same
    machinery the leader elector uses, so expiry, release sentinels and
    transitions all follow the arbiter's clock). The manager:

    - acquires its **primary** slot at start (requesting a graceful
      reclaim when a survivor adopted it first);
    - **renews** every owned slot each ``renew_s`` (the ``shard.lease_flap``
      fault point drops one renewal round — the lease survives one
      missed renewal by construction, so nobody double-adopts);
    - **adopts** orphaned slots: a released slot immediately, an
      expired slot as soon as the arbiter agrees, a never-claimed slot
      after a startup grace (so a slow-starting peer is not robbed).
      Adoption is breaker-backed (an injected/real takeover failure
      releases the slot and backs off) and runs journal takeover
      reconciliation against the dead shard's ``shard-{i}.wal`` before
      the backlog is re-ingested;
    - **hands off** slots gracefully (stop dispatching, drain in-flight
      journal intents, release) for planned moves, reclaim requests and
      conflict-aware rebalancing (``plan_rebalance``).

    The arbiter is duck-typed: an in-process ``ClusterStore`` or a
    ``LoopbackBackend`` (whose lease verbs POST the arbiter's
    ``/apis/v1alpha1/leases/`` endpoint and whose LEASES mirror is the
    ``/backend/v1/`` slot-watch that wakes the probe loop on release)."""

    def __init__(
        self,
        arbiter,
        cache: "FederatedCache",
        identity: Optional[str] = None,
        *,
        lease_s: Optional[float] = None,
        renew_s: Optional[float] = None,
        adopt: Optional[bool] = None,
        journal_dir: Optional[str] = None,
        grace_s: Optional[float] = None,
        rebalance: Optional[float] = None,
        conflict_fn: Optional[Callable[[], float]] = None,
        on_owned_change: Optional[Callable[[set, set], None]] = None,
    ) -> None:
        self.arbiter = arbiter
        self.cache = cache
        self.primary = cache.shard
        self.shards = cache.shards
        self.identity = identity or f"shard-{self.primary}@{os.getpid()}.{id(self):x}"
        self.lease_s = slot_lease_seconds() if lease_s is None else float(lease_s)
        self.renew_s = (
            slot_renew_seconds(self.lease_s) if renew_s is None else float(renew_s)
        )
        self.adopt = adopt_enabled() if adopt is None else bool(adopt)
        self.journal_dir = shard_journal_dir() if journal_dir is None else journal_dir
        self.grace_s = self.lease_s if grace_s is None else float(grace_s)
        self.rebalance = rebalance_threshold() if rebalance is None else float(rebalance)
        self._conflict_fn = conflict_fn
        self._on_owned_change = on_owned_change
        self._lock = threading.Lock()
        self._owned: set[int] = set()  #: guarded_by _lock
        self._adoption_order: list[int] = []  #: guarded_by _lock
        self._reclaiming = False  #: guarded_by _lock
        self._last_conflicts = 0.0  #: guarded_by _lock
        self._breaker = faults.CircuitBreaker(
            f"shard-adopt-{self.primary}", failure_threshold=3, reset_timeout=2.0
        )
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._started_at: Optional[float] = None
        self._watching = False

    # -- introspection -------------------------------------------------------

    def owned_slots(self) -> set[int]:
        with self._lock:
            return set(self._owned)

    # -- lifecycle -----------------------------------------------------------

    def start(self, deadline_s: float = 60.0) -> bool:
        """Acquire the primary slot (requesting reclaim from a survivor
        that adopted it), publish ownership, start the renew/probe loop.
        Returns False if the primary could not be acquired within
        ``deadline_s`` (the loop is NOT started)."""
        deadline = time.monotonic() + deadline_s
        reclaim = reclaim_lease_name(self.primary)
        requested = False
        try:
            while not self._stop.is_set():
                try:
                    lease = self.arbiter.try_acquire_lease(
                        slot_lease_name(self.primary), self.identity, self.lease_s
                    )
                except ConnectionError as e:  # BackendPartitioned
                    log.warningf("slot %d acquire: arbiter unreachable (%s)",
                                 self.primary, e)
                    lease = None
                if lease is not None and lease.holder_identity == self.identity:
                    break
                if time.monotonic() >= deadline:
                    return False
                if lease is not None and not requested:
                    # a survivor adopted our slot while we were down:
                    # ask for it back through the store
                    try:
                        self.arbiter.try_acquire_lease(
                            reclaim, self.identity, max(self.lease_s, 2 * self.renew_s)
                        )
                        requested = True
                    except ConnectionError:
                        pass
                time.sleep(min(self.renew_s, 0.25))
        finally:
            if requested:
                try:
                    self.arbiter.release_lease(reclaim, self.identity)
                except ConnectionError:
                    pass
        if self._stop.is_set():
            return False
        self._set_owned({self.primary})
        self._started_at = time.monotonic()
        self._watch_slots()
        self._thread = threading.Thread(
            target=self._loop, name=f"kb-slot-mgr-{self.primary}", daemon=True
        )
        self._thread.start()
        return True

    def stop(self, release: bool = True) -> None:
        """Graceful shutdown: stop the loop and (by default) release
        every owned slot so survivors adopt immediately instead of
        waiting out the lease."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        if release:
            for slot in self.owned_slots():
                try:
                    self.arbiter.release_lease(slot_lease_name(slot), self.identity)
                except ConnectionError:
                    pass

    def kill(self) -> None:
        """Simulated SIGKILL for chaos drills: stop renewing WITHOUT
        releasing — the slots must expire on the arbiter's clock, which
        is exactly what survivors' adoption is tested against."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # -- the loop ------------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.renew_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 - the loop must survive
                log.errorf("slot manager %s: probe round failed: %s",
                           self.identity, e)

    def step(self) -> None:
        """One renew/probe round — called from the loop, and directly by
        deterministic tests."""
        self._renew_owned()
        self._honor_reclaims()
        self._maybe_rebalance()
        if self.adopt:
            self._probe_orphans()

    def _watch_slots(self) -> None:
        """Subscribe the arbiter's LEASES feed (the in-process store's
        handler ring, or the LoopbackBackend's ``/backend/v1/`` mirror —
        the slot-watch) so a released slot wakes the probe loop
        immediately instead of waiting out a probe period."""
        if self._watching:
            return

        def _on_lease(old, new) -> None:
            if self._stop.is_set():
                return
            if parse_slot_lease_name(new.metadata.name) is None:
                return
            # only a RELEASE (graceful handoff / shutdown) wakes the
            # probe immediately — peer renewals carry no new work, and
            # expiry is passive (the periodic probe discovers it)
            if not new.holder_identity:
                self._wake.set()

        try:
            self.arbiter.add_event_handler(LEASES, EventHandler(on_update=_on_lease))
            self._watching = True
        except Exception as e:  # noqa: BLE001 - watch is an optimization
            log.warningf("slot manager %s: lease watch unavailable (%s); "
                         "falling back to periodic probes", self.identity, e)

    # -- renewal -------------------------------------------------------------

    def _renew_owned(self) -> None:
        if faults.should_fire("shard.lease_flap"):
            # one dropped renewal round: the lease outlives a single
            # missed renewal (renew_s < lease_s), so no survivor can
            # adopt — the reacquire next round is a no-op transition
            log.warningf("slot manager %s: renewal round dropped (lease flap)",
                         self.identity)
            return
        for slot in sorted(self.owned_slots()):
            name = slot_lease_name(slot)
            try:
                lease = self.arbiter.try_acquire_lease(name, self.identity, self.lease_s)
            except ConnectionError as e:
                log.warningf("slot %d renew: arbiter unreachable (%s)", slot, e)
                continue
            if lease.holder_identity != self.identity:
                # lost the slot (expired while we were wedged and a
                # survivor adopted it): drop it from the owned set so we
                # stop dispatching work we no longer own
                log.errorf(
                    "slot %d lost to %s; dropping from owned set",
                    slot, lease.holder_identity or "<released>",
                )
                with self._lock:
                    owned = set(self._owned)
                owned.discard(slot)
                self._set_owned(owned)

    # -- adoption ------------------------------------------------------------

    def _probe_orphans(self) -> None:
        now = time.monotonic()
        in_grace = (
            self._started_at is not None and now - self._started_at < self.grace_s
        )
        owned = self.owned_slots()
        for slot in range(self.shards):
            if slot in owned:
                continue
            name = slot_lease_name(slot)
            cur = self.arbiter.get(LEASES, name)
            if cur is None and in_grace:
                # never claimed: give a slow-starting peer its grace
                continue
            req = self.arbiter.get(LEASES, reclaim_lease_name(slot))
            if (
                req is not None
                and req.holder_identity
                and req.holder_identity != self.identity
                and time.time() <= req.renew_time + req.lease_duration_seconds
            ):
                # a reclaiming primary has dibs on this slot — don't
                # race (or instantly re-adopt) the lease we just
                # released for it
                continue
            t0 = time.monotonic()
            try:
                lease = self.arbiter.try_acquire_lease(name, self.identity, self.lease_s)
            except ConnectionError:
                continue
            if lease.holder_identity != self.identity:
                continue  # still live, or another survivor won the race
            if cur is not None and cur.holder_identity == self.identity:
                # we already held it (e.g. a handoff raced our own
                # renewal) — nothing to adopt
                continue
            self._adopt(slot, t0)

    def _adopt(self, slot: int, t0: float) -> None:
        """We hold the orphaned slot's lease; take over its work:
        reconcile the dead owner's journal against store truth, widen
        the cache's owned set (which re-ingests the orphaned backlog),
        and notify the scheduler so streaming seeds the adopted gang
        keys. Breaker-backed: a takeover failure releases the slot and
        backs off, so a poisoned journal cannot wedge every survivor in
        a tight adopt/crash loop."""
        if not self._breaker.allow():
            metrics.register_shard_adoption("flap_suppressed")
            try:
                self.arbiter.release_lease(slot_lease_name(slot), self.identity)
            except ConnectionError:
                pass
            return
        try:
            if faults.should_fire("shard.adopt"):
                raise faults.FaultInjected("shard.adopt: injected takeover failure")
            report = self._reconcile_peer_journal(slot)
            with self._lock:
                owned = set(self._owned) | {slot}
            change = self.cache.set_owned_slots(owned)
            with self._lock:
                # merge, don't overwrite: a concurrent handoff may have
                # retired another slot while set_owned_slots ran
                self._owned = set(self._owned) | {slot}
                self._adoption_order.append(slot)
            self._publish_owned(owned)
            self._notify(change["adopted_gangs"], change["removed_gangs"])
            took = time.monotonic() - t0
            metrics.register_shard_adoption("adopted")
            metrics.observe_shard_takeover(took)
            self._breaker.record_success()
            log.infof(
                "slot %d adopted by %s in %.3fs (%d pod(s) re-ingested%s)",
                slot, self.identity, took, change["adopted_pods"],
                f"; journal: {report.as_dict()}" if report is not None else "",
            )
        except Exception as e:  # noqa: BLE001 - takeover must not kill the loop
            self._breaker.record_failure()
            metrics.register_shard_adoption("failed")
            log.errorf("slot %d adoption failed (%s); releasing for retry", slot, e)
            try:
                self.arbiter.release_lease(slot_lease_name(slot), self.identity)
            except ConnectionError:
                pass

    def _reconcile_peer_journal(self, slot: int):
        """Journal takeover for the dead owner of ``slot``: replay its
        ``shard-{slot}.wal`` and reconcile the in-flight intents against
        store truth (confirm landed, re-dispatch orphaned, roll back
        half-bound gangs) BEFORE the backlog is rescheduled — otherwise
        the adopter would race the dead shard's already-dispatched
        writes. Never raises on a missing/foreign journal (adoption
        proceeds; the optimistic-bind path stays correct regardless)."""
        if not self.journal_dir or slot == self.primary:
            return None
        path = shard_journal_path(self.journal_dir, slot)
        if not os.path.exists(path):
            return None
        from kube_batch_tpu.recovery import WriteIntentJournal, reconcile_journal

        journal = WriteIntentJournal(path)
        try:
            return reconcile_journal(journal, self.cache.store)
        finally:
            journal.close()

    # -- handoff -------------------------------------------------------------

    def handoff(self, slot: int, drain_s: Optional[float] = None) -> bool:
        """Graceful planned move of an owned slot: stop dispatching its
        work (narrow the cache filter first), drain this scheduler's
        in-flight journal intents for pods in the slot, then release the
        lease so the next owner adopts with a clean journal. An injected
        ``shard.handoff`` failure aborts the protocol and keeps the slot
        (we still hold the lease — correctness over the planned move)."""
        with self._lock:
            if slot not in self._owned:
                return False
            owned = set(self._owned)
        owned.discard(slot)
        change = self.cache.set_owned_slots(owned)
        try:
            if faults.should_fire("shard.handoff"):
                raise faults.FaultInjected("shard.handoff: injected handoff failure")
            self._drain_slot(slot, drain_s)
            self.arbiter.release_lease(slot_lease_name(slot), self.identity)
        except Exception as e:  # noqa: BLE001 - keep the slot on any failure
            log.errorf("slot %d handoff aborted (%s); keeping the slot", slot, e)
            restored = self.cache.set_owned_slots(owned | {slot})
            self._notify(restored["adopted_gangs"], restored["removed_gangs"])
            metrics.register_shard_handoff("aborted")
            return False
        with self._lock:
            # merge, don't overwrite: a concurrent adopt may have added
            # another slot while we drained this one
            self._owned = set(self._owned) - {slot}
            if slot in self._adoption_order:
                self._adoption_order.remove(slot)
        self._publish_owned(owned)
        self._notify(change["adopted_gangs"], change["removed_gangs"])
        metrics.register_shard_handoff("completed")
        log.infof("slot %d handed off by %s", slot, self.identity)
        return True

    def _drain_slot(self, slot: int, drain_s: Optional[float]) -> None:
        """Wait (bounded) until this cache's journal holds no in-flight
        intent for a pod hashing into ``slot`` — the 'confirm journal'
        step of the handoff protocol. The filter is already narrowed, so
        no NEW intents for the slot can appear; this only waits out the
        write pool's in-flight tail."""
        journal = getattr(self.cache, "journal", None)
        if journal is None:
            return
        deadline = time.monotonic() + (self.lease_s if drain_s is None else drain_s)
        while time.monotonic() < deadline:
            pending = False
            for intent in journal.outstanding():
                ns, _, name = intent.pod.partition("/")
                pod = self.cache.store.get_pod(ns, name)
                if pod is None:
                    continue
                key = shard_key_of(pod, self.cache.store, self.cache.shard_key)
                if shard_index(key, self.shards) == slot:
                    pending = True
                    break
            if not pending:
                return
            time.sleep(min(0.01, self.renew_s))
        log.warningf(
            "slot %d handoff: drain window expired with intents still in "
            "flight; the next owner's takeover reconciliation covers them",
            slot,
        )

    def _honor_reclaims(self) -> None:
        """A joining scheduler that found its primary adopted acquires
        ``shard-slot-{i}-reclaim``; hand adopted slots back to live
        reclaimers (the polite half of the reclaim protocol)."""
        owned = self.owned_slots()
        now = time.time()
        for slot in sorted(owned):
            if slot == self.primary:
                continue
            req = self.arbiter.get(LEASES, reclaim_lease_name(slot))
            if req is None or not req.holder_identity:
                continue
            if now > req.renew_time + req.lease_duration_seconds:
                continue  # stale request; the joiner died again
            log.infof("slot %d reclaim requested by %s; handing off",
                      slot, req.holder_identity)
            self.handoff(slot)

    # -- rebalancing ---------------------------------------------------------

    def _maybe_rebalance(self) -> None:
        """Conflict-aware shedding: when this scheduler's bind-conflict
        counters (the same deltas the fleet heatmap aggregates) grow
        faster than ``KBT_SHARD_REBALANCE`` per probe round and it owns
        adopted slots, hand the most recent one off — a less contended
        peer adopts it within the lease window."""
        if self.rebalance <= 0:
            return
        fn = self._conflict_fn or _process_conflicts_total
        total = float(fn())
        with self._lock:
            delta = total - self._last_conflicts
            self._last_conflicts = total
            owned = set(self._owned)
            order = list(self._adoption_order)
        slot = plan_rebalance(owned, self.primary, order, delta, self.rebalance)
        if slot is not None:
            log.infof(
                "rebalance: conflict delta %.0f >= %.0f; shedding slot %d",
                delta, self.rebalance, slot,
            )
            self.handoff(slot)

    # -- bookkeeping ---------------------------------------------------------

    def _set_owned(self, owned: set) -> None:
        change = self.cache.set_owned_slots(owned)
        with self._lock:
            self._owned = set(owned)
        self._publish_owned(owned)
        self._notify(change["adopted_gangs"], change["removed_gangs"])

    def _publish_owned(self, owned: set) -> None:
        metrics.set_shard_slots_owned(len(owned))
        for slot in range(self.shards):
            metrics.set_shard_slot_owned(slot, slot in owned)

    def _notify(self, adopted_gangs: set, removed_gangs: set) -> None:
        if self._on_owned_change is not None and (adopted_gangs or removed_gangs):
            try:
                self._on_owned_change(set(adopted_gangs), set(removed_gangs))
            except Exception as e:  # noqa: BLE001 - observer must not break takeover
                log.errorf("owned-change callback failed: %s", e)


def _process_conflicts_total() -> float:
    """Sum of this process's contended-bind outcomes (won/retried/lost)
    — the default conflict signal ``_maybe_rebalance`` thresholds."""
    total = 0.0
    for key, value in metrics.federation_conflicts.samples().items():
        labels = dict(key)
        if labels.get("outcome") in ("won", "retried", "lost"):
            total += value
    return total


class FederatedCache(SchedulerCache):
    """A SchedulerCache owning a dynamic set of shard slots.

    The pod filter narrows the base rule ("my pending pods + every
    non-pending pod") to "my pending pods *in my owned slots* + every
    non-pending pod" — full cluster capacity stays visible, only the
    work divides. The owned set starts as ``{shard}`` (the primary
    slot) and widens/narrows at runtime as a ``ShardSlotManager``
    adopts orphaned slots or hands slots off; ``set_owned_slots``
    backfills the mirror from store truth so pods whose events predate
    a filter flip are not lost. Conditional (optimistic) dispatch is
    forced on."""

    def __init__(
        self,
        store,
        shard: int = 0,
        shards: int = 1,
        shard_key: Optional[str] = None,
        **kwargs,
    ) -> None:
        if not (0 <= shard < max(1, shards)):
            raise ValueError(f"shard {shard} out of range for {shards} shards")
        self.shard = shard
        self.shards = max(1, shards)
        self.shard_key = shard_key or shard_key_mode()
        if self.shard_key not in SHARD_KEYS:
            raise ValueError(f"shard_key must be one of {SHARD_KEYS}")
        # Set before super().__init__: subscription replays existing
        # store objects through _pod_filter during construction. Reads
        # are a single attribute load (atomic swap on ownership change).
        self._owned: frozenset[int] = frozenset({shard})
        kwargs["conditional_binds"] = True
        super().__init__(store, **kwargs)

    @property
    def owned_slots(self) -> frozenset:
        return self._owned

    def _pod_filter(self, pod) -> bool:
        # Only UNBOUND pending pods shard: a bound pod — even one still
        # phase-Pending, and even another shard's — holds node capacity
        # this scheduler must account for, or its snapshots would
        # over-place and every dispatch under contention would lose its
        # store-side admission check forever (conflict livelock).
        if pod.phase == PodPhase.PENDING and not pod.node_name:
            return (
                pod.scheduler_name == self.scheduler_name
                and shard_index(
                    shard_key_of(pod, self.store, self.shard_key), self.shards
                )
                in self._owned
            )
        return True  # bound/terminal pods hold capacity for everyone

    def _has_task(self, pod) -> bool:
        """Whether the mirror already tracks this pod (dedupe guard for
        the backfill below: ``_add_pod`` is not idempotent)."""
        from kube_batch_tpu.api.job_info import TaskInfo

        ti = TaskInfo(pod)
        self._resolve_shadow_job(ti)
        if not ti.job:
            return False
        with self._mutex:
            job = self.jobs.get(ti.job)
            return job is not None and ti.uid in job.tasks

    def set_owned_slots(self, slots) -> dict:
        """Swap the owned-slot set and reconcile the mirror against
        store truth. Ordering is the correctness argument: the filter
        flips FIRST (future events for added slots pass, removed slots
        drop), THEN the store is listed and the mirror backfilled — so
        an event racing the flip is at worst applied twice, and the
        dedupe guard makes the second application a no-op. Returns what
        changed: added/removed slots, re-ingested pod count, and the
        gang keys gained/lost (what streaming seeds/prunes)."""
        new = frozenset(int(s) for s in slots)
        for s in new:
            if not (0 <= s < self.shards):
                raise ValueError(f"slot {s} out of range for {self.shards} shards")
        old = self._owned
        change = {
            "added": set(new - old),
            "removed": set(old - new),
            "adopted_pods": 0,
            "adopted_gangs": set(),
            "removed_gangs": set(),
        }
        if new == old:
            return change
        self._owned = new
        for pod in self.store.list(PODS):
            if pod.phase != PodPhase.PENDING or pod.node_name:
                continue
            if pod.scheduler_name != self.scheduler_name:
                continue
            idx = shard_index(
                shard_key_of(pod, self.store, self.shard_key), self.shards
            )
            if idx in change["added"]:
                change["adopted_gangs"].add(_gang_key(pod))
                if not self._has_task(pod):
                    self.add_pod(pod)
                    change["adopted_pods"] += 1
            elif idx in change["removed"]:
                change["removed_gangs"].add(_gang_key(pod))
                if self._has_task(pod):
                    self.delete_pod(pod)
        if change["added"] or change["removed"]:
            log.infof(
                "owned slots %s -> %s (+%s -%s; %d pod(s) re-ingested)",
                sorted(old), sorted(new), sorted(change["added"]),
                sorted(change["removed"]), change["adopted_pods"],
            )
        return change


# -- fsck --------------------------------------------------------------------


def fsck(
    store,
    epsilon: float = 1e-6,
    shard_key: Optional[str] = None,
    now: Optional[float] = None,
) -> list[str]:
    """Cross-scheduler consistency check over store truth; returns
    violations (empty = clean). Invariants:

    - every bound, non-terminal pod names an existing node;
    - per node, the sum of bound non-terminal requests fits allocatable;
    - the store's incremental allocation ledger (``node_allocated``)
      agrees with that recomputed sum — a drifted ledger means a
      conditional admission decision was made against wrong state;
    - **unowned slots**: when the world runs leased shard slots
      (``shard-slot-*`` leases exist), every slot with pending unbound
      pods must have a live, unexpired lease — orphaned work is visible
      to operators even with adoption disabled. ``shard_key`` overrides
      the hash mode (default: this process's ``KBT_SHARD_KEY``);
      ``now`` pins the expiry clock for deterministic tests."""
    from kube_batch_tpu.api.helpers import get_pod_resource_request
    from kube_batch_tpu.api.resource_info import Resource

    out: list[str] = []
    nodes = {n.name: n for n in store.list(NODES)}
    per_node: dict[str, Resource] = {}
    for pod in store.list(PODS):
        if not pod.node_name or pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            continue
        if pod.node_name not in nodes:
            out.append(
                f"pod {pod.namespace}/{pod.name} bound to missing node "
                f"{pod.node_name!r}"
            )
            continue
        per_node.setdefault(pod.node_name, Resource.empty()).add(
            get_pod_resource_request(pod)
        )
    for name, used in per_node.items():
        cap = Resource.from_resource_list(nodes[name].allocatable)
        if not used.less_equal(cap):
            out.append(f"node {name} over capacity: used {used} > allocatable {cap}")
    ledger = getattr(store, "node_allocated", None)
    if ledger is not None:
        for name in nodes:
            have = ledger(name)
            want = per_node.get(name, Resource.empty())
            if abs(have.milli_cpu - want.milli_cpu) > epsilon or abs(
                have.memory - want.memory
            ) > epsilon:
                out.append(
                    f"node {name} allocation ledger drift: ledger {have} vs "
                    f"recomputed {want}"
                )
    # unowned-slot check: only meaningful when slot leases exist (plain
    # static-map or single-scheduler worlds skip it)
    slot_leases = {}
    for lease in store.list(LEASES):
        slot = parse_slot_lease_name(lease.metadata.name)
        if slot is not None:
            slot_leases[slot] = lease
    if slot_leases:
        now = time.time() if now is None else now
        slots_n = max(slot_leases) + 1
        mode = shard_key or shard_key_mode()
        pending_by_slot: dict[int, int] = {}
        for pod in store.list(PODS):
            if pod.phase == PodPhase.PENDING and not pod.node_name:
                idx = shard_index(shard_key_of(pod, store, mode), slots_n)
                pending_by_slot[idx] = pending_by_slot.get(idx, 0) + 1
        for slot, n in sorted(pending_by_slot.items()):
            lease = slot_leases.get(slot)
            live = (
                lease is not None
                and lease.holder_identity
                and now <= lease.renew_time + lease.lease_duration_seconds
            )
            if not live:
                holder = "no lease" if lease is None else (
                    "released" if not lease.holder_identity
                    else f"expired lease held by {lease.holder_identity}"
                )
                out.append(
                    f"unowned slot {slot}: {n} pending pod(s) but no live "
                    f"lease ({holder})"
                )
    return out


# -- smoke -------------------------------------------------------------------


def _seed_world(store, gangs: int, members: int, nodes: int) -> None:
    from kube_batch_tpu.testing import (
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    if store.get("queues", "default") is None:
        store.create_queue(build_queue("default"))
    for i in range(nodes):
        store.create_node(
            build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=32))
        )
    for g in range(gangs):
        name = f"fg{g}"
        store.create_pod_group(build_pod_group(name, min_member=members))
        for m in range(members):
            store.create_pod(
                build_pod(
                    name=f"{name}-p{m}",
                    group_name=name,
                    req=build_resource_list(cpu=1, memory="512Mi"),
                )
            )


def _wait_all_bound(store, total: int, deadline_s: float) -> bool:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        pods = store.list(PODS)
        if len(pods) >= total and all(p.node_name for p in pods):
            return True
        time.sleep(0.005)
    return False


def smoke(
    shards: int = 2,
    gangs: int = 6,
    members: int = 3,
    nodes: int = 8,
    protocol: Optional[int] = None,
    codec: Optional[str] = None,
    rtt_probes: int = 0,
    bulk: bool = False,
) -> dict:
    """End-to-end federation proof, runnable standalone
    (``python -m kube_batch_tpu.federation``) and from hack/verify.py:

    1. start a real SchedulerServer on loopback whose own loop is idled
       (unmatched scheduler name) — it is the store process;
    2. run ``shards`` FederatedCache+Scheduler pairs against it, each
       over its own LoopbackBackend (the full wire path: list+watch,
       conditional binds, 409 conflicts);
    3. assert every pod bound exactly once (a store-side handler counts
       ""->node transitions per pod), the union placement is
       capacity-valid (fsck clean), and the *set* of bound pods matches
       a single-scheduler twin on an identical world (which pods bind
       is deterministic; which node wins a race is not).

    ``protocol``/``codec`` pin the wire generation for bench rows:
    ``protocol=1`` runs the whole topology on the pre-v2 surface
    (server pinned, clients capped), ``protocol=2`` the full v2 stack.
    When pinned, the result additionally carries the measured row —
    ``binds_per_s``, ``wire_bytes_per_bind`` (protocol bytes both
    directions over total binds), ``backend_rtt_p50_s`` (``rtt_probes``
    timed version round-trips: fresh-connection urllib under v1, pooled
    keep-alive under v2) and server-side txn batch stats.

    ``bulk=True`` runs every scheduler (and the parity twin) on the
    gang bulk-dispatch conf (``enqueue, xla_allocate`` with the device
    size floor pinned off) so binds flow through ``bind_many`` — the
    path that opens all-or-nothing gang transactions and, under v2,
    coalesces the cycle's gangs into one ``/backend/v1/txn`` round
    trip. The default serial conf binds per task and never batches."""
    import statistics
    import tempfile
    import threading

    from kube_batch_tpu.cache import EventHandler, LoopbackBackend
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer

    total = gangs * members
    # bulk-dispatch conf: no O(cluster) fairness sweeps, and the device
    # size floor pinned off so small worlds still route through
    # bind_many's gang transactions instead of per-pod serial dispatch
    conf_path = None
    saved_floor = os.environ.get("KBT_MIN_DEVICE_PAIRS")
    if bulk:
        os.environ["KBT_MIN_DEVICE_PAIRS"] = "0"
        fh = tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", prefix="kbt-fed-", delete=False
        )
        fh.write(
            'actions: "enqueue, xla_allocate"\n'
            "tiers:\n"
            "- plugins:\n"
            "  - name: priority\n"
            "  - name: gang\n"
            "  - name: conformance\n"
            "- plugins:\n"
            "  - name: predicates\n"
            "  - name: nodeorder\n"
        )
        fh.close()
        conf_path = fh.name
    server = SchedulerServer(
        scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
        schedule_period=60.0,
        wire_protocol=1 if protocol == 1 else 2,
    )
    server.start()
    bind_counts: dict[str, int] = {}
    counts_lock = threading.Lock()

    def _count_bind(old, new) -> None:
        if not old.node_name and new.node_name:
            with counts_lock:
                key = f"{new.namespace}/{new.name}"
                bind_counts[key] = bind_counts.get(key, 0) + 1

    server.store.add_event_handler(PODS, EventHandler(on_update=_count_bind))
    backends: list[LoopbackBackend] = []
    scheds: list[tuple[Scheduler, threading.Thread]] = []
    stop = threading.Event()
    txn0 = metrics.store_backend_txn_batch.snapshot()
    rtts: list[float] = []
    negotiated: tuple = (None, None)
    wire_bytes = 0
    elapsed = 0.0
    try:
        _seed_world(server.store, gangs, members, nodes)
        base = f"http://127.0.0.1:{server.listen_port}"
        t0 = time.monotonic()
        for i in range(shards):
            backend = LoopbackBackend(base, protocol=protocol, codec=codec)
            cache = FederatedCache(
                backend, shard=i, shards=shards, shard_key="gang",
                staleness_fn=backend.snapshot_age,
            )
            cache.run()
            backend.start(period=0.02)
            backends.append(backend)
            sched = Scheduler(
                cache, scheduler_conf=conf_path, schedule_period=0.05
            )
            t = threading.Thread(
                target=sched.run, args=(stop,), name=f"kb-fed-{i}", daemon=True
            )
            t.start()
            scheds.append((sched, t))
        all_bound = _wait_all_bound(server.store, total, deadline_s=60.0)
        elapsed = time.monotonic() - t0
        for _ in range(max(0, rtt_probes)):
            p0 = time.perf_counter()
            backends[0].version
            rtts.append(time.perf_counter() - p0)
    finally:
        stop.set()
        for _, t in scheds:
            t.join(timeout=10.0)
        for backend in backends:
            backend.stop()
        for sched, _ in scheds:
            sched.cache.stop()
        if backends:
            negotiated = (backends[0]._protocol, backends[0]._codec)
            wire_bytes = sum(b.bytes_tx + b.bytes_rx for b in backends)
        server.stop()

    violations = fsck(server.store)
    counts = dict(bind_counts)
    exactly_once = all_bound and sorted(counts.values()) == [1] * total

    # single-scheduler twin: same world, one cache, in-process
    from kube_batch_tpu.cache import ClusterStore

    twin = ClusterStore()
    _seed_world(twin, gangs, members, nodes)
    twin_cache = SchedulerCache(twin)
    twin_cache.run()
    twin_sched = Scheduler(
        twin_cache, scheduler_conf=conf_path, schedule_period=0.02
    )
    twin_stop = threading.Event()
    t = threading.Thread(target=twin_sched.run, args=(twin_stop,), daemon=True)
    t.start()
    try:
        _wait_all_bound(twin, total, deadline_s=30.0)
    finally:
        twin_stop.set()
        t.join(timeout=10.0)
        twin_cache.stop()
        if bulk:
            if saved_floor is None:
                os.environ.pop("KBT_MIN_DEVICE_PAIRS", None)
            else:
                os.environ["KBT_MIN_DEVICE_PAIRS"] = saved_floor
            try:
                os.unlink(conf_path)
            except OSError:
                pass
    fed_bound = {
        f"{p.namespace}/{p.name}"
        for p in server.store.list(PODS)
        if p.node_name
    }
    twin_bound = {
        f"{p.namespace}/{p.name}" for p in twin.list(PODS) if p.node_name
    }

    out = {
        "shards": shards,
        "pods": total,
        "bound": len(fed_bound),
        "exactly_once": exactly_once,
        "double_binds": sum(1 for v in counts.values() if v > 1),
        "fsck_violations": violations,
        "union_parity": fed_bound == twin_bound,
    }
    if protocol is not None:
        txn1 = metrics.store_backend_txn_batch.snapshot()
        batches = txn1["count"] - txn0["count"]
        out.update(
            {
                "protocol": negotiated[0],
                "codec": negotiated[1],
                "elapsed_s": round(elapsed, 4),
                "binds_per_s": (
                    round(total / elapsed, 2) if elapsed > 0 else 0.0
                ),
                "wire_bytes_per_bind": round(wire_bytes / max(1, total), 1),
                "backend_rtt_p50_s": (
                    round(statistics.median(rtts), 6) if rtts else None
                ),
                "txn_batches": batches,
                "txn_batch_mean": (
                    round((txn1["sum"] - txn0["sum"]) / batches, 2)
                    if batches else 0.0
                ),
            }
        )
    out["ok"] = bool(
        all_bound
        and exactly_once
        and not violations
        and out["union_parity"]
        and out["bound"] == total
    )
    return out


def smoke_streaming(
    shards: int = 2,
    gangs: int = 6,
    members: int = 3,
    nodes: int = 8,
) -> dict:
    """Streaming-federation parity drill (``python -m
    kube_batch_tpu.federation --streaming``, the hack/verify.py
    ``federation_streaming_smoke`` gate): N federated shards over one
    live LoopbackBackend wire path each, run twice on an identical
    arrival sequence —

    1. **streaming**: every shard's conf says ``streaming: true`` with a
       long (5s) backstop period, so after the initial full cycle the
       arrivals bind through event-driven micro-cycles over each shard's
       resident arena, peer binds crossing the shard filter as bound-pod
       adds the trigger *absorbs* as occupancy patches;
    2. **periodic**: the same world on ``streaming: false`` with a short
       full-cycle period.

    Asserts the pinned invariant — federated micro drain + backstop
    ≡ periodic federated loop, **bind-for-bind** (same pod on the same
    node, not just the same bound set) — plus exactly-once binds, clean
    fsck, micro-cycles actually taken, and pump-thread/listener teardown
    hygiene (zero leaked store listeners after the shards stop)."""
    import tempfile
    import threading

    from kube_batch_tpu.cache import EventHandler, LoopbackBackend
    from kube_batch_tpu.ops import encode_cache
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.server import SchedulerServer
    from kube_batch_tpu.streaming import SMOKE_CONF
    from kube_batch_tpu.testing import (
        build_node,
        build_pod,
        build_pod_group,
        build_resource_list,
    )

    def run_mode(streaming: bool) -> tuple[dict, dict]:
        with tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", prefix="kbt-fedstream-", delete=False
        ) as fh:
            fh.write(SMOKE_CONF.format(streaming=str(streaming).lower()))
            conf_path = fh.name
        server = SchedulerServer(
            scheduler_name="store-arbiter", listen_address="127.0.0.1:0",
            schedule_period=60.0,
        )
        server.start()
        store = server.store
        bind_counts: dict[str, int] = {}
        counts_lock = threading.Lock()

        def _count_bind(old, new) -> None:
            if not old.node_name and new.node_name:
                with counts_lock:
                    key = f"{new.namespace}/{new.name}"
                    bind_counts[key] = bind_counts.get(key, 0) + 1

        store.add_event_handler(PODS, EventHandler(on_update=_count_bind))
        listeners_before = encode_cache.listener_count()
        backends: list[LoopbackBackend] = []
        scheds: list[tuple[Scheduler, threading.Thread]] = []
        stop = threading.Event()
        try:
            # the in-process server already bootstrapped the default queue
            for i in range(nodes):
                store.create_node(
                    build_node(
                        f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=32)
                    )
                )
            base = f"http://127.0.0.1:{server.listen_port}"
            for i in range(shards):
                backend = LoopbackBackend(base)
                cache = FederatedCache(
                    backend, shard=i, shards=shards, shard_key="gang",
                    staleness_fn=backend.snapshot_age,
                )
                cache.run()
                backend.start(period=0.02)
                backends.append(backend)
                sched = Scheduler(
                    cache, scheduler_conf=conf_path,
                    schedule_period=5.0 if streaming else 0.05,
                )
                t = threading.Thread(
                    target=sched.run, args=(stop,), name=f"kb-fedstream-{i}",
                    daemon=True,
                )
                t.start()
                scheds.append((sched, t))
            # identical sequential arrival schedule both modes: feed one
            # gang, wait until its owner shard binds it, feed the next —
            # every micro-cycle solves against a world whose history is
            # exactly the periodic run's, so parity is bind-for-bind
            for g in range(gangs):
                name = f"fs{g}"
                store.create_pod_group(build_pod_group(name, min_member=members))
                for m in range(members):
                    store.create_pod(
                        build_pod(
                            name=f"{name}-p{m}", group_name=name,
                            req=build_resource_list(cpu=1, memory="512Mi"),
                        )
                    )
                deadline = time.monotonic() + 30.0
                while True:
                    mine = [
                        p for p in store.list(PODS)
                        if p.name.startswith(f"{name}-")
                    ]
                    if len(mine) == members and all(p.node_name for p in mine):
                        break
                    if time.monotonic() > deadline:
                        raise AssertionError(
                            f"gang {name} not bound within 30s "
                            f"(streaming={streaming})"
                        )
                    time.sleep(0.002)
        finally:
            stop.set()
            for _, t in scheds:
                t.join(timeout=10.0)
            for backend in backends:
                backend.stop()
            for sched, _ in scheds:
                sched.cache.stop()
            try:
                os.unlink(conf_path)
            except OSError:
                pass
        placed = {
            f"{p.namespace}/{p.name}": p.node_name for p in store.list(PODS)
        }
        violations = fsck(store)
        with counts_lock:
            counts = dict(bind_counts)
        stats = {
            "micro_cycles": sum(s.micro_cycles_run for s, _ in scheds),
            "exactly_once": sorted(counts.values()) == [1] * (gangs * members),
            "fsck_violations": violations,
            # teardown hygiene: stopping the shards must leave zero store
            # listeners (a leaked trigger would fire into a dead loop)
            # and every pump thread joined
            "listeners_clean": encode_cache.listener_count() == listeners_before,
            "pumps_joined": all(b._thread is None for b in backends),
        }
        server.stop()
        return placed, stats

    stream_placed, stream_stats = run_mode(True)
    full_placed, full_stats = run_mode(False)
    total = gangs * members
    out = {
        "shards": shards,
        "gangs": gangs,
        "pods": total,
        "bound": sum(1 for v in stream_placed.values() if v),
        "micro_cycles": stream_stats["micro_cycles"],
        "parity": stream_placed == full_placed,
        "exactly_once": stream_stats["exactly_once"] and full_stats["exactly_once"],
        "fsck_violations": (
            stream_stats["fsck_violations"] + full_stats["fsck_violations"]
        ),
        "listeners_clean": (
            stream_stats["listeners_clean"] and full_stats["listeners_clean"]
        ),
        "pumps_joined": stream_stats["pumps_joined"] and full_stats["pumps_joined"],
        "full_cycle_micro_cycles": full_stats["micro_cycles"],
    }
    out["ok"] = bool(
        out["parity"]
        and out["bound"] == total
        and out["micro_cycles"] > 0
        and out["full_cycle_micro_cycles"] == 0
        and out["exactly_once"]
        and not out["fsck_violations"]
        and out["listeners_clean"]
        and out["pumps_joined"]
    )
    return out


def smoke_kill_one(
    shards: int = 4,
    gangs: int = 16,
    members: int = 2,
    nodes: int = 12,
    lease_s: float = 1.0,
    renew_s: float = 0.25,
    strict: bool = False,
) -> dict:
    """Kill-and-adopt drill (``python -m kube_batch_tpu.federation
    --kill-one``, the hack/verify.py ``--federation`` gate and the image
    build both run it):

    1. run ``shards`` FederatedCache+Scheduler pairs over ONE in-process
       store, each holding its primary slot through a ``ShardSlotManager``
       (short leases: ``lease_s``/``renew_s``) and journaling intents to
       ``shard-{i}.wal``;
    2. the shard owning the most gangs gets a dying binder that raises a
       BaseException mid-``bind_many`` after a few gang transactions —
       the in-process analogue of SIGKILL-ing the owner with the write
       pool mid-batch — then its slot manager is ``kill()``-ed (renewals
       stop WITHOUT release, so the lease must expire on the arbiter's
       clock);
    3. while the lease runs out, fsck is polled for the ``unowned slot``
       violation (the operator-visible orphaned-work window);
    4. a survivor must adopt the slot within the lease window
       (lease + 2×renew + slack), reconcile the dead shard's journal,
       and schedule its backlog;
    5. final asserts: every pod bound exactly once (zero lost, zero
       duplicate), fsck clean, union parity vs a single-scheduler twin,
       and exactly one survivor owns the orphaned slot.

    MTTR here = binder death -> first post-kill bind of a pod hashing to
    the victim's slot (journal re-dispatch or adopted-backlog bind,
    whichever lands first). ``strict`` additionally requires the
    unowned-slot fsck window to have been OBSERVED by the poll (the
    window is real but an aggressive adopter can shrink it below the
    poll period, so by default it is reported, not gated)."""
    import tempfile
    import threading

    from kube_batch_tpu.cache import ClusterStore, EventHandler
    from kube_batch_tpu.cache.cache import StoreBinder
    from kube_batch_tpu.recovery import WriteIntentJournal
    from kube_batch_tpu.scheduler import Scheduler

    total = gangs * members
    die_after = 2

    class _Killed(BaseException):
        # BaseException on purpose: nothing between the binder and the
        # kb-write pool may catch it, mirroring a process death
        pass

    killed: dict = {"evt": threading.Event()}

    class _DyingBinder(StoreBinder):
        """Commits ``left`` write statements, then dies forever."""

        def __init__(self, store, left):
            super().__init__(store)
            self.left = left

        def _die(self):
            if "t" not in killed:
                killed["t"] = time.monotonic()
            killed["evt"].set()
            raise _Killed()

        def bind_many_versioned(self, bindings, snapshot_version):
            if killed["evt"].is_set() or self.left <= 0:
                self._die()
            self.left -= 1
            return super().bind_many_versioned(bindings, snapshot_version)

        def bind(self, pod, hostname):
            if killed["evt"].is_set() or self.left <= 0:
                self._die()
            self.left -= 1
            super().bind(pod, hostname)

    store = ClusterStore()
    _seed_world(store, gangs, members, nodes)

    # victim = the slot owning the most gangs (guarantees work both
    # before the kill and orphaned after it)
    gang_slot: dict[str, int] = {}
    for pod in store.list(PODS):
        gang_slot[_gang_key(pod)] = shard_index(
            shard_key_of(pod, store, "gang"), shards
        )
    per_slot: dict[int, int] = {}
    for slot in gang_slot.values():
        per_slot[slot] = per_slot.get(slot, 0) + 1
    victim = max(per_slot, key=lambda s: (per_slot[s], -s))

    bind_counts: dict[str, int] = {}
    bind_times: list = []  # (slot, monotonic stamp)
    counts_lock = threading.Lock()

    def _count_bind(old, new) -> None:
        if not old.node_name and new.node_name:
            with counts_lock:
                key = f"{new.namespace}/{new.name}"
                bind_counts[key] = bind_counts.get(key, 0) + 1
                bind_times.append(
                    (shard_index(shard_key_of(new, store, "gang"), shards),
                     time.monotonic())
                )

    store.add_event_handler(PODS, EventHandler(on_update=_count_bind))

    mgrs: list = []
    caches: list = []
    journals: list = []
    threads: list = []
    stops: list = []
    note = ""
    t_kill = None
    t_adopt = None
    adopter = None
    unowned_observed = False
    all_bound = False
    with tempfile.TemporaryDirectory() as tmp:
        try:
            for i in range(shards):
                journal = WriteIntentJournal(shard_journal_path(tmp, i))
                journals.append(journal)
                binder = _DyingBinder(store, die_after) if i == victim else None
                cache = FederatedCache(
                    store, shard=i, shards=shards, shard_key="gang",
                    binder=binder, journal=journal,
                )
                cache.run()
                caches.append(cache)
                sched = Scheduler(cache, schedule_period=0.05)
                mgr = ShardSlotManager(
                    store, cache, identity=f"kb-smoke-{i}",
                    lease_s=lease_s, renew_s=renew_s, adopt=True,
                    journal_dir=tmp, grace_s=5.0, rebalance=0,
                    on_owned_change=(
                        lambda a, r, s=sched: s.on_owned_slots_changed(a, r)
                    ),
                )
                if not mgr.start(deadline_s=10.0):
                    raise RuntimeError(f"shard {i} never acquired its slot")
                mgrs.append(mgr)
                stop = threading.Event()
                stops.append(stop)
                t = threading.Thread(
                    target=sched.run, args=(stop,), name=f"kb-kill-{i}",
                    daemon=True,
                )
                t.start()
                threads.append(t)

            if not killed["evt"].wait(timeout=30.0):
                note = "victim never dispatched (no kill happened)"
            else:
                t_kill = killed["t"]
                # the "SIGKILL": stop the victim's scheduler and stop
                # renewing WITHOUT releasing — the lease must expire
                stops[victim].set()
                threads[victim].join(timeout=10.0)
                mgrs[victim].kill()
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    for i, mgr in enumerate(mgrs):
                        if i != victim and victim in mgr.owned_slots():
                            t_adopt = time.monotonic()
                            adopter = mgr.identity
                            break
                    if t_adopt is not None:
                        break
                    if not unowned_observed:
                        unowned_observed = any(
                            v.startswith(f"unowned slot {victim}")
                            for v in fsck(store, shard_key="gang")
                        )
                    time.sleep(0.005)
                all_bound = _wait_all_bound(store, total, deadline_s=60.0)
        finally:
            for stop in stops:
                stop.set()
            for t in threads:
                t.join(timeout=10.0)
            double_owned = sum(
                1 for i, mgr in enumerate(mgrs)
                if i != victim and victim in mgr.owned_slots()
            )
            for i, mgr in enumerate(mgrs):
                if i != victim:
                    mgr.stop(release=True)
            for cache in caches:
                cache.stop()
            for journal in journals:
                journal.close()

    violations = fsck(store, shard_key="gang")
    counts = dict(bind_counts)
    exactly_once = all_bound and sorted(counts.values()) == [1] * total

    # single-scheduler twin on an identical world: the SET of bound pods
    # must match (which pods bind is deterministic)
    import threading as _threading

    twin = ClusterStore()
    _seed_world(twin, gangs, members, nodes)
    twin_cache = SchedulerCache(twin)
    twin_cache.run()
    from kube_batch_tpu.scheduler import Scheduler as _Scheduler

    twin_sched = _Scheduler(twin_cache, schedule_period=0.02)
    twin_stop = _threading.Event()
    t = _threading.Thread(target=twin_sched.run, args=(twin_stop,), daemon=True)
    t.start()
    try:
        _wait_all_bound(twin, total, deadline_s=30.0)
    finally:
        twin_stop.set()
        t.join(timeout=10.0)
        twin_cache.stop()
    fed_bound = {
        f"{p.namespace}/{p.name}" for p in store.list(PODS) if p.node_name
    }
    twin_bound = {
        f"{p.namespace}/{p.name}" for p in twin.list(PODS) if p.node_name
    }

    takeover_window_s = lease_s + 2 * renew_s + 1.0
    takeover_s = (
        round(t_adopt - t_kill, 4)
        if (t_adopt is not None and t_kill is not None) else None
    )
    mttr_s = None
    if t_kill is not None:
        with counts_lock:
            post = [
                t for slot, t in bind_times if slot == victim and t > t_kill
            ]
        if post:
            mttr_s = round(min(post) - t_kill, 4)

    out = {
        "shards": shards,
        "pods": total,
        "bound": len(fed_bound),
        "victim_slot": victim,
        "victim_gangs": per_slot.get(victim, 0),
        "adopter": adopter,
        "takeover_s": takeover_s,
        "takeover_window_s": round(takeover_window_s, 4),
        "mttr_s": mttr_s,
        "unowned_window_observed": unowned_observed,
        "double_owned": double_owned,
        "exactly_once": exactly_once,
        "double_binds": sum(1 for v in counts.values() if v > 1),
        "fsck_violations": violations,
        "union_parity": fed_bound == twin_bound,
        "lease_s": lease_s,
        "renew_s": renew_s,
        "note": note or (
            "in-process SIGKILL: dying binder raises mid-bind_many, slot "
            "manager stops renewing without release; survivor adopts on "
            "lease expiry, reconciles the journal, schedules the backlog"
        ),
    }
    out["ok"] = bool(
        all_bound
        and exactly_once
        and not violations
        and out["union_parity"]
        and out["bound"] == total
        and adopter is not None
        and double_owned == 1
        and takeover_s is not None
        and takeover_s <= takeover_window_s
        and mttr_s is not None
        and mttr_s <= takeover_window_s + 3.0
        and (unowned_observed or not strict)
    )
    return out


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="federation smoke: N schedulers over one store, "
        "optimistic conflicts, exactly-once binds; --kill-one runs the "
        "kill-and-adopt drill (leased slots, survivor adoption, MTTR)"
    )
    parser.add_argument("--shards", type=int, default=None)
    parser.add_argument("--gangs", type=int, default=None)
    parser.add_argument("--members", type=int, default=None)
    parser.add_argument("--nodes", type=int, default=None)
    parser.add_argument(
        "--wire-protocol", type=int, default=None, choices=(1, 2),
        help="pin the wire generation (1 = pre-v2 surface end to end, "
        "2 = full v2 stack) and emit the measured transport row",
    )
    parser.add_argument(
        "--codec", default=None, choices=("json", "binary"),
        help="with --wire-protocol: the client codec preference",
    )
    parser.add_argument(
        "--rtt-probes", type=int, default=0,
        help="with --wire-protocol: timed version round-trips for the "
        "backend_rtt_p50_s column",
    )
    parser.add_argument(
        "--bulk", action="store_true",
        help="schedule on the gang bulk-dispatch conf (bind_many -> "
        "gang transactions; v2 coalesces them per cycle)",
    )
    parser.add_argument(
        "--kill-one", action="store_true",
        help="kill-and-adopt drill: SIGKILL one shard owner mid-bind_many "
        "and require a survivor to adopt its slot within the lease window",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="with --kill-one: also require the transient 'unowned slot' "
        "fsck window to have been observed",
    )
    parser.add_argument(
        "--streaming", action="store_true",
        help="streaming-federation parity drill: the same federated world "
        "scheduled by event-driven micro-cycles (watch pump -> absorbed "
        "occupancy patches) and by the periodic loop must bind "
        "bind-for-bind identically",
    )
    parser.add_argument(
        "--json", action="store_true", help="print the result dict as JSON"
    )
    args = parser.parse_args(argv)
    if args.streaming:
        result = smoke_streaming(
            shards=args.shards or 2,
            gangs=args.gangs or 6,
            members=args.members or 3,
            nodes=args.nodes or 8,
        )
    elif args.kill_one:
        result = smoke_kill_one(
            shards=args.shards or 4,
            gangs=args.gangs or 16,
            members=args.members or 2,
            strict=args.strict,
        )
    else:
        result = smoke(
            shards=args.shards or 2,
            gangs=args.gangs or 6,
            members=args.members or 3,
            nodes=args.nodes or 8,
            protocol=args.wire_protocol,
            codec=args.codec,
            rtt_probes=args.rtt_probes,
            bulk=args.bulk,
        )
    if args.json:
        print(json.dumps(result, sort_keys=True))
    elif args.streaming:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"federation streaming parity: {status} "
            f"({result['bound']}/{result['pods']} pods bound across "
            f"{result['shards']} streaming shards, "
            f"micro_cycles={result['micro_cycles']}, "
            f"parity={result['parity']}, exactly_once={result['exactly_once']}, "
            f"listeners_clean={result['listeners_clean']}, "
            f"fsck={'clean' if not result['fsck_violations'] else result['fsck_violations']})"
        )
    elif args.kill_one:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"federation kill drill: {status} (victim slot "
            f"{result['victim_slot']} adopted by {result['adopter']} in "
            f"{result['takeover_s']}s <= {result['takeover_window_s']}s, "
            f"mttr={result['mttr_s']}s, {result['bound']}/{result['pods']} "
            f"pods bound, exactly_once={result['exactly_once']}, "
            f"union_parity={result['union_parity']}, "
            f"fsck={'clean' if not result['fsck_violations'] else result['fsck_violations']})"
        )
    else:
        status = "ok" if result["ok"] else "FAILED"
        print(
            f"federation smoke: {status} ({result['bound']}/{result['pods']} pods "
            f"bound across {result['shards']} schedulers, exactly_once="
            f"{result['exactly_once']}, union_parity={result['union_parity']}, "
            f"fsck={'clean' if not result['fsck_violations'] else result['fsck_violations']})"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    # re-enter through the canonical module: `python -m` executes this
    # file as __main__, whose module-level state would otherwise be
    # distinct from the one other modules import
    from kube_batch_tpu.federation import main as _canonical_main

    raise SystemExit(_canonical_main())
