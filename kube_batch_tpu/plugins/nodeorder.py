"""nodeorder plugin: weighted sum of upstream k8s priorities
(reference pkg/scheduler/plugins/nodeorder/nodeorder.go:109-222).

Implements the same four priorities with the k8s 1.13 formulas:

- LeastRequested:  ((cap - req) * 10 // cap) per cpu/mem, averaged with
  integer division (k8s least_requested.go).
- BalancedResourceAllocation: 10 - |cpuFraction - memFraction| * 10,
  floored; 0 when either fraction >= 1 (k8s balanced_resource_allocation.go).
- NodeAffinity (preferred): raw sum of matching preferred-term weights —
  the reference calls CalculateNodeAffinityPriorityMap without the
  normalizing reduce (nodeorder.go:199-205), so the raw sum is parity.
- InterPodAffinity: simplified count of resident pods matched by the
  task's required affinity terms minus anti-affinity matches (the
  reference's full symmetric-weight algorithm rebuilds an O(N^2) node map
  per scored node — a known perf sink SURVEY.md section 2.6 — and is
  deliberately not replicated; 0 when the task has no pod-affinity terms,
  which keeps the fast path identical).

All four are pure functions of (task request, node used/allocatable,
labels), so the XLA path computes the first two on-device and the label
terms as precomputed matrices (kube_batch_tpu.ops).
"""

from __future__ import annotations

import math

from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session

MAX_PRIORITY = 10  # schedulerapi.MaxPriority

NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"


def least_requested_score(requested_cpu: float, requested_mem: float,
                          cap_cpu: float, cap_mem: float) -> int:
    """k8s LeastRequestedPriorityMap: per-dimension integer score
    ((cap-req)*10)//cap, clamped at 0, averaged with integer division."""

    def dim(req: float, cap: float) -> int:
        if cap == 0:
            return 0
        if req > cap:
            return 0
        return int(((cap - req) * MAX_PRIORITY) // cap)

    return (dim(requested_cpu, cap_cpu) + dim(requested_mem, cap_mem)) // 2


def balanced_resource_score(requested_cpu: float, requested_mem: float,
                            cap_cpu: float, cap_mem: float) -> int:
    """k8s BalancedResourceAllocationMap: 10 - |cpuF - memF| * 10 floored;
    0 when either fraction >= 1."""

    def fraction(req: float, cap: float) -> float:
        return req / cap if cap != 0 else 1.0

    cpu_f = fraction(requested_cpu, cap_cpu)
    mem_f = fraction(requested_mem, cap_mem)
    if cpu_f >= 1.0 or mem_f >= 1.0:
        return 0
    return int(MAX_PRIORITY - math.fabs(cpu_f - mem_f) * MAX_PRIORITY)


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> int:
    """Sum of preferred node-affinity term weights matching node labels."""
    affinity = task.pod.affinity
    if affinity is None or not affinity.node_affinity_preferred:
        return 0
    labels = node.node.labels if node.node else {}
    return sum(w for w, term in affinity.node_affinity_preferred if term.matches(labels))


def pod_affinity_score(task: TaskInfo, node: NodeInfo) -> int:
    """Simplified inter-pod affinity: matched resident pods minus
    anti-matched (see module docstring)."""
    affinity = task.pod.affinity
    if affinity is None:
        return 0
    if not affinity.pod_affinity_required and not affinity.pod_anti_affinity_required:
        return 0
    score = 0
    for resident in node.tasks.values():
        labels = resident.pod.metadata.labels
        for term in affinity.pod_affinity_required:
            if all(labels.get(k) == v for k, v in term.label_selector.items()):
                score += 1
        for term in affinity.pod_anti_affinity_required:
            if all(labels.get(k) == v for k, v in term.label_selector.items()):
                score -= 1
    return score


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "nodeorder"

    def on_session_open(self, ssn: Session) -> None:
        # Weights default to 1 (nodeorder.go:139-153).
        least_req_w = self.arguments.get_int(LEAST_REQUESTED_WEIGHT, 1)
        balanced_w = self.arguments.get_int(BALANCED_RESOURCE_WEIGHT, 1)
        node_aff_w = self.arguments.get_int(NODE_AFFINITY_WEIGHT, 1)
        pod_aff_w = self.arguments.get_int(POD_AFFINITY_WEIGHT, 1)

        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            req_cpu = node.used.milli_cpu + task.resreq.milli_cpu
            req_mem = node.used.memory + task.resreq.memory
            cap_cpu = node.allocatable.milli_cpu
            cap_mem = node.allocatable.memory
            score = 0.0
            score += least_requested_score(req_cpu, req_mem, cap_cpu, cap_mem) * least_req_w
            score += balanced_resource_score(req_cpu, req_mem, cap_cpu, cap_mem) * balanced_w
            score += node_affinity_score(task, node) * node_aff_w
            score += pod_affinity_score(task, node) * pod_aff_w
            return score

        ssn.add_node_order_fn(self.name, node_order_fn)


def new(arguments: Arguments) -> Plugin:
    return NodeOrderPlugin(arguments)
