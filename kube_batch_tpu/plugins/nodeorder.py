"""nodeorder plugin: weighted sum of upstream k8s priorities
(reference pkg/scheduler/plugins/nodeorder/nodeorder.go:109-222).

Implements the same four priorities with the k8s 1.13 formulas:

- LeastRequested:  ((cap - req) * 10 // cap) per cpu/mem, averaged with
  integer division (k8s least_requested.go).
- BalancedResourceAllocation: 10 - |cpuFraction - memFraction| * 10,
  floored; 0 when either fraction >= 1 (k8s balanced_resource_allocation.go).
- NodeAffinity (preferred): raw sum of matching preferred-term weights —
  the reference calls CalculateNodeAffinityPriorityMap without the
  normalizing reduce (nodeorder.go:199-205), so the raw sum is parity.
- InterPodAffinity: the full k8s-1.13 symmetric-weight algorithm
  (nodeorder.go:210-216 -> CalculateInterPodAffinityPriority): incoming
  pod's preferred terms, existing pods' preferred terms toward the
  incoming pod, and existing pods' *required* terms at the hard symmetric
  weight, summed over topology domains and normalized to 0..10. The
  reference rebuilds its node map per scored node (a known perf sink,
  SURVEY.md section 2.6); here the all-nodes score map is computed once
  per (task, session-state) and memoized via ssn.state_seq.

All four are pure functions of (task request, node used/allocatable,
labels), so the XLA path computes the first two on-device and the label
terms as precomputed matrices (kube_batch_tpu.ops).
"""

from __future__ import annotations


from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session

MAX_PRIORITY = 10  # schedulerapi.MaxPriority

NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"


def least_requested_score(requested_cpu: float, requested_mem: float,
                          cap_cpu: float, cap_mem: float) -> int:
    """k8s LeastRequestedPriorityMap: per-dimension integer score
    ((cap-req)*10)//cap, clamped at 0, averaged with integer division.

    Computed in the comparison dtype (api/numerics.py) like
    balanced_resource_score: byte-denominated memory caps exceed the f32
    integer range, so the floor boundary must land where the f32 device
    kernels put it, not where exact float64 would."""
    from kube_batch_tpu.api.numerics import comparison_dtype

    dt = comparison_dtype()

    def dim(req: float, cap: float) -> int:
        if cap == 0:
            return 0
        if req > cap:
            return 0
        return int(((dt(cap) - dt(req)) * dt(MAX_PRIORITY)) // dt(cap))

    return (dim(requested_cpu, cap_cpu) + dim(requested_mem, cap_mem)) // 2


def balanced_resource_score(requested_cpu: float, requested_mem: float,
                            cap_cpu: float, cap_mem: float) -> int:
    """k8s BalancedResourceAllocationMap: 10 - |cpuF - memF| * 10 floored;
    0 when either fraction >= 1.

    Fractions are off the integer grid, so every operation runs in the
    comparison dtype (api/numerics.py): in f32 mode the truncation
    boundary lands exactly where the device kernels put it, keeping node
    choice bit-identical to the solve."""
    from kube_batch_tpu.api.numerics import comparison_dtype

    dt = comparison_dtype()

    def fraction(req: float, cap: float):
        return dt(req) / dt(cap) if cap != 0 else dt(1.0)

    cpu_f = fraction(requested_cpu, cap_cpu)
    mem_f = fraction(requested_mem, cap_mem)
    if cpu_f >= 1.0 or mem_f >= 1.0:
        return 0
    return int(dt(MAX_PRIORITY) - abs(cpu_f - mem_f) * dt(MAX_PRIORITY))


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> int:
    """Sum of preferred node-affinity term weights matching node labels."""
    affinity = task.pod.affinity
    if affinity is None or not affinity.node_affinity_preferred:
        return 0
    labels = node.node.labels if node.node else {}
    return sum(w for w, term in affinity.node_affinity_preferred if term.matches(labels))


# v1.DefaultHardPodAffinitySymmetricWeight (k8s 1.13): each *required*
# affinity term an existing pod holds toward the incoming pod scores this
# much over the existing pod's topology domain.
HARD_POD_AFFINITY_SYMMETRIC_WEIGHT = 1


def _sel_matches(selector: dict[str, str], labels: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def vectorized_least_balanced(req_cpu, req_mem, cap_cpu, cap_mem):
    """Whole-node-axis twins of least_requested_score /
    balanced_resource_score (identical floor/trunc semantics to the
    scalar formulas above) — shared by every vectorized scorer
    (actions/scan.py, plugins/tensorscore.py) so the numerically
    sensitive parity lives in exactly one place. Computed in the
    comparison dtype (api/numerics.py) so truncation boundaries match
    the device kernels' f32 in production."""
    import numpy as np

    from kube_batch_tpu.api.numerics import comparison_dtype

    dt = comparison_dtype()
    req_cpu = np.asarray(req_cpu, dt)
    req_mem = np.asarray(req_mem, dt)
    cap_cpu = np.asarray(cap_cpu, dt)
    cap_mem = np.asarray(cap_mem, dt)

    def least_dim(rq, cp):
        safe = np.where(cp == 0.0, 1.0, cp)
        sc = np.floor_divide((cp - rq) * MAX_PRIORITY, safe)
        return np.where((cp == 0.0) | (rq > cp), 0.0, sc)

    least = np.floor_divide(
        least_dim(req_cpu, cap_cpu) + least_dim(req_mem, cap_mem), 2.0
    )
    cpu_f = np.where(
        cap_cpu != 0.0, req_cpu / np.where(cap_cpu == 0.0, 1.0, cap_cpu), 1.0
    )
    mem_f = np.where(
        cap_mem != 0.0, req_mem / np.where(cap_mem == 0.0, 1.0, cap_mem), 1.0
    )
    balanced = np.where(
        (cpu_f >= 1.0) | (mem_f >= 1.0),
        0.0,
        np.trunc(MAX_PRIORITY - np.abs(cpu_f - mem_f) * MAX_PRIORITY),
    )
    return least, balanced


def any_pod_affinity_terms(nodes: dict[str, NodeInfo], tasks) -> bool:
    """True when any resident or given pod carries pod-affinity terms."""
    for t in tasks:
        aff = t.pod.affinity
        if aff is not None and aff.has_pod_affinity_terms():
            return True
    for node in nodes.values():
        for resident in node.tasks.values():
            aff = resident.pod.affinity
            if aff is not None and aff.has_pod_affinity_terms():
                return True
    return False


def interpod_affinity_scores(task: TaskInfo, nodes: dict[str, NodeInfo]) -> dict[str, int]:
    """k8s 1.13 CalculateInterPodAffinityPriority over every node (the
    algorithm behind the reference's NewInterPodAffinityPriority map fn,
    nodeorder.go:210-216):

    for each existing pod E on each node (anchored at E's node's topology
    domain):
    - incoming pod's *preferred* (anti-)affinity terms matching E:
      +/- term weight;
    - E's *preferred* (anti-)affinity terms matching the incoming pod:
      +/- term weight (the symmetric half);
    - E's *required* affinity terms matching the incoming pod:
      + hardPodAffinitySymmetricWeight each;
    then normalize to 0..10 ints: 10 * (count - min) / (max - min).

    Model notes (same deviations as predicates.check_pod_affinity): the
    ``kubernetes.io/hostname`` topology domain is the anchor node itself
    (nodes carry no implicit hostname label here), and terms match
    cluster-wide (PodAffinityTerm has no namespaces field).
    """
    counts: dict[str, float] = {name: 0.0 for name in nodes}
    p_aff = task.pod.affinity
    p_labels = task.pod.metadata.labels

    def add_domain(anchor: NodeInfo, topology_key: str, weight: float) -> None:
        if topology_key == "kubernetes.io/hostname":
            counts[anchor.name] += weight
            return
        labels = anchor.node.labels if anchor.node else {}
        value = labels.get(topology_key)
        if value is None:
            return
        for other in nodes.values():
            other_labels = other.node.labels if other.node else {}
            if other_labels.get(topology_key) == value:
                counts[other.name] += weight

    for node in nodes.values():
        for resident in node.tasks.values():
            epod = resident.pod
            if epod is task.pod:
                continue
            e_labels = epod.metadata.labels
            if p_aff is not None:
                for w, term in p_aff.pod_affinity_preferred:
                    if _sel_matches(term.label_selector, e_labels):
                        add_domain(node, term.topology_key, w)
                for w, term in p_aff.pod_anti_affinity_preferred:
                    if _sel_matches(term.label_selector, e_labels):
                        add_domain(node, term.topology_key, -w)
            e_aff = epod.affinity
            if e_aff is not None:
                for w, term in e_aff.pod_affinity_preferred:
                    if _sel_matches(term.label_selector, p_labels):
                        add_domain(node, term.topology_key, w)
                for w, term in e_aff.pod_anti_affinity_preferred:
                    if _sel_matches(term.label_selector, p_labels):
                        add_domain(node, term.topology_key, -w)
                if HARD_POD_AFFINITY_SYMMETRIC_WEIGHT > 0:
                    for term in e_aff.pod_affinity_required:
                        if _sel_matches(term.label_selector, p_labels):
                            add_domain(
                                node,
                                term.topology_key,
                                HARD_POD_AFFINITY_SYMMETRIC_WEIGHT,
                            )

    mx = max(counts.values(), default=0.0)
    mn = min(counts.values(), default=0.0)
    diff = mx - mn
    if diff <= 0:
        return {name: 0 for name in counts}
    return {name: int(MAX_PRIORITY * ((c - mn) / diff)) for name, c in counts.items()}


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "nodeorder"

    def on_session_open(self, ssn: Session) -> None:
        import numpy as np

        # Weights default to 1 (nodeorder.go:139-153).
        least_req_w = self.arguments.get_int(LEAST_REQUESTED_WEIGHT, 1)
        balanced_w = self.arguments.get_int(BALANCED_RESOURCE_WEIGHT, 1)
        node_aff_w = self.arguments.get_int(NODE_AFFINITY_WEIGHT, 1)
        pod_aff_w = self.arguments.get_int(POD_AFFINITY_WEIGHT, 1)

        # least/balanced memo: one comparison-dtype vectorized pass over
        # the whole node axis per (task, session-state) — the serial
        # scan then pays a dict lookup per node instead of 5+ boxed f32
        # scalar ops per (task, node) pair (the per-pair scalar path
        # made the serial oracle 2.4x slower). Values are identical:
        # vectorized_least_balanced is the property-tested twin of the
        # scalar formulas, in the same dtype (the FORMULAS live in one
        # place; tensorscore keeps its own memo scaffolding for its
        # batch-task API). Session node membership is immutable, so
        # caps/index build once and the used sweep keys on state_seq
        # alone.
        n_nodes = len(ssn.nodes)
        lb_idx = {name: i for i, name in enumerate(ssn.nodes)}
        cap_c = np.fromiter(
            (n.allocatable.milli_cpu for n in ssn.nodes.values()), np.float64,
            count=n_nodes,
        )
        cap_m = np.fromiter(
            (n.allocatable.memory for n in ssn.nodes.values()), np.float64,
            count=n_nodes,
        )
        used_memo: dict = {"seq": -1, "c": None, "m": None}
        lb_memo: dict = {"uid": None, "seq": -1, "least": None, "balanced": None}

        def lb_scores(task: TaskInfo):
            if lb_memo["uid"] != task.uid or lb_memo["seq"] != ssn.state_seq:
                if used_memo["seq"] != ssn.state_seq:
                    used_memo["c"] = np.fromiter(
                        (n.used.milli_cpu for n in ssn.nodes.values()),
                        np.float64, count=n_nodes,
                    )
                    used_memo["m"] = np.fromiter(
                        (n.used.memory for n in ssn.nodes.values()),
                        np.float64, count=n_nodes,
                    )
                    used_memo["seq"] = ssn.state_seq
                least, balanced = vectorized_least_balanced(
                    used_memo["c"] + task.resreq.milli_cpu,
                    used_memo["m"] + task.resreq.memory,
                    cap_c,
                    cap_m,
                )
                lb_memo["uid"] = task.uid
                lb_memo["seq"] = ssn.state_seq
                lb_memo["least"] = least
                lb_memo["balanced"] = balanced
            return lb_memo
        # InterPodAffinity memo: the all-nodes score map for one task,
        # invalidated by any session mutation (ssn.state_seq); the serial
        # node scan calls node_order_fn once per node for the same task.
        # Fast path: if no pod anywhere in the snapshot carries terms,
        # every score is 0 forever — the common cluster pays O(1), not a
        # per-task O(nodes x residents) walk. (Pods cannot be *added*
        # mid-session, so a False verdict holds for the whole session.)
        memo: dict = {"uid": None, "seq": -1, "scores": {}, "active": None}

        def interpod_score(task: TaskInfo, node: NodeInfo) -> int:
            if memo["active"] is None:
                all_tasks = (t for j in ssn.jobs.values() for t in j.tasks.values())
                memo["active"] = any_pod_affinity_terms(ssn.nodes, all_tasks)
            if not memo["active"]:
                return 0
            if memo["uid"] != task.uid or memo["seq"] != ssn.state_seq:
                memo["uid"] = task.uid
                memo["seq"] = ssn.state_seq
                memo["scores"] = interpod_affinity_scores(task, ssn.nodes)
            return memo["scores"].get(node.name, 0)

        def node_order_fn(task: TaskInfo, node: NodeInfo) -> float:
            lb = lb_scores(task)
            i = lb_idx[node.name]
            score = float(lb["least"][i]) * least_req_w
            score += float(lb["balanced"][i]) * balanced_w
            score += node_affinity_score(task, node) * node_aff_w
            score += interpod_score(task, node) * pod_aff_w
            return score

        ssn.add_node_order_fn(self.name, node_order_fn)


def new(arguments: Arguments) -> Plugin:
    return NodeOrderPlugin(arguments)
