"""gang plugin: minMember semantics end-to-end
(reference pkg/scheduler/plugins/gang/gang.go:48-162)."""

from __future__ import annotations

import time

from kube_batch_tpu import metrics
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo
from kube_batch_tpu.api.types import ValidateResult
from kube_batch_tpu.apis.types import (
    NOT_ENOUGH_PODS_REASON,
    NOT_ENOUGH_RESOURCES_REASON,
    POD_GROUP_UNSCHEDULABLE_TYPE,
    PodGroupCondition,
)
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session


class GangPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments

    @property
    def name(self) -> str:
        return "gang"

    def on_session_open(self, ssn: Session) -> None:
        def valid_job_fn(job: JobInfo) -> ValidateResult:
            """Enough potentially-schedulable tasks? (gang.go:48-69)."""
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    passed=False,
                    reason=NOT_ENOUGH_PODS_REASON,
                    message=(
                        f"Not enough valid tasks for gang-scheduling, "
                        f"valid: {vtn}, min: {job.min_available}"
                    ),
                )
            return None

        ssn.add_job_valid_fn(self.name, valid_job_fn)

        def preemptable_fn(
            preemptor: TaskInfo, preemptees: list[TaskInfo]
        ) -> list[TaskInfo]:
            """Protect victims whose job would drop below minAvailable
            (gang.go:71-93)."""
            victims: list[TaskInfo] = []
            for preemptee in preemptees:
                job = ssn.jobs[preemptee.job]
                occupied = job.ready_task_num()
                preemptable = job.min_available <= occupied - 1 or job.min_available == 1
                if preemptable:
                    victims.append(preemptee)
            return victims

        ssn.add_reclaimable_fn(self.name, preemptable_fn)
        ssn.add_preemptable_fn(self.name, preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            """Non-ready jobs first (gang.go:96-118)."""
            l_ready = l.ready()
            r_ready = r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name, job_order_fn)
        ssn.add_job_ready_fn(self.name, lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name, lambda job: job.pipelined())

    def on_session_close(self, ssn: Session) -> None:
        """Emit Unschedulable conditions + metrics for non-ready jobs
        (gang.go:132-162)."""
        explain_records = getattr(ssn, "explain_records", {}) or {}
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if not job.ready():
                unready = job.min_available - job.ready_task_num()
                msg = (
                    f"{unready}/{len(job.tasks)} tasks in gang unschedulable: "
                    f"{job.fit_error()}"
                )
                reason = NOT_ENOUGH_RESOURCES_REASON
                # Forensics enrichment (obs/explain): when the allocate
                # action published a record for this gang, the condition
                # carries the dominant plane as its reason and the
                # elimination/would-fit-if breakdown as its message —
                # this is also the cross-shard channel, since conditions
                # ride /backend/v1/ commits into the arbiter store.
                rec = explain_records.get(job.uid)
                if rec is not None and rec.get("verdict") != "bound":
                    from kube_batch_tpu.obs import explain as _explain

                    reason = rec["reason"]
                    msg = _explain.condition_message(rec)
                unschedulable_jobs += 1
                metrics.update_unschedule_task_count(job.name, unready)
                metrics.register_job_retries(job.name)
                if job.pod_group is not None:
                    ssn.update_job_condition(
                        job,
                        PodGroupCondition(
                            type=POD_GROUP_UNSCHEDULABLE_TYPE,
                            status="True",
                            transition_id=ssn.uid,
                            last_transition_time=time.time(),
                            reason=reason,
                            message=msg,
                        ),
                    )
        metrics.update_unschedule_job_count(unschedulable_jobs)


def new(arguments: Arguments) -> Plugin:
    return GangPlugin(arguments)
