"""proportion plugin: queue-level weighted fair share via iterative
water-filling (reference pkg/scheduler/plugins/proportion/proportion.go:101-223)."""

from __future__ import annotations

from kube_batch_tpu.api.helpers import min_resource, share
from kube_batch_tpu.api.job_info import TaskInfo
from kube_batch_tpu.api.numerics import comparison_dtype, quantize
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus, allocated_status
from kube_batch_tpu.framework.arguments import Arguments
from kube_batch_tpu.framework.event import Event, EventHandler
from kube_batch_tpu.framework.interface import Plugin
from kube_batch_tpu.framework.session import Session


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "share", "deserved", "allocated", "request")

    def __init__(self, queue_id: str, name: str, weight: int) -> None:
        self.queue_id = queue_id
        self.name = name
        self.weight = weight
        self.share = 0.0
        self.deserved = Resource.empty()
        self.allocated = Resource.empty()
        self.request = Resource.empty()


class ProportionPlugin(Plugin):
    def __init__(self, arguments: Arguments) -> None:
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.queue_attrs: dict[str, _QueueAttr] = {}

    @property
    def name(self) -> str:
        return "proportion"

    def _update_share(self, attr: _QueueAttr) -> None:
        """share = max over deserved dimensions of allocated/deserved
        (proportion.go:211-223)."""
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share(attr.allocated.get(rn), attr.deserved.get(rn))
            if s > res:
                res = s
        attr.share = res

    def on_session_open(self, ssn: Session) -> None:
        for node in ssn.nodes.values():
            self.total_resource.add(node.allocatable)

        # Build queue attributes from jobs (proportion.go:66-99).
        for job in ssn.jobs.values():
            if job.queue not in self.queue_attrs:
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                self.queue_attrs[job.queue] = _QueueAttr(
                    queue_id=queue.name, name=queue.name, weight=queue.weight
                )
            attr = self.queue_attrs[job.queue]
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
                        attr.request.add(t.resreq)
                elif status == TaskStatus.PENDING:
                    for t in tasks.values():
                        attr.request.add(t.resreq)

        # Iterative water-filling of deserved by weight until remaining
        # is exhausted or every queue's request is met (proportion.go:101-144).
        remaining = self.total_resource.clone()
        met: set[str] = set()
        while True:
            total_weight = sum(
                attr.weight
                for attr in self.queue_attrs.values()
                if attr.queue_id not in met
            )
            if total_weight == 0:
                break
            deserved_this_round = Resource.empty()
            for attr in self.queue_attrs.values():
                if attr.queue_id in met:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(remaining.clone().multi(attr.weight / total_weight))
                if not attr.deserved.less_equal(attr.request):
                    attr.deserved = min_resource(attr.deserved, attr.request)
                    met.add(attr.queue_id)
                self._update_share(attr)
                deserved_this_round.add(attr.deserved.clone().sub(old_deserved))
            remaining.sub(deserved_this_round)
            if remaining.is_empty():
                break

        # Water-filled deserved is off the integer grid (weight-fraction
        # products). Land it on the comparison dtype (api/numerics.py)
        # so the overused gate and share denominators see EXACTLY the
        # values the f32 device kernels see — sub-f32-ulp boundary flips
        # between the serial oracle and the solve cannot happen (r4
        # verdict, weak #3). A float64 run quantizes to itself. The
        # gates quantize their *allocated* side too
        # (Resource.less_equal(dtype=comparison_dtype())).
        dt = comparison_dtype()
        for attr in self.queue_attrs.values():
            d = attr.deserved
            d.milli_cpu = quantize(d.milli_cpu, dt)
            d.memory = quantize(d.memory, dt)
            for rn in d.scalars:
                d.scalars[rn] = quantize(d.scalars[rn], dt)
            self._update_share(attr)

        def queue_order_fn(l: QueueInfo, r: QueueInfo) -> int:
            """Lower share first (proportion.go:146-159)."""
            la = self.queue_attrs.get(l.name)
            ra = self.queue_attrs.get(r.name)
            ls = la.share if la else 0.0
            rs = ra.share if ra else 0.0
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.name, queue_order_fn)

        def reclaimable_fn(reclaimer: TaskInfo, reclaimees: list[TaskInfo]) -> list[TaskInfo]:
            """Victim OK while its queue stays at or above deserved
            (proportion.go:161-186)."""
            victims: list[TaskInfo] = []
            allocations: dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs[reclaimee.job]
                attr = self.queue_attrs[job.queue]
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                # both sides in the comparison dtype: the serial gate
                # must round exactly as the f32 device gate does
                if attr.deserved.less_equal(allocated, dtype=comparison_dtype()):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name, reclaimable_fn)

        def overused_fn(queue: QueueInfo) -> bool:
            """deserved <= allocated (proportion.go:188-199)."""
            attr = self.queue_attrs.get(queue.name)
            if attr is None:
                return False
            return attr.deserved.less_equal(
                attr.allocated, dtype=comparison_dtype()
            )

        ssn.add_overused_fn(self.name, overused_fn)

        def on_allocate(event: Event) -> None:
            job = ssn.jobs[event.task.job]
            attr = self.queue_attrs[job.queue]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event: Event) -> None:
            job = ssn.jobs[event.task.job]
            attr = self.queue_attrs[job.queue]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        ssn.add_event_handler(
            EventHandler(allocate_func=on_allocate, deallocate_func=on_deallocate)
        )

    def on_session_close(self, ssn: Session) -> None:
        self.total_resource = Resource.empty()
        self.queue_attrs = {}


def new(arguments: Arguments) -> Plugin:
    return ProportionPlugin(arguments)
