"""Full-fidelity wire codec for the store-backend protocol.

The scheduler HTTP endpoints in ``server.py`` serialize objects for
*observability* — a pod on ``/apis/v1alpha1/pods`` carries only
namespace/name/phase/node. A networked store backend
(``cache/backend.py``) needs the whole object back: requests, gang
annotations, affinity, tolerations — everything the solve reads. This
module is that codec: a generic recursive encoder/decoder over the
``apis/types.py`` dataclasses, driven by field type hints, so a new
field on any API type rides the wire without touching this file.

Encoding rules: dataclass -> dict of encoded fields, str-Enum -> its
value, dict -> encoded values (keys stay strings), list/tuple -> JSON
array, scalars/None pass through. Decoding inverts field-by-field from
the declared type; unknown wire fields are ignored (forward
compatibility) and missing ones fall back to the dataclass default.

Wire protocol v2 (negotiated, see deployment/README.md) adds two layers
on top of the same wire-dict data model:

- **binary framing** (``dumps_binary`` / ``loads_binary``): a
  length-prefixed msgpack-style tagged encoding of the wire dicts —
  stdlib-only, big-endian, every string/container length-prefixed, the
  whole message behind a 4-byte magic + payload length header so a
  codec mismatch fails loudly instead of half-parsing. Round-trip
  equality against the JSON codec is pinned by the ``--json``
  self-check CLI (``python -m kube_batch_tpu.apis.wire --json``) and
  tests/test_wire_v2.py.
- **field-level deltas** (``delta_of`` / ``apply_delta``): a MODIFIED
  watch event under v2 carries only the changed top-level fields (plus
  tombstones for fields the encoding dropped) instead of the full
  object; the client mirror applies the patch in place via
  ``dataclasses.replace``.
"""

from __future__ import annotations

import dataclasses
import struct
import typing
from enum import Enum
from typing import Any, Optional, Union

from kube_batch_tpu.apis import types as api_types

__all__ = [
    "KIND_TYPES",
    "CODECS",
    "BINARY_CONTENT_TYPE",
    "JSON_CONTENT_TYPE",
    "to_wire",
    "from_wire",
    "decode_kind",
    "encode_kind",
    "dumps_binary",
    "loads_binary",
    "delta_of",
    "apply_delta",
]

# Negotiable codecs for the /backend/v1/ surface. "json" is the v1
# baseline every server speaks; "binary" is offered by v2 servers in
# their /backend/v1/version capability advertisement.
CODECS = ("json", "binary")
JSON_CONTENT_TYPE = "application/json"
BINARY_CONTENT_TYPE = "application/x-kbt-binary"

# kind name (cache/store.py KINDS) -> dataclass; string keys on purpose:
# apis/ sits below cache/ in the layering and must not import it.
KIND_TYPES: dict[str, type] = {
    "pods": api_types.Pod,
    "nodes": api_types.Node,
    "podgroups": api_types.PodGroup,
    "queues": api_types.Queue,
    "poddisruptionbudgets": api_types.PodDisruptionBudget,
    "priorityclasses": api_types.PriorityClass,
    "persistentvolumes": api_types.PersistentVolume,
    "persistentvolumeclaims": api_types.PersistentVolumeClaim,
    "storageclasses": api_types.StorageClass,
    "leases": api_types.Lease,
}

_hints_cache: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    hints = _hints_cache.get(cls)
    if hints is None:
        # types.py uses `from __future__ import annotations`: field types
        # are strings until resolved against the defining module
        hints = typing.get_type_hints(cls, vars(api_types))
        _hints_cache[cls] = hints
    return hints


def to_wire(obj: Any) -> Any:
    """Encode any API object (or nested fragment) to JSON-able data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_wire(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(hint: Any, data: Any) -> Any:
    """Decode wire data back into the shape ``hint`` declares."""
    origin = typing.get_origin(hint)
    if origin is Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if data is None:
            return None
        return from_wire(args[0], data) if args else data
    if origin in (list, tuple):
        args = typing.get_args(hint)
        if data is None:
            return [] if origin is list else ()
        if origin is list:
            inner = args[0] if args else Any
            return [from_wire(inner, v) for v in data]
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(from_wire(args[0], v) for v in data)
        return tuple(
            from_wire(args[i] if i < len(args) else Any, v)
            for i, v in enumerate(data)
        )
    if origin is dict:
        args = typing.get_args(hint)
        inner = args[1] if len(args) == 2 else Any
        return {k: from_wire(inner, v) for k, v in (data or {}).items()}
    if isinstance(hint, type) and issubclass(hint, Enum):
        return hint(data)
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        if data is None:
            return None
        hints = _hints(hint)
        names = {f.name for f in dataclasses.fields(hint)}
        kwargs = {
            k: from_wire(hints.get(k, Any), v)
            for k, v in data.items()
            if k in names
        }
        return hint(**kwargs)
    return data


def decode_kind(kind: str, data: dict) -> Any:
    """Decode one wire object of the named store kind."""
    cls = KIND_TYPES.get(kind)
    if cls is None:
        raise KeyError(f"unknown wire kind {kind!r}")
    return from_wire(cls, data)


def encode_kind(kind: str, obj: Any) -> Optional[dict]:
    """Encode one store object of the named kind (None passes through —
    watch deletes carry no new object)."""
    if obj is None:
        return None
    if kind not in KIND_TYPES:
        raise KeyError(f"unknown wire kind {kind!r}")
    return to_wire(obj)


# -- binary framing (wire protocol v2) ---------------------------------------
#
# Tagged msgpack-style encoding of the SAME wire-dict data model the
# JSON codec carries (None/bool/int/float/str/list/dict). Big-endian
# throughout; every string and container is length-prefixed; the whole
# message rides behind a magic + payload-length header. Hand-rolled on
# struct only — the container bakes no msgpack dependency, and the
# subset here is exactly what to_wire can produce.

_MAGIC = b"KBW2"  # 4-byte frame magic: "kbt binary wire, protocol 2"

_T_NONE = 0xC0
_T_FALSE = 0xC2
_T_TRUE = 0xC3
_T_F64 = 0xCB
_T_U8, _T_U16, _T_U32, _T_U64 = 0xCC, 0xCD, 0xCE, 0xCF
_T_I8, _T_I16, _T_I32, _T_I64 = 0xD0, 0xD1, 0xD2, 0xD3
_T_S8, _T_S16, _T_S32 = 0xD9, 0xDA, 0xDB
_T_A16, _T_A32 = 0xDC, 0xDD
_T_M16, _T_M32 = 0xDE, 0xDF


def _pack_value(obj: Any, out: list) -> None:
    if obj is None:
        out.append(b"\xc0")
    elif obj is True:
        out.append(b"\xc3")
    elif obj is False:
        out.append(b"\xc2")
    elif isinstance(obj, int) and not isinstance(obj, bool):
        if 0 <= obj < 0x80:
            out.append(struct.pack(">B", obj))
        elif -32 <= obj < 0:
            out.append(struct.pack(">B", 0x100 + obj))
        elif obj >= 0:
            for tag, fmt, hi in (
                (_T_U8, ">B", 1 << 8), (_T_U16, ">H", 1 << 16),
                (_T_U32, ">I", 1 << 32), (_T_U64, ">Q", 1 << 64),
            ):
                if obj < hi:
                    out.append(struct.pack(">B", tag) + struct.pack(fmt, obj))
                    return
            raise ValueError(f"int too large for binary wire codec: {obj}")
        else:
            for tag, fmt, lo in (
                (_T_I8, ">b", -(1 << 7)), (_T_I16, ">h", -(1 << 15)),
                (_T_I32, ">i", -(1 << 31)), (_T_I64, ">q", -(1 << 63)),
            ):
                if obj >= lo:
                    out.append(struct.pack(">B", tag) + struct.pack(fmt, obj))
                    return
            raise ValueError(f"int too small for binary wire codec: {obj}")
    elif isinstance(obj, float):
        out.append(struct.pack(">Bd", _T_F64, obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        n = len(raw)
        if n < 32:
            out.append(struct.pack(">B", 0xA0 | n))
        elif n < 0x100:
            out.append(struct.pack(">BB", _T_S8, n))
        elif n < 0x10000:
            out.append(struct.pack(">BH", _T_S16, n))
        else:
            out.append(struct.pack(">BI", _T_S32, n))
        out.append(raw)
    elif isinstance(obj, (list, tuple)):
        n = len(obj)
        if n < 16:
            out.append(struct.pack(">B", 0x90 | n))
        elif n < 0x10000:
            out.append(struct.pack(">BH", _T_A16, n))
        else:
            out.append(struct.pack(">BI", _T_A32, n))
        for v in obj:
            _pack_value(v, out)
    elif isinstance(obj, dict):
        n = len(obj)
        if n < 16:
            out.append(struct.pack(">B", 0x80 | n))
        elif n < 0x10000:
            out.append(struct.pack(">BH", _T_M16, n))
        else:
            out.append(struct.pack(">BI", _T_M32, n))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError(f"binary wire map keys must be str, got {type(k).__name__}")
            _pack_value(k, out)
            _pack_value(v, out)
    else:
        raise TypeError(f"type not encodable on the binary wire: {type(obj).__name__}")


def _unpack_value(data: bytes, pos: int) -> tuple[Any, int]:
    if pos >= len(data):
        raise ValueError("binary wire payload truncated")
    tag = data[pos]
    pos += 1
    if tag < 0x80:
        return tag, pos
    if tag >= 0xE0:
        return tag - 0x100, pos
    if 0xA0 <= tag < 0xC0:
        n = tag & 0x1F
        return data[pos:pos + n].decode("utf-8"), pos + n
    if 0x90 <= tag < 0xA0:
        return _unpack_seq(data, pos, tag & 0x0F)
    if 0x80 <= tag < 0x90:
        return _unpack_map(data, pos, tag & 0x0F)
    if tag == _T_NONE:
        return None, pos
    if tag == _T_TRUE:
        return True, pos
    if tag == _T_FALSE:
        return False, pos
    if tag == _T_F64:
        return struct.unpack_from(">d", data, pos)[0], pos + 8
    for t, fmt, size in (
        (_T_U8, ">B", 1), (_T_U16, ">H", 2), (_T_U32, ">I", 4), (_T_U64, ">Q", 8),
        (_T_I8, ">b", 1), (_T_I16, ">h", 2), (_T_I32, ">i", 4), (_T_I64, ">q", 8),
    ):
        if tag == t:
            return struct.unpack_from(fmt, data, pos)[0], pos + size
    for t, fmt, size in ((_T_S8, ">B", 1), (_T_S16, ">H", 2), (_T_S32, ">I", 4)):
        if tag == t:
            n = struct.unpack_from(fmt, data, pos)[0]
            pos += size
            if pos + n > len(data):
                raise ValueError("binary wire payload truncated")
            return data[pos:pos + n].decode("utf-8"), pos + n
    if tag in (_T_A16, _T_A32):
        fmt, size = (">H", 2) if tag == _T_A16 else (">I", 4)
        n = struct.unpack_from(fmt, data, pos)[0]
        return _unpack_seq(data, pos + size, n)
    if tag in (_T_M16, _T_M32):
        fmt, size = (">H", 2) if tag == _T_M16 else (">I", 4)
        n = struct.unpack_from(fmt, data, pos)[0]
        return _unpack_map(data, pos + size, n)
    raise ValueError(f"unknown binary wire tag 0x{tag:02x}")


def _unpack_seq(data: bytes, pos: int, n: int) -> tuple[list, int]:
    items = []
    for _ in range(n):
        v, pos = _unpack_value(data, pos)
        items.append(v)
    return items, pos


def _unpack_map(data: bytes, pos: int, n: int) -> tuple[dict, int]:
    items = {}
    for _ in range(n):
        k, pos = _unpack_value(data, pos)
        if not isinstance(k, str):
            raise ValueError("binary wire map key is not a string")
        v, pos = _unpack_value(data, pos)
        items[k] = v
    return items, pos


def dumps_binary(obj: Any) -> bytes:
    """Encode wire-dict data (the to_wire data model) to a framed
    binary message: ``KBW2`` magic + u32 payload length + payload."""
    out: list = []
    _pack_value(obj, out)
    payload = b"".join(out)
    return _MAGIC + struct.pack(">I", len(payload)) + payload


def loads_binary(data: bytes) -> Any:
    """Inverse of :func:`dumps_binary`. A wrong-codec body (JSON bytes
    handed to the binary decoder, or vice versa) fails on the frame
    magic — the loud half of the codec-mismatch triage ladder."""
    if len(data) < 8 or data[:4] != _MAGIC:
        raise ValueError(
            "not a KBW2 binary wire frame (codec mismatch? the peer may "
            "be speaking JSON — check KBT_WIRE_CODEC and the negotiated "
            "protocol on /backend/v1/version)"
        )
    (n,) = struct.unpack_from(">I", data, 4)
    if len(data) != 8 + n:
        raise ValueError(
            f"binary wire frame length mismatch (header says {n}, "
            f"got {len(data) - 8} payload bytes)"
        )
    value, pos = _unpack_value(data, 8)
    if pos != len(data):
        raise ValueError("binary wire frame has trailing bytes")
    return value


# -- field-level deltas (wire protocol v2 watch) -----------------------------

_MISSING = object()


def delta_of(kind: str, old_obj: Any, new_obj: Any) -> dict:
    """Field-level patch turning ``old_obj`` into ``new_obj``:
    ``{"changed": {field: wire value}, "removed": [field, ...]}``.
    Top-level dataclass fields only — nested changes ride as the whole
    changed field, which for the hot MODIFIED event (a pod bind:
    node_name + phase) is a fraction of the full object."""
    if kind not in KIND_TYPES:
        raise KeyError(f"unknown wire kind {kind!r}")
    old_w = to_wire(old_obj) or {}
    new_w = to_wire(new_obj) or {}
    changed = {k: v for k, v in new_w.items() if old_w.get(k, _MISSING) != v}
    removed = [k for k in old_w if k not in new_w]
    return {"changed": changed, "removed": removed}


def apply_delta(kind: str, obj: Any, delta: dict) -> Any:
    """Apply a :func:`delta_of` patch to a decoded object, returning the
    patched object (``dataclasses.replace`` — the input is not mutated,
    preserving the mirror's replace-don't-mutate contract). Removed
    fields reset to their dataclass default."""
    cls = KIND_TYPES.get(kind)
    if cls is None:
        raise KeyError(f"unknown wire kind {kind!r}")
    hints = _hints(cls)
    fields = {f.name: f for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for name, value in (delta.get("changed") or {}).items():
        if name in fields:  # unknown fields: same forward-compat rule as from_wire
            kwargs[name] = from_wire(hints.get(name, Any), value)
    for name in delta.get("removed") or ():
        f = fields.get(name)
        if f is None:
            continue
        if f.default is not dataclasses.MISSING:
            kwargs[name] = f.default
        elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
            kwargs[name] = f.default_factory()  # type: ignore[misc]
    return dataclasses.replace(obj, **kwargs)


# -- seeded self-check CLI (hack/verify.py gate + Dockerfile build) ----------


def _gen_value(hint: Any, rng, depth: int = 0) -> Any:
    """Generate a seeded value of the hinted type (the property-test
    input source: every API dataclass, every field, no fixtures)."""
    origin = typing.get_origin(hint)
    if origin is Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if not args or rng.random() < 0.25:
            return None
        return _gen_value(args[0], rng, depth)
    if origin is list:
        args = typing.get_args(hint)
        inner = args[0] if args else str
        return [_gen_value(inner, rng, depth + 1) for _ in range(rng.randrange(3))]
    if origin is tuple:
        args = typing.get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(
                _gen_value(args[0], rng, depth + 1) for _ in range(rng.randrange(3))
            )
        return tuple(_gen_value(a, rng, depth + 1) for a in args)
    if origin is dict:
        args = typing.get_args(hint)
        inner = args[1] if len(args) == 2 else str
        return {
            f"k{rng.randrange(1000)}": _gen_value(inner, rng, depth + 1)
            for _ in range(rng.randrange(3))
        }
    if isinstance(hint, type) and issubclass(hint, Enum):
        return rng.choice(list(hint))
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        hints = _hints(hint)
        return hint(**{
            f.name: _gen_value(hints.get(f.name, str), rng, depth + 1)
            for f in dataclasses.fields(hint)
        })
    if hint is bool:
        return rng.random() < 0.5
    if hint is int:
        return rng.randrange(-(1 << 40), 1 << 40)
    if hint is float:
        return rng.choice([0.0, 1.5, -2.25, float(rng.randrange(1 << 30))])
    if hint is str:
        return "".join(rng.choice("abcdefghij-/ü") for _ in range(rng.randrange(12)))
    return f"any{rng.randrange(100)}"


def self_check(seed: int = 0, cases: int = 5) -> dict:
    """Seeded codec property suite over every wire kind. Properties:
    JSON round trip == dataclass; binary round trip == JSON wire dict
    AND == dataclass; cross-codec re-encode is byte-stable; unknown
    wire fields are tolerated; delta_of/apply_delta reproduces a
    mutated object exactly."""
    import json as _json
    import random as _random

    rng = _random.Random(seed)
    checked = failures = 0
    json_bytes = binary_bytes = 0
    errors: list[str] = []
    for kind, cls in sorted(KIND_TYPES.items()):
        for case in range(cases):
            checked += 1
            try:
                obj = _gen_value(cls, rng)
                wire_dict = encode_kind(kind, obj)
                jtext = _json.dumps(wire_dict, sort_keys=True)
                json_bytes += len(jtext.encode())
                # 1: JSON round trip inverts to the same dataclass
                assert decode_kind(kind, _json.loads(jtext)) == obj, "json != dataclass"
                # 2: binary round trip preserves the wire dict and object
                frame = dumps_binary(wire_dict)
                binary_bytes += len(frame)
                back = loads_binary(frame)
                assert back == wire_dict, "binary wire dict drifted"
                assert decode_kind(kind, back) == obj, "binary != dataclass"
                # 3: cross-codec re-encode stability (binary -> json -> binary)
                assert _json.dumps(back, sort_keys=True) == jtext, "re-encode unstable"
                assert dumps_binary(back) == frame, "binary re-encode unstable"
                # 4: unknown-field tolerance (forward compatibility)
                poisoned = dict(wire_dict)
                poisoned["__future_field__"] = {"nested": [1, 2.5, "x", None]}
                assert decode_kind(kind, poisoned) == obj, "unknown field broke decode"
                # 5: delta round trip on a mutated twin
                twin = _gen_value(cls, rng)
                patch = delta_of(kind, obj, twin)
                assert apply_delta(kind, obj, patch) == twin, "delta != twin"
            except Exception as e:  # noqa: BLE001 - the gate reports, not raises
                failures += 1
                errors.append(f"{kind}[{case}]: {e}")
    return {
        "ok": failures == 0,
        "kinds": len(KIND_TYPES),
        "cases": checked,
        "failures": failures,
        "errors": errors[:10],
        "json_bytes": json_bytes,
        "binary_bytes": binary_bytes,
        "seed": seed,
    }


def main(argv: Optional[list] = None) -> int:
    import argparse
    import json as _json

    ap = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.apis.wire",
        description="Wire-codec self-check: seeded JSON/binary/delta "
                    "round-trip properties over every API kind.",
    )
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cases", type=int, default=5, help="cases per kind")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one machine-readable summary line")
    args = ap.parse_args(argv)
    summary = self_check(seed=args.seed, cases=args.cases)
    if args.as_json:
        print(_json.dumps(summary, sort_keys=True))
    else:
        for err in summary["errors"]:
            print(f"wire: FAIL {err}")
        print(
            f"wire: {'ok' if summary['ok'] else 'FAILED'} "
            f"({summary['cases']} cases over {summary['kinds']} kinds, "
            f"json {summary['json_bytes']}B vs binary {summary['binary_bytes']}B)"
        )
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
