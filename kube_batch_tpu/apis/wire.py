"""Full-fidelity wire codec for the store-backend protocol.

The scheduler HTTP endpoints in ``server.py`` serialize objects for
*observability* — a pod on ``/apis/v1alpha1/pods`` carries only
namespace/name/phase/node. A networked store backend
(``cache/backend.py``) needs the whole object back: requests, gang
annotations, affinity, tolerations — everything the solve reads. This
module is that codec: a generic recursive encoder/decoder over the
``apis/types.py`` dataclasses, driven by field type hints, so a new
field on any API type rides the wire without touching this file.

Encoding rules: dataclass -> dict of encoded fields, str-Enum -> its
value, dict -> encoded values (keys stay strings), list/tuple -> JSON
array, scalars/None pass through. Decoding inverts field-by-field from
the declared type; unknown wire fields are ignored (forward
compatibility) and missing ones fall back to the dataclass default.
"""

from __future__ import annotations

import dataclasses
import typing
from enum import Enum
from typing import Any, Optional, Union

from kube_batch_tpu.apis import types as api_types

__all__ = ["KIND_TYPES", "to_wire", "from_wire", "decode_kind", "encode_kind"]

# kind name (cache/store.py KINDS) -> dataclass; string keys on purpose:
# apis/ sits below cache/ in the layering and must not import it.
KIND_TYPES: dict[str, type] = {
    "pods": api_types.Pod,
    "nodes": api_types.Node,
    "podgroups": api_types.PodGroup,
    "queues": api_types.Queue,
    "poddisruptionbudgets": api_types.PodDisruptionBudget,
    "priorityclasses": api_types.PriorityClass,
    "persistentvolumes": api_types.PersistentVolume,
    "persistentvolumeclaims": api_types.PersistentVolumeClaim,
    "storageclasses": api_types.StorageClass,
    "leases": api_types.Lease,
}

_hints_cache: dict[type, dict[str, Any]] = {}


def _hints(cls: type) -> dict[str, Any]:
    hints = _hints_cache.get(cls)
    if hints is None:
        # types.py uses `from __future__ import annotations`: field types
        # are strings until resolved against the defining module
        hints = typing.get_type_hints(cls, vars(api_types))
        _hints_cache[cls] = hints
    return hints


def to_wire(obj: Any) -> Any:
    """Encode any API object (or nested fragment) to JSON-able data."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: to_wire(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(hint: Any, data: Any) -> Any:
    """Decode wire data back into the shape ``hint`` declares."""
    origin = typing.get_origin(hint)
    if origin is Union:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if data is None:
            return None
        return from_wire(args[0], data) if args else data
    if origin in (list, tuple):
        args = typing.get_args(hint)
        if data is None:
            return [] if origin is list else ()
        if origin is list:
            inner = args[0] if args else Any
            return [from_wire(inner, v) for v in data]
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(from_wire(args[0], v) for v in data)
        return tuple(
            from_wire(args[i] if i < len(args) else Any, v)
            for i, v in enumerate(data)
        )
    if origin is dict:
        args = typing.get_args(hint)
        inner = args[1] if len(args) == 2 else Any
        return {k: from_wire(inner, v) for k, v in (data or {}).items()}
    if isinstance(hint, type) and issubclass(hint, Enum):
        return hint(data)
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        if data is None:
            return None
        hints = _hints(hint)
        names = {f.name for f in dataclasses.fields(hint)}
        kwargs = {
            k: from_wire(hints.get(k, Any), v)
            for k, v in data.items()
            if k in names
        }
        return hint(**kwargs)
    return data


def decode_kind(kind: str, data: dict) -> Any:
    """Decode one wire object of the named store kind."""
    cls = KIND_TYPES.get(kind)
    if cls is None:
        raise KeyError(f"unknown wire kind {kind!r}")
    return from_wire(cls, data)


def encode_kind(kind: str, obj: Any) -> Optional[dict]:
    """Encode one store object of the named kind (None passes through —
    watch deletes carry no new object)."""
    if obj is None:
        return None
    if kind not in KIND_TYPES:
        raise KeyError(f"unknown wire kind {kind!r}")
    return to_wire(obj)
