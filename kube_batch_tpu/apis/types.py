"""Object model for the in-process cluster.

PodGroup/Queue mirror the reference CRDs
(reference pkg/apis/scheduling/v1alpha1/types.go:93-209, labels.go:20);
Pod/Node/PriorityClass/PodDisruptionBudget are minimal stand-ins for the
core-v1 objects, carrying exactly the fields the scheduler reads
(resources, selectors, taints/tolerations, host ports, affinity,
priority, phase/conditions).

Resource quantities are plain ``dict[str, float]`` resource lists keyed by
resource name ("cpu" in milli-units is NOT used here: "cpu" is in cores and
converted to milli-CPU by kube_batch_tpu.api.resource_info, matching the
reference's Quantity.MilliValue semantics).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

# Annotation key marking a pod's gang membership
# (reference pkg/apis/scheduling/v1alpha1/labels.go:20).
GROUP_NAME_ANNOTATION_KEY = "scheduling.k8s.io/group-name"

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "obj") -> str:
    return f"{prefix}-{next(_uid_counter):08d}"


@dataclass
class ObjectMeta:
    """Standard object metadata (name/namespace/uid/labels/annotations)."""

    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None  # non-None => object is terminating
    owner_job: Optional[str] = None  # stand-in for ownerReferences -> controller

    def __post_init__(self) -> None:
        if not self.uid:
            self.uid = new_uid(self.name or "obj")
        if not self.creation_timestamp:
            self.creation_timestamp = time.time()


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


class PodPhase(str, Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class Container:
    """One container: resource requests drive scheduling (limits ignored,
    matching the reference's use of requests in pod_info.go:53-73)."""

    name: str = "main"
    requests: dict[str, float] = field(default_factory=dict)
    ports: list[int] = field(default_factory=list)  # hostPorts


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return (not self.key) or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"  # NoSchedule | PreferNoSchedule | NoExecute


@dataclass
class NodeSelectorTerm:
    """matchExpressions subset: key In values / Exists / NotIn / DoesNotExist."""

    key: str = ""
    operator: str = "In"
    values: list[str] = field(default_factory=list)

    def matches(self, labels: dict[str, str]) -> bool:
        present = self.key in labels
        if self.operator == "Exists":
            return present
        if self.operator == "DoesNotExist":
            return not present
        if self.operator == "In":
            return present and labels[self.key] in self.values
        if self.operator == "NotIn":
            return not present or labels[self.key] not in self.values
        raise ValueError(f"unknown node selector operator {self.operator!r}")


@dataclass
class PodAffinityTerm:
    """Pod (anti-)affinity: match pods by label selector within a topology
    domain (topology_key over node labels)."""

    label_selector: dict[str, str] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"


@dataclass
class Affinity:
    # requiredDuringSchedulingIgnoredDuringExecution node affinity: OR of terms
    node_affinity_required: list[NodeSelectorTerm] = field(default_factory=list)
    # preferred node affinity: (weight, term) pairs, summed when matching
    node_affinity_preferred: list[tuple[int, NodeSelectorTerm]] = field(default_factory=list)
    pod_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    # preferredDuringSchedulingIgnoredDuringExecution pod (anti-)affinity:
    # (weight, term) pairs — scored by nodeorder's InterPodAffinity
    # priority, never gating feasibility
    pod_affinity_preferred: list[tuple[int, PodAffinityTerm]] = field(default_factory=list)
    pod_anti_affinity_preferred: list[tuple[int, PodAffinityTerm]] = field(default_factory=list)

    def has_pod_affinity_terms(self) -> bool:
        return bool(
            self.pod_affinity_required
            or self.pod_anti_affinity_required
            or self.pod_affinity_preferred
            or self.pod_anti_affinity_preferred
        )


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    phase: PodPhase = PodPhase.PENDING
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    node_name: str = ""
    scheduler_name: str = "kube-batch-tpu"
    node_selector: dict[str, str] = field(default_factory=dict)
    tolerations: list[Toleration] = field(default_factory=list)
    affinity: Optional[Affinity] = None
    priority: Optional[int] = None
    priority_class_name: str = ""
    conditions: list[PodCondition] = field(default_factory=list)
    # Names of PersistentVolumeClaims this pod mounts (same namespace) —
    # the slice of pod.spec.volumes the volume binder consults.
    volumes: list[str] = field(default_factory=list)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class NodeCondition:
    type: str = "Ready"  # Ready | OutOfDisk | MemoryPressure | DiskPressure | PIDPressure
    status: str = "True"


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    conditions: list[NodeCondition] = field(default_factory=lambda: [NodeCondition()])
    unschedulable: bool = False  # spec.unschedulable (cordon)

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def labels(self) -> dict[str, str]:
        return self.metadata.labels

    def ready(self) -> bool:
        return any(c.type == "Ready" and c.status == "True" for c in self.conditions)


# ---------------------------------------------------------------------------
# PodGroup / Queue CRDs (reference types.go:93-209)
# ---------------------------------------------------------------------------


class PodGroupPhase(str, Enum):
    """reference types.go:24-44."""

    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


POD_GROUP_UNSCHEDULABLE_TYPE = "Unschedulable"

# Condition reasons (reference types.go:77-90).
POD_FAILED_REASON = "PodFailed"
POD_DELETED_REASON = "PodDeleted"
NOT_ENOUGH_RESOURCES_REASON = "NotEnoughResources"
NOT_ENOUGH_PODS_REASON = "NotEnoughTasks"


@dataclass
class PodGroupCondition:
    type: str = POD_GROUP_UNSCHEDULABLE_TYPE
    status: str = "True"
    transition_id: str = ""
    last_transition_time: float = 0.0
    reason: str = ""
    message: str = ""


@dataclass
class PodGroupSpec:
    """reference types.go:113-136."""

    min_member: int = 0
    queue: str = ""
    priority_class_name: str = ""
    min_resources: Optional[dict[str, float]] = None


@dataclass
class PodGroupStatus:
    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: list[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0


@dataclass
class PodGroup:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class QueueSpec:
    weight: int = 1
    capability: dict[str, float] = field(default_factory=dict)


@dataclass
class QueueStatus:
    unknown: int = 0
    pending: int = 0
    running: int = 0


@dataclass
class Queue:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: QueueSpec = field(default_factory=QueueSpec)
    status: QueueStatus = field(default_factory=QueueStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# PriorityClass / PodDisruptionBudget (minimal)
# ---------------------------------------------------------------------------


@dataclass
class PriorityClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PodDisruptionBudget:
    """Legacy gang-scheduling source (reference cache/event_handlers.go:494-604):
    a PDB with min_available N over a label selector acts as a shadow gang."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    min_available: int = 0
    selector: dict[str, str] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.name


# ---------------------------------------------------------------------------
# Volumes (reference wires PV/PVC/StorageClass informers into the k8s
# volumebinder at cache.go:268-297; interface contract interface.go:46-56).
# Minimal models: what assume-at-allocate / bind-at-dispatch needs.
# ---------------------------------------------------------------------------


class VolumeBindingMode(str, Enum):
    IMMEDIATE = "Immediate"
    WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"


class VolumePhase(str, Enum):
    """PV status.phase (subset) / PVC status.phase."""

    PENDING = "Pending"
    AVAILABLE = "Available"
    BOUND = "Bound"
    RELEASED = "Released"
    LOST = "Lost"


@dataclass
class StorageClass:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)  # cluster-scoped
    provisioner: str = ""
    volume_binding_mode: VolumeBindingMode = VolumeBindingMode.IMMEDIATE

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolume:
    """Cluster-scoped. `node_affinity` carries the volume's topology
    (required node-selector terms, OR-of-terms like pod node affinity);
    empty means accessible from every node."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    capacity_storage: float = 0.0  # bytes
    storage_class_name: str = ""
    node_affinity: list[NodeSelectorTerm] = field(default_factory=list)
    claim_ref: str = ""  # "namespace/name" of the bound PVC
    phase: VolumePhase = VolumePhase.AVAILABLE

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class PersistentVolumeClaim:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    storage_class_name: str = ""
    request_storage: float = 0.0  # bytes (spec.resources.requests[storage])
    volume_name: str = ""  # spec.volumeName, set when bound
    phase: VolumePhase = VolumePhase.PENDING

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


# ---------------------------------------------------------------------------
# Lease (coordination.k8s.io/v1 shape, minimal)
# ---------------------------------------------------------------------------


@dataclass
class Lease:
    """Leader-election lease — the role the reference fills with a
    ConfigMap resource lock (cmd/kube-batch/app/server.go:115-139,
    resourcelock.ConfigMapsResourceLock). Arbitration happens inside the
    store that holds the lease (ClusterStore.try_acquire_lease), so all
    timestamps are the arbiter's clock — candidates never compare their
    own clocks."""

    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder_identity: str = ""
    lease_duration_seconds: float = 15.0
    acquire_time: float = 0.0  # arbiter clock, time.time()
    renew_time: float = 0.0  # arbiter clock, time.time()
    lease_transitions: int = 0  # leadership changes, k8s leaseTransitions

    @property
    def name(self) -> str:
        return self.metadata.name
