"""SchedulerCache: the event-driven mutable mirror of the cluster.

Redesign of reference pkg/scheduler/cache/cache.go:72-345 +
event_handlers.go:37-795 + util.go:42-60 for the in-process runtime:
instead of nine client-go informers against an API server, the cache
subscribes to a ClusterStore (cache/store.py) and receives the same
add/update/delete callbacks. Everything downstream is kept:

- Jobs/Nodes/Queues/PriorityClasses mirrors under one mutex;
- the pod filter (only this scheduler's pending pods + every
  non-pending pod, cache.go:245-266);
- shadow PodGroups for podgroup-less pods (util.go:42-60);
- PriorityClass resolution at snapshot time (cache.go:570-580);
- write side: Bind/Evict mutate the mirror synchronously, then fire
  the store write asynchronously; a failed write re-enters through the
  rate-limited ``errTasks`` resync queue (cache.go:480-534);
- terminated jobs are garbage-collected through the ``deletedJobs``
  queue (cache.go:480-510);
- Snapshot() deep-clones jobs/nodes/queues for the session
  (cache.go:535-585).

The default write side is the store itself (the in-process stand-in for
the API server): Bind writes ``pod.node_name`` back through
``store.update_pod`` — which re-enters the cache as an update event and
flips the task Binding->Bound, exactly how a kubelet-confirmed bind
round-trips through the watch stream in the reference.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from kube_batch_tpu import faults, log, metrics, obs
from kube_batch_tpu.api.cluster_info import ClusterInfo
from kube_batch_tpu.api.job_info import JobInfo, TaskInfo, job_key, pod_key
from kube_batch_tpu.api.node_info import NodeInfo
from kube_batch_tpu.api.queue_info import QueueInfo
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.apis.types import (
    Node,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    PodGroup,
    PodGroupPhase,
    PodGroupSpec,
    PodPhase,
    PriorityClass,
    Queue,
    ObjectMeta,
)
from kube_batch_tpu.cache.store import (
    NODES,
    PDBS,
    POD_GROUPS,
    PODS,
    PRIORITY_CLASSES,
    PVCS,
    PVS,
    QUEUES,
    STORAGE_CLASSES,
    ClusterStore,
    EventHandler,
    StaleWrite,
)
from kube_batch_tpu.utils.locking import assume_locked
from kube_batch_tpu.utils.workqueue import RateLimitingQueue

_encode_cache = None


def _notify_encode_cache(kind: str, key: str, obj=None, old=None) -> None:
    """Dirty-feed hook for the incremental encoder
    (ops/encode_cache.py): every informer event bumps the monotonic
    store version and drops the churned object's memo entries; the same
    feed fans out ``(kind, key, obj, old)`` to streaming-mode listeners
    (streaming.py) so micro-cycles wake on churn instead of polling.
    Lazily imported — the ops package pulls jax, which cache
    construction must not require. Called AFTER releasing the mirror
    mutex (listeners may take their own locks)."""
    global _encode_cache
    if _encode_cache is None:
        try:
            from kube_batch_tpu.ops import encode_cache as _ec
        except Exception:  # noqa: BLE001 -- encoder absent: nothing to feed
            _encode_cache = False
            return
        _encode_cache = _ec
    if _encode_cache is not False:
        _encode_cache.note_store_event(kind, key, obj=obj, old=old)

SHADOW_POD_GROUP_KEY = "kube-batch-tpu/shadow-pod-group"


def shadow_pod_group(pg: Optional[PodGroup]) -> bool:
    """reference cache/util.go:33-41."""
    if pg is None:
        return True
    return SHADOW_POD_GROUP_KEY in pg.metadata.annotations


def create_shadow_pod_group(pod: Pod) -> PodGroup:
    """Single-member gang for a pod with no PodGroup
    (reference cache/util.go:43-60). Job identity follows the pod's
    controller when it has one, so sibling pods of one controller share
    a shadow group. Phase starts Inqueue: the Go zero-value phase (\"\")
    passes allocate's Pending gate (allocate.go:52); our dataclass
    default is Pending, so the equivalent pass-through is explicit."""
    jid = pod.metadata.owner_job or pod.metadata.uid
    pg = PodGroup(
        metadata=ObjectMeta(
            name=str(jid),
            namespace=pod.namespace,
            uid=f"shadow-{jid}",
            annotations={SHADOW_POD_GROUP_KEY: str(jid)},
        ),
        spec=PodGroupSpec(min_member=1),
    )
    pg.status.phase = PodGroupPhase.INQUEUE
    return pg


def _is_terminated(status: TaskStatus) -> bool:
    """reference event_handlers.go:37-39."""
    return status in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)


def job_terminated(job: JobInfo) -> bool:
    """reference api/helpers.go:101-106 — with one divergence: a shadow
    PodGroup counts as absent. It exists only inside the cache, so no
    store delete event will ever unset it; without this, every shadow
    job would leak in ``jobs`` (and get cloned into every snapshot)
    after its pod is deleted."""
    return shadow_pod_group(job.pod_group) and job.pdb is None and not job.tasks


class StoreBinder:
    """Default Binder: writes the bind back to the store (the reference's
    defaultBinder posts a v1.Binding to the API server, cache.go:110-129).
    The store update re-enters the cache as a pod update event."""

    def __init__(self, store: ClusterStore) -> None:
        self._store = store

    def bind(self, pod: Pod, hostname: str) -> None:
        bound = dataclasses.replace(pod, node_name=hostname)
        self._store.update_pod(bound)

    def bind_many_versioned(
        self, bindings: list[tuple[str, str, str]], snapshot_version: int
    ) -> None:
        """Optimistic gang transaction: all entries commit or the store
        raises StaleWrite (federation dispatch path, one gang per call)."""
        self._store.conditional_bind_many(bindings, snapshot_version)


class StoreEvictor:
    """Default Evictor: deletes the pod from the store (the reference's
    defaultEvictor deletes it from the API server, cache.go:131-146)."""

    def __init__(self, store: ClusterStore) -> None:
        self._store = store

    def evict(self, pod: Pod) -> None:
        log.V(3).infof("Evicting pod %s/%s", pod.namespace, pod.name)
        self._store.delete_pod(pod.namespace, pod.name)

    def evict_versioned(self, pod: Pod, snapshot_version: int) -> None:
        """Optimistic evict: rejected with StaleWrite when the pod's node
        took a placement write the snapshot never saw."""
        log.V(3).infof(
            "Evicting pod %s/%s (snapshot v%d)",
            pod.namespace, pod.name, snapshot_version,
        )
        self._store.conditional_evict(pod.namespace, pod.name, snapshot_version)


class StoreStatusUpdater:
    """Default StatusUpdater (reference cache.go:149-166)."""

    def __init__(self, store: ClusterStore) -> None:
        self._store = store

    def update_pod_condition(self, pod: Pod, condition: PodCondition) -> None:
        """Write the condition through the store (the reference posts it
        to the API server) so subscribers see the update event and stale
        TaskInfo.pod references can't swallow it."""
        cur = self._store.get_pod(pod.namespace, pod.name)
        if cur is None:
            return
        conds = list(cur.conditions)
        for i, c in enumerate(conds):
            if c.type == condition.type:
                if (c.status, c.reason, c.message) == (
                    condition.status,
                    condition.reason,
                    condition.message,
                ):
                    return
                conds[i] = condition
                break
        else:
            conds.append(condition)
        self._store.update_pod(dataclasses.replace(cur, conditions=conds))

    def update_pod_group(self, pg: PodGroup) -> None:
        if self._store.get(POD_GROUPS, f"{pg.metadata.namespace}/{pg.name}") is not None:
            self._store.update_pod_group(pg)


class NoopVolumeBinder:
    """Volume hooks as structural no-ops (the reference test utils'
    FakeVolumeBinder shape, util/test_utils.go:150-163)."""

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        return None

    def bind_volumes(self, task: TaskInfo) -> None:
        return None


class VolumeBindingError(Exception):
    """A pod's claims cannot be satisfied on the chosen node (assume
    time) or the assumed binding no longer holds (bind time)."""


class StoreVolumeBinder:
    """Assume-at-allocate / bind-at-dispatch volume binder over the
    in-process store — the role the reference's defaultVolumeBinder +
    upstream k8s volumebinder play (cache.go:165-189; contract
    interface.go:46-56; call sites session.go:241-260 and :298-322).

    Mirrors of PVs/PVCs/StorageClasses are fed by store subscriptions
    (the reference wires the same three informers into newSchedulerCache,
    cache.go:268-297).

    - `allocate_volumes(task, hostname)` (= AssumePodVolumes): for every
      claim the pod mounts, verify a bound claim's PV tolerates the node,
      or pick the smallest Available PV matching class/capacity/topology
      and record the assumption in-memory. Raises VolumeBindingError when
      any claim cannot be satisfied — the session leaves the task
      unallocated, like the serial loop does on AssumePodVolumes error.
    - `bind_volumes(task)` (= BindPodVolumes): write the assumed
      bindings through the store (PV.claim_ref + both phases -> Bound).
      Raises when an assumed PV was claimed or deleted meanwhile; the
      session routes that through the errTasks resync queue.

    All static binding happens at schedule time regardless of the class's
    volume_binding_mode (in-process there is no separate PV controller to
    do Immediate-mode binding earlier); the StorageClass mirror validates
    that claims name real classes. Dynamic provisioning has no in-process
    counterpart: any class with no pre-provisioned matching PV fails the
    assume, exactly like a cluster whose provisioner is down."""

    def __init__(self, store: ClusterStore) -> None:
        self._store = store
        self._lock = threading.RLock()
        self._pvs: dict[str, object] = {}
        self._pvcs: dict[str, object] = {}
        self._classes: dict[str, object] = {}
        # task uid -> {pvc_key: pv_name} assumed (not yet written)
        self._assumed: dict[str, dict[str, str]] = {}
        # pv name -> pvc_key reserved by an assumption
        self._reserved: dict[str, str] = {}
        for kind, mirror in ((PVS, self._pvs), (PVCS, self._pvcs), (STORAGE_CLASSES, self._classes)):
            store.add_event_handler(
                kind,
                EventHandler(
                    on_add=lambda obj, m=mirror, k=kind: self._upsert(m, k, obj),
                    on_update=lambda old, new, m=mirror, k=kind: self._upsert(m, k, new),
                    on_delete=lambda obj, m=mirror, k=kind: self._remove(m, k, obj),
                ),
            )

    def _key(self, kind: str, obj) -> str:
        from kube_batch_tpu.cache.store import obj_key

        return obj_key(kind, obj)

    def _upsert(self, mirror: dict, kind: str, obj) -> None:
        with self._lock:
            mirror[self._key(kind, obj)] = obj

    def _remove(self, mirror: dict, kind: str, obj) -> None:
        with self._lock:
            mirror.pop(self._key(kind, obj), None)

    # -- assume (AssumePodVolumes, session.go:241-260) ---------------------

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        claims = getattr(task.pod, "volumes", None)
        if not claims:
            task.volume_ready = True
            return
        node = self._store.get(NODES, hostname)
        node_labels = node.metadata.labels if node is not None else {}
        with self._lock:
            assumed: dict[str, str] = {}
            all_bound = True
            for claim in claims:
                pvc_key = f"{task.namespace}/{claim}"
                pvc = self._pvcs.get(pvc_key)
                if pvc is None:
                    raise VolumeBindingError(
                        f"pod <{task.namespace}/{task.name}> mounts unknown "
                        f"claim <{pvc_key}>"
                    )
                if (
                    pvc.storage_class_name
                    and pvc.storage_class_name not in self._classes
                ):
                    raise VolumeBindingError(
                        f"claim <{pvc_key}> names unknown storage class "
                        f"<{pvc.storage_class_name}>"
                    )
                if pvc.volume_name:
                    pv = self._pvs.get(pvc.volume_name)
                    if pv is None:
                        raise VolumeBindingError(
                            f"claim <{pvc_key}> bound to missing volume "
                            f"<{pvc.volume_name}>"
                        )
                    if not self._pv_fits_node(pv, node_labels):
                        raise VolumeBindingError(
                            f"volume <{pv.name}> of claim <{pvc_key}> does "
                            f"not tolerate node <{hostname}>"
                        )
                    continue
                pv = self._find_best_pv(
                    pvc, pvc_key, node_labels, exclude=set(assumed.values())
                )
                if pv is None:
                    raise VolumeBindingError(
                        f"no persistent volume satisfies claim <{pvc_key}> "
                        f"on node <{hostname}>"
                    )
                assumed[pvc_key] = pv.name
                all_bound = False
            # commit assumptions only when every claim succeeded
            for pvc_key, pv_name in assumed.items():
                self._reserved[pv_name] = pvc_key
            if assumed:
                self._assumed.setdefault(task.uid, {}).update(assumed)
            task.volume_ready = all_bound

    @assume_locked
    def _find_best_pv(self, pvc, pvc_key: str, node_labels: dict, exclude=frozenset()):
        """Smallest Available PV matching class/capacity/topology, not
        reserved by another assumption nor picked for a sibling claim of
        the same pod (`exclude`) — k8s findBestMatchPVForClaim."""
        from kube_batch_tpu.apis.types import VolumePhase

        best = None
        for pv in self._pvs.values():
            if pv.phase != VolumePhase.AVAILABLE or pv.claim_ref:
                continue
            if pv.name in exclude:
                continue
            reserved_for = self._reserved.get(pv.name)
            if reserved_for is not None and reserved_for != pvc_key:
                continue
            if pv.storage_class_name != pvc.storage_class_name:
                continue
            if pv.capacity_storage < pvc.request_storage:
                continue
            if not self._pv_fits_node(pv, node_labels):
                continue
            if best is None or pv.capacity_storage < best.capacity_storage:
                best = pv
        return best

    @staticmethod
    def _pv_fits_node(pv, node_labels: dict) -> bool:
        if not pv.node_affinity:
            return True
        return any(term.matches(node_labels) for term in pv.node_affinity)

    # -- bind (BindPodVolumes, session.go:298-322) -------------------------

    def bind_volumes(self, task: TaskInfo) -> None:
        from kube_batch_tpu.apis.types import VolumePhase

        with self._lock:
            # Read, don't pop: a failed bind must keep the assumption
            # record (and its reservations), or a retry would vacuously
            # succeed and bind the pod without its volumes. Successful
            # writes are idempotent on retry (claim_ref == pvc_key
            # passes the conflict check), so partial failure is safe.
            assumed = dict(self._assumed.get(task.uid, {}))
        for pvc_key, pv_name in assumed.items():
            pv = self._store.get(PVS, pv_name)
            pvc = self._store.get(PVCS, pvc_key)
            if pv is None or pvc is None:
                raise VolumeBindingError(
                    f"assumed volume <{pv_name}> or claim <{pvc_key}> "
                    "vanished before bind"
                )
            if pv.claim_ref and pv.claim_ref != pvc_key:
                raise VolumeBindingError(
                    f"assumed volume <{pv_name}> was claimed by "
                    f"<{pv.claim_ref}>"
                )
            self._store.update_persistent_volume(
                dataclasses.replace(pv, claim_ref=pvc_key, phase=VolumePhase.BOUND)
            )
            self._store.update_persistent_volume_claim(
                dataclasses.replace(
                    pvc, volume_name=pv_name, phase=VolumePhase.BOUND
                )
            )
        task.volume_ready = True
        with self._lock:
            # Re-read under the writing lock: only retire the entries we
            # actually bound — a concurrent assume may have added more.
            rec = self._assumed.get(task.uid)
            if rec is not None:
                for pvc_key in assumed:
                    rec.pop(pvc_key, None)
                if not rec:
                    self._assumed.pop(task.uid, None)
            for pv_name in assumed.values():
                self._reserved.pop(pv_name, None)

    # -- rollback (a failed/abandoned assumption must free the PVs) --------

    def forget(self, task_uid: str) -> None:
        with self._lock:
            for pv_name in self._assumed.pop(task_uid, {}).values():
                self._reserved.pop(pv_name, None)

    def reset(self) -> None:
        """Drop every outstanding assumption. Called at snapshot time:
        assume/bind both happen synchronously within one session, so
        anything still assumed when a new session starts belongs to a
        gang that never dispatched — its PVs must come back.

        Within a cycle, an unready gang's reservations deliberately
        persist: the reference keeps an Allocated-but-not-ready gang's
        *node* resources held for the rest of the cycle too (the task
        stays Allocated on its NodeInfo until the session ends,
        session.go:241-296) — volumes follow the same lifetime so a
        later job cannot take a PV out from under a gang that might
        still complete this cycle."""
        with self._lock:
            self._assumed.clear()
            self._reserved.clear()


class SchedulerCache:
    """The L2 cache (reference cache/cache.go:72-108)."""

    def __init__(
        self,
        store: ClusterStore,
        scheduler_name: str = "kube-batch-tpu",
        default_queue: str = "default",
        binder=None,
        evictor=None,
        status_updater=None,
        volume_binder=None,
        journal=None,
        staleness_fn=None,
        conditional_binds: Optional[bool] = None,
    ) -> None:
        self._mutex = threading.RLock()
        self.store = store
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        # Crash consistency (recovery/): when a WriteIntentJournal is
        # attached, every bind/evict appends an intent BEFORE its store
        # write dispatches and confirms AFTER the write acks, so a
        # takeover can reconcile the in-flight set instead of guessing.
        self.journal = journal
        # Scheduling cycle id, stamped into journal records; the
        # scheduler loop advances it each run_once.
        self.cycle = 0
        # Bounded-staleness hook: a watch-fed deployment wires the
        # watcher's snapshot_age here; the in-process store is
        # synchronously consistent (age 0).
        self._staleness_fn = staleness_fn

        self.jobs: dict[str, JobInfo] = {}
        self.nodes: dict[str, NodeInfo] = {}
        self.queues: dict[str, QueueInfo] = {}
        self.priority_classes: dict[str, PriorityClass] = {}
        self._default_priority_class: Optional[PriorityClass] = None
        self._default_priority = 0

        self.binder = binder or StoreBinder(store)
        self.evictor = evictor or StoreEvictor(store)
        self.status_updater = status_updater or StoreStatusUpdater(store)
        self.volume_binder = volume_binder or StoreVolumeBinder(store)

        self._err_tasks = RateLimitingQueue(key_fn=lambda t: t.uid)
        self._deleted_jobs = RateLimitingQueue(key_fn=lambda j: j.uid)
        # Transient write-side failures retry in place (with jitter)
        # before the heavier errTasks resync path; see _write_with_retry.
        try:
            self._write_retries = max(0, int(os.environ.get("KBT_WRITE_RETRIES", "2")))
        except ValueError:
            log.errorf(
                "KBT_WRITE_RETRIES=%r is not an integer; using 2",
                os.environ.get("KBT_WRITE_RETRIES"),
            )
            self._write_retries = 2
        # errTasks terminal drop: a permanently-rejected write must not
        # ride the resync queue forever (see _process_resync_task).
        try:
            self._resync_max_retries = max(
                1, int(os.environ.get("KBT_RESYNC_MAX_RETRIES", "15"))
            )
        except ValueError:
            log.errorf(
                "KBT_RESYNC_MAX_RETRIES=%r is not an integer; using 15",
                os.environ.get("KBT_RESYNC_MAX_RETRIES"),
            )
            self._resync_max_retries = 15
        # Omega-style optimistic dispatch (federation): bulk binds and
        # evicts go through the store's conditional transactions, one
        # gang per transaction, carrying the snapshot's store version.
        # A StaleWrite loser refreshes its version and retries up to
        # KBT_CONFLICT_MAX_RETRIES times with jittered backoff; a
        # terminal loser accepts store truth (confirm the intent, resync
        # the gang's tasks). On by default when KBT_FEDERATION is set;
        # federation.py passes conditional_binds=True explicitly.
        if conditional_binds is None:
            conditional_binds = bool(os.environ.get("KBT_FEDERATION", ""))
        self._conditional_binds = conditional_binds
        try:
            self._conflict_max_retries = max(
                0, int(os.environ.get("KBT_CONFLICT_MAX_RETRIES", "3"))
            )
        except ValueError:
            log.errorf(
                "KBT_CONFLICT_MAX_RETRIES=%r is not an integer; using 3",
                os.environ.get("KBT_CONFLICT_MAX_RETRIES"),
            )
            self._conflict_max_retries = 3
        # Coalesced conditional writes (wire protocol v2): every gang
        # dispatched by one cycle rides ONE /backend/v1/txn round trip
        # (all-or-nothing per gang, per-txn conflict results) when the
        # negotiated backend supports it. Off -> per-gang round trips.
        self._txn_coalesce = os.environ.get(
            "KBT_TXN_COALESCE", "1"
        ).lower() not in ("", "0", "false")
        # Store version this cache's latest snapshot solved over — the
        # version every conditional dispatch carries (#: guarded_by _mutex
        # for writes; dispatch reads the int atomically).
        self._snapshot_version = 0
        self._writer: Optional[ThreadPoolExecutor] = None
        self._workers: list[threading.Thread] = []
        self._stop = threading.Event()
        self._synced = False

        self._subscribe()

    # -- informer wiring (reference cache.go:233-301) ----------------------

    def _pod_filter(self, pod: Pod) -> bool:
        """Only this scheduler's pending pods, plus every non-pending pod
        (they hold node resources no matter who scheduled them)."""
        if pod.scheduler_name == self.scheduler_name and pod.phase == PodPhase.PENDING:
            return True
        return pod.phase != PodPhase.PENDING

    def _subscribe(self) -> None:
        s = self.store
        s.add_event_handler(
            PODS,
            EventHandler(
                on_add=self.add_pod,
                on_update=self.update_pod,
                on_delete=self.delete_pod,
                filter=self._pod_filter,
            ),
        )
        s.add_event_handler(
            NODES,
            EventHandler(
                on_add=self.add_node,
                on_update=self.update_node,
                on_delete=self.delete_node,
            ),
        )
        s.add_event_handler(
            POD_GROUPS,
            EventHandler(
                on_add=self.add_pod_group,
                on_update=self.update_pod_group,
                on_delete=self.delete_pod_group,
            ),
        )
        s.add_event_handler(
            QUEUES,
            EventHandler(
                on_add=self.add_queue,
                on_update=self.update_queue,
                on_delete=self.delete_queue,
            ),
        )
        s.add_event_handler(
            PDBS,
            EventHandler(
                on_add=self.add_pdb,
                on_update=self.update_pdb,
                on_delete=self.delete_pdb,
            ),
        )
        s.add_event_handler(
            PRIORITY_CLASSES,
            EventHandler(
                on_add=self.add_priority_class,
                on_update=self.update_priority_class,
                on_delete=self.delete_priority_class,
            ),
        )
        self._synced = True

    def run(self) -> None:
        """Start the resync + GC workers and the async write pool
        (reference cache.go:304-325)."""
        if self._writer is not None:
            return
        self._stop.clear()
        self._err_tasks.restart()
        self._deleted_jobs.restart()
        self._writer = ThreadPoolExecutor(max_workers=8, thread_name_prefix="kb-write")
        for name, fn in (
            ("kb-resync", self._process_resync_task),
            ("kb-gc", self._process_cleanup_job),
        ):
            t = threading.Thread(target=self._worker, args=(fn,), name=name, daemon=True)
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self._err_tasks.shut_down()
        self._deleted_jobs.shut_down()
        if self._writer is not None:
            self._writer.shutdown(wait=True)
            self._writer = None
        for t in self._workers:
            t.join(timeout=5)
        self._workers.clear()

    def wait_for_cache_sync(self) -> bool:
        """The store replays existing objects at subscription, so the
        mirror is synchronously warm (reference cache.go:327-348)."""
        return self._synced

    def snapshot_age(self) -> float:
        """Seconds the mirror may lag the source of truth — 0 for the
        in-process store (synchronous event dispatch); a watch-fed
        deployment wires its watcher's snapshot_age via staleness_fn.
        The scheduler's refuse-to-schedule guard (KBT_MAX_SNAPSHOT_AGE_S)
        reads this every cycle."""
        if self._staleness_fn is not None:
            return float(self._staleness_fn())
        return 0.0

    def _worker(self, fn) -> None:
        while not self._stop.is_set():
            fn()

    # -- job/task primitives (reference event_handlers.go:43-180) ----------

    @assume_locked
    def _get_or_create_job(self, ti: TaskInfo) -> Optional[JobInfo]:
        if not ti.job:
            if ti.pod.scheduler_name != self.scheduler_name:
                log.V(4).infof(
                    "Pod %s/%s not scheduled by %s, skip shadow PodGroup",
                    ti.namespace, ti.name, self.scheduler_name,
                )
                return None
            pg = create_shadow_pod_group(ti.pod)
            ti.job = job_key(pg.metadata.namespace, pg.name)
            if ti.job not in self.jobs:
                job = JobInfo(ti.job)
                job.set_pod_group(pg)
                job.queue = self.default_queue
                self.jobs[ti.job] = job
        elif ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    @assume_locked
    def _add_task(self, ti: TaskInfo) -> None:
        job = self._get_or_create_job(ti)
        if job is not None:
            job.add_task_info(ti)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                self.nodes[ti.node_name] = NodeInfo(None)
            if not _is_terminated(ti.status):
                # overcommit=True: this is the watch-event path — the
                # store already committed the bind. A cross-shard bind
                # race can oversubscribe a node; the mirror records the
                # negative idle (node reads unfit) instead of raising
                # out of the pump thread.
                self.nodes[ti.node_name].add_task(ti, overcommit=True)

    @assume_locked
    def _add_pod(self, pod: Pod) -> None:
        self._add_task(TaskInfo(pod))

    @assume_locked
    def _delete_task(self, ti: TaskInfo) -> None:
        job_err = node_err = None
        if ti.job:
            job = self.jobs.get(ti.job)
            if job is not None:
                try:
                    job.delete_task_info(ti)
                except KeyError as e:
                    job_err = e
            else:
                job_err = KeyError(f"job {ti.job} not found for task {ti.namespace}/{ti.name}")
        if ti.node_name:
            node = self.nodes.get(ti.node_name)
            # Terminated tasks were never added to the node (_add_task
            # guards with _is_terminated), so only remove what is
            # actually resident — otherwise every delete/update of a
            # Succeeded/Failed pod raises and strands the task.
            if node is not None and pod_key(ti.pod) in node.tasks:
                try:
                    node.remove_task(ti)
                except KeyError as e:
                    node_err = e
        if job_err or node_err:
            raise KeyError(f"{job_err or ''}; {node_err or ''}")

    @assume_locked
    def _update_task(self, old: TaskInfo, new: TaskInfo) -> None:
        self._delete_task(old)
        self._add_task(new)

    def _resolve_shadow_job(self, pi: TaskInfo) -> None:
        """Recompute the shadow job id for a podgroup-less pod of this
        scheduler, so delete/update events find the job that
        ``_get_or_create_job`` filed the task under. (The reference
        recomputes only from the annotation, event_handlers.go:160-180,
        which strands shadow-job members on delete — fixed here.)"""
        if not pi.job and pi.pod.scheduler_name == self.scheduler_name:
            pi.job = job_key(
                pi.pod.namespace, pi.pod.metadata.owner_job or pi.pod.metadata.uid
            )

    @assume_locked
    def _delete_pod(self, pod: Pod) -> None:
        pi = TaskInfo(pod)
        self._resolve_shadow_job(pi)
        # Prefer the cached task: it carries Binding/Bound state the bare
        # pod does not (reference event_handlers.go:160-172).
        task = pi
        job = self.jobs.get(pi.job)
        if job is not None and pi.uid in job.tasks:
            task = job.tasks[pi.uid]
        self._delete_task(task)
        job = self.jobs.get(pi.job)
        if job is not None and job_terminated(job):
            self._delete_job(job)

    def _sync_task(self, old_task: TaskInfo) -> None:
        """Re-fetch the pod and reconcile (reference event_handlers.go:97-115)."""
        with self._mutex:
            pod = self.store.get_pod(old_task.namespace, old_task.name)
            if pod is None:
                self._delete_task(old_task)
                log.V(3).infof(
                    "Pod %s/%s was deleted, removed from cache",
                    old_task.namespace, old_task.name,
                )
                return
            self._update_task(old_task, TaskInfo(pod))

    # -- public pod handlers -----------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        with self._mutex:
            try:
                self._add_pod(pod)
            except KeyError as e:
                log.errorf("Failed to add pod %s/%s to cache: %s", pod.namespace, pod.name, e)
                return
        _notify_encode_cache(PODS, pod.metadata.uid, obj=pod)
        log.V(3).infof("Added pod <%s/%s> to cache", pod.namespace, pod.name)

    def update_pod(self, old: Pod, new: Pod) -> None:
        with self._mutex:
            try:
                self._delete_pod(old)
                self._add_pod(new)
            except KeyError as e:
                log.errorf("Failed to update pod %s/%s in cache: %s", new.namespace, new.name, e)
                return
        _notify_encode_cache(PODS, new.metadata.uid, obj=new, old=old)
        log.V(3).infof("Updated pod <%s/%s> in cache", new.namespace, new.name)

    def delete_pod(self, pod: Pod) -> None:
        with self._mutex:
            try:
                self._delete_pod(pod)
            except KeyError as e:
                log.errorf("Failed to delete pod %s/%s from cache: %s", pod.namespace, pod.name, e)
                return
        _notify_encode_cache(PODS, pod.metadata.uid, old=pod)
        log.V(3).infof("Deleted pod <%s/%s> from cache", pod.namespace, pod.name)

    # -- node handlers (reference event_handlers.go:262-370) ---------------

    def add_node(self, node: Node) -> None:
        with self._mutex:
            if node.name in self.nodes:
                self.nodes[node.name].set_node(node)
            else:
                self.nodes[node.name] = NodeInfo(node)
        _notify_encode_cache(NODES, node.name, obj=node)

    def update_node(self, old: Node, new: Node) -> None:
        with self._mutex:
            ni = self.nodes.get(new.name)
            if ni is None:
                log.errorf("Failed to update node %s: does not exist in cache", new.name)
                return
            if (
                old.allocatable != new.allocatable
                or old.capacity != new.capacity
                or old.taints != new.taints
                or old.metadata.labels != new.metadata.labels
                or old.unschedulable != new.unschedulable
                or old.conditions != new.conditions
            ):
                ni.set_node(new)
                changed = True
            else:
                changed = False
        if changed:
            _notify_encode_cache(NODES, new.name, obj=new, old=old)

    def delete_node(self, node: Node) -> None:
        with self._mutex:
            if node.name not in self.nodes:
                log.errorf("Failed to delete node %s: does not exist in cache", node.name)
                return
            del self.nodes[node.name]
        _notify_encode_cache(NODES, node.name, old=node)

    # -- podgroup handlers (reference event_handlers.go:372-493) -----------

    @assume_locked
    def _set_pod_group(self, pg: PodGroup) -> None:
        jid = job_key(pg.metadata.namespace, pg.name)
        if jid not in self.jobs:
            self.jobs[jid] = JobInfo(jid)
        self.jobs[jid].set_pod_group(pg)
        if not pg.spec.queue:
            self.jobs[jid].queue = self.default_queue

    def add_pod_group(self, pg: PodGroup) -> None:
        with self._mutex:
            self._set_pod_group(pg)
        _notify_encode_cache(
            POD_GROUPS, f"{pg.metadata.namespace}/{pg.name}", obj=pg
        )
        log.V(4).infof("Added PodGroup <%s/%s> to cache", pg.metadata.namespace, pg.name)

    def update_pod_group(self, old: PodGroup, new: PodGroup) -> None:
        with self._mutex:
            self._set_pod_group(new)
        _notify_encode_cache(
            POD_GROUPS, f"{new.metadata.namespace}/{new.name}", obj=new, old=old
        )

    def delete_pod_group(self, pg: PodGroup) -> None:
        with self._mutex:
            jid = job_key(pg.metadata.namespace, pg.name)
            job = self.jobs.get(jid)
            if job is None:
                log.errorf("Failed to delete PodGroup %s: job not found", jid)
                return
            job.unset_pod_group()
            self._delete_job(job)
        _notify_encode_cache(POD_GROUPS, f"{pg.metadata.namespace}/{pg.name}", old=pg)

    # -- pdb handlers (reference event_handlers.go:494-604) ----------------

    def add_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._mutex:
            self._set_pdb(pdb)

    def update_pdb(self, old: PodDisruptionBudget, new: PodDisruptionBudget) -> None:
        with self._mutex:
            self._set_pdb(new)

    def delete_pdb(self, pdb: PodDisruptionBudget) -> None:
        with self._mutex:
            jid = pdb.metadata.owner_job or f"{pdb.metadata.namespace}/{pdb.name}"
            job = self.jobs.get(jid)
            if job is None:
                log.errorf("Failed to delete PDB %s: job not found", jid)
                return
            job.unset_pdb()
            self._delete_job(job)

    @assume_locked
    def _set_pdb(self, pdb: PodDisruptionBudget) -> None:
        jid = pdb.metadata.owner_job or f"{pdb.metadata.namespace}/{pdb.name}"
        if jid not in self.jobs:
            self.jobs[jid] = JobInfo(jid)
        self.jobs[jid].set_pdb(pdb)
        # PDBs predate queues; they land in the default queue — unless a
        # PodGroup already assigned one (don't stomp it).
        if not self.jobs[jid].queue:
            self.jobs[jid].queue = self.default_queue

    # -- queue handlers (reference event_handlers.go:607-699) --------------

    def add_queue(self, q: Queue) -> None:
        with self._mutex:
            qi = QueueInfo(q)
            self.queues[qi.name] = qi
        _notify_encode_cache(QUEUES, q.name, obj=q)

    def update_queue(self, old: Queue, new: Queue) -> None:
        with self._mutex:
            self.queues.pop(old.name, None)
            self.queues[new.name] = QueueInfo(new)
        _notify_encode_cache(QUEUES, new.name, obj=new, old=old)

    def delete_queue(self, q: Queue) -> None:
        with self._mutex:
            self.queues.pop(q.name, None)
        _notify_encode_cache(QUEUES, q.name, old=q)

    # -- priorityclass handlers (reference event_handlers.go:701-795) ------

    def add_priority_class(self, pc: PriorityClass) -> None:
        with self._mutex:
            self._add_priority_class(pc)

    def update_priority_class(self, old: PriorityClass, new: PriorityClass) -> None:
        with self._mutex:
            self._delete_priority_class(old)
            self._add_priority_class(new)

    def delete_priority_class(self, pc: PriorityClass) -> None:
        with self._mutex:
            self._delete_priority_class(pc)

    @assume_locked
    def _add_priority_class(self, pc: PriorityClass) -> None:
        if pc.global_default:
            if self._default_priority_class is not None:
                log.errorf(
                    "Updated default priority class from <%s> to <%s> forcefully",
                    self._default_priority_class.name, pc.name,
                )
            self._default_priority_class = pc
            self._default_priority = pc.value
        self.priority_classes[pc.name] = pc

    @assume_locked
    def _delete_priority_class(self, pc: PriorityClass) -> None:
        if pc.global_default:
            self._default_priority_class = None
            self._default_priority = 0
        self.priority_classes.pop(pc.name, None)

    # -- write side (reference cache.go:369-448) ---------------------------

    @assume_locked
    def _find_job_and_task(self, ti: TaskInfo) -> tuple[JobInfo, TaskInfo]:
        job = self.jobs.get(ti.job)
        if job is None:
            raise KeyError(f"failed to find job {ti.job} for task {ti.uid}")
        task = job.tasks.get(ti.uid)
        if task is None:
            raise KeyError(f"failed to find task {ti.uid} in status {ti.status}")
        return job, task

    # -- write-intent journal hooks (recovery/journal.py) ------------------

    def _journal_intents(self, op: str, entries: list) -> list:
        """Append-before-dispatch; a journal failure degrades to an
        unjournaled dispatch, loudly — availability over protection."""
        if self.journal is None or not entries:
            return [None] * len(entries)
        try:
            # span link both ways: the append is a child span of the
            # dispatching cycle, and the journal records carry the trace
            # id so a takeover's reconciliation can name the trace that
            # wrote each intent it re-litigates
            cur = obs.current()
            # explain payloads (obs/explain): the allocate action
            # publishes per-gang forensics into the process registry
            # before dispatch reaches here, so each intent can carry the
            # compact (verdict, reason) tuple of the decision it records
            explain = None
            from kube_batch_tpu.obs import explain as _explain

            if _explain.enabled():
                explain = {}
                for gang in {e[0] for e in entries}:
                    payload = _explain.intent_payload(gang)
                    if payload is not None:
                        explain[gang] = payload
            with obs.span("journal.append", op=op, n=len(entries)) as jspan:
                seqs = self.journal.append_intents(
                    op, entries, cycle=self.cycle,
                    trace=cur.trace_id if cur is not None else "",
                    explain=explain,
                )
                jspan.set_attr("first_seq", seqs[0] if seqs else None)
                return seqs
        except Exception as e:  # noqa: BLE001 - disk full / injected fault
            metrics.register_journal_records("append_failed", len(entries))
            log.errorf(
                "journal append failed (%s); dispatching %d %s write(s) "
                "unjournaled", e, len(entries), op,
            )
            return [None] * len(entries)

    def _journal_confirm(self, seq) -> None:
        """Confirm-after-ack (no-op for unjournaled writes)."""
        if seq is None or self.journal is None:
            return
        try:
            self.journal.confirm(seq)
            obs.event("journal.confirm", seq=seq)
        except Exception as e:  # noqa: BLE001
            log.errorf("journal confirm of seq %s failed: %s", seq, e)

    def bind(self, ti: TaskInfo, hostname: str) -> None:
        """Mirror update now, API write async; failure resyncs
        (reference cache.go:404-448)."""
        with self._mutex:
            job, task = self._find_job_and_task(ti)
            node = self.nodes.get(hostname)
            if node is None:
                raise KeyError(f"failed to bind task {task.uid}: host {hostname} missing")
            job.update_task_status(task, TaskStatus.BINDING)
            task.node_name = hostname
            # overcommit=True: the session solved over a snapshot; the
            # live node may have drifted (a peer shard's bind landed
            # meanwhile). The store's conditional write is the real
            # admission check — raising here would strand the task in
            # Binding with no write submitted and no resync.
            node.add_task(task, overcommit=True)
            pod = task.pod
        seqs = self._journal_intents(
            "bind", [(task.job, f"{pod.namespace}/{pod.name}", hostname)]
        )
        self._submit_write(self._do_bind, pod, hostname, task, seqs[0])

    def bind_many(self, pairs: list, keys=None) -> None:
        """Bulk bind for the replay path: the per-bind net effect of
        `bind()` under ONE mutex acquisition and ONE async write
        submission (the reference fires a goroutine per pod,
        cache.go:439-445; a vectorized action produces 50k binds in one
        call, so the write side batches to match). `pairs` is
        [(TaskInfo, hostname)]; a pair whose job/task/host vanished from
        the mirror (concurrent delete events run under this same mutex)
        routes through errTasks instead of aborting the batch, and
        per-pod write failures still resync individually. ``keys`` is
        the replay's precomputed key hint — this binder resolves
        jobs/tasks itself, so it is accepted for protocol compatibility
        and unused."""
        del keys
        with obs.span("dispatch", binds=len(pairs)):
            resolved = []
            failed = []
            with self._mutex:
                for ti, hostname in pairs:
                    try:
                        job, task = self._find_job_and_task(ti)
                        node = self.nodes.get(hostname)
                        if node is None:
                            raise KeyError(f"host {hostname} missing")
                    except KeyError as e:
                        log.errorf("Failed to bind task %s: %s", ti.uid, e)
                        failed.append(ti)
                        continue
                    job.update_task_status(task, TaskStatus.BINDING)
                    task.node_name = hostname
                    # overcommit=True: same as bind() — snapshot drift
                    # from a peer shard's bind must not strand the task
                    node.add_task(task, overcommit=True)
                    resolved.append((task.pod, hostname, task))
            for ti in failed:
                self.resync_task(ti)
            # One journal append covers the whole bulk statement (the gang
            # ids ride per entry), flushed before the batch dispatches — a
            # leader killed mid-batch leaves exactly the unconfirmed suffix
            # for the standby's reconciliation.
            seqs = self._journal_intents(
                "bind",
                [
                    (task.job, f"{pod.namespace}/{pod.name}", hostname)
                    for pod, hostname, task in resolved
                ],
            )
            # the kb-write pool thread has no ambient contextvar context:
            # capture the current span HERE and pass it through, or the
            # async half of the bind would start a disconnected trace
            self._submit_write(
                self._do_bind_many,
                [(p, h, t, s) for (p, h, t), s in zip(resolved, seqs)],
                obs.current(),
            )

    def _do_bind_many(self, resolved: list, ctx=None) -> None:
        if self._conditional_binds and hasattr(self.binder, "bind_many_versioned"):
            # one optimistic transaction per gang: a gang commits whole
            # or loses whole, so the conflict loser re-solves a complete
            # gang instead of reconciling a half-bound one
            gangs: dict[str, list] = {}
            for entry in resolved:
                gangs.setdefault(entry[2].job, []).append(entry)
            # Coalescing (wire protocol v2): every gang this cycle
            # dispatched rides one /backend/v1/txn round trip instead of
            # one RTT per gang. Gangs stay all-or-nothing — the batch is
            # transport-level only; a conflicted gang falls back to the
            # per-gang retry ladder with a fresh version.
            supports = getattr(self.store, "supports_txn", None)
            if (
                self._txn_coalesce
                and len(gangs) > 1
                and callable(supports)
                and supports()
            ):
                self._do_bind_txn(gangs, ctx)
                return
            for gang in gangs.values():
                self._do_bind_gang(gang, ctx)
            return
        for pod, hostname, task, seq in resolved:
            self._do_bind(pod, hostname, task, seq)

    def _do_bind_txn(self, gangs: dict, ctx=None) -> None:
        """Dispatch every gang of this cycle in ONE coalesced store txn.
        Exactly-once is per gang, exactly as in the per-gang path: each
        txn carries its own snapshot version, an applied gang confirms
        its own journal seqs, a conflicted gang re-enters
        ``_do_bind_gang``'s retry ladder (which refreshes the version),
        and a transport failure mid-batch degrades LOUDLY to per-gang v1
        writes — whose conditional versions make any server-side partial
        application resolve to store truth, never a double bind."""
        order = list(gangs.values())
        txns = []
        for entries in order:
            version = self._snapshot_version
            if faults.should_fire("federation.stale_assign"):
                version = 0  # deliberately ancient: forces the conflict path
            txns.append(
                {
                    "op": "bind",
                    "bindings": [
                        [pod.namespace, pod.name, hostname]
                        for pod, hostname, _task, _seq in entries
                    ],
                    "snapshotVersion": version,
                }
            )
        pods = sum(len(e) for e in order)
        with obs.span(
            "txn.batch", parent=ctx, gangs=len(order), pods=pods
        ) as tspan:
            if faults.should_fire("store.txn_batch"):
                results = None
            else:
                try:
                    results = self.store.submit_txn(txns)
                except Exception as e:  # noqa: BLE001 - any batch failure degrades
                    log.errorf("coalesced txn batch failed (%s)", e)
                    results = None
            if results is None:
                tspan.set_attr("outcome", "degraded")
                log.errorf(
                    "degrading %d gang(s) to per-gang conditional writes",
                    len(order),
                )
                for entries in order:
                    self._do_bind_gang(entries, ctx)
                return
            conflicts = 0
            for entries, result in zip(order, results):
                if "conflict" not in result:
                    metrics.register_federation_conflict(
                        "clean", exemplar=tspan.trace_id
                    )
                    for _pod, _hostname, _task, seq in entries:
                        self._journal_confirm(seq)
                    continue
                conflicts += 1
                c = result["conflict"]
                what = f"gang <{entries[0][2].job}> ({len(entries)} pod(s))"
                for node in sorted(
                    {h for _p, h, _t, _s in entries}
                ):
                    metrics.register_federation_node_conflict(node)
                metrics.register_federation_conflict(
                    "retried", exemplar=tspan.trace_id
                )
                metrics.register_bind_retry()
                log.warningf(
                    "bind of %s conflicted in coalesced txn (%s %s: %s), "
                    "re-dispatching per-gang",
                    what, c.get("kind", ""), c.get("key", ""),
                    c.get("reason", "conflict"),
                )
                self._do_bind_gang(entries, ctx)
            tspan.set_attr("outcome", "ok")
            tspan.set_attr("conflicts", conflicts)

    def _do_bind_gang(self, entries: list, ctx=None) -> None:
        """Dispatch one gang as a conditional store transaction carrying
        the snapshot version (Omega optimistic concurrency). On
        StaleWrite the loser refreshes its version and retries with
        jittered backoff; past KBT_CONFLICT_MAX_RETRIES it accepts store
        truth — the journal intents are confirmed (the conflict resolved
        them: the winning placement stands) and the gang's tasks resync
        from the store, re-solving next cycle. This is reconcile_journal's
        takeover-time "store truth wins" rule applied per cycle.

        ``ctx`` is the dispatching cycle's span, captured before the
        kb-write pool hop (bind_many) — the gang.bind span parents to it
        so a conflict's whole retry story stays on one trace."""
        bindings = [
            (pod.namespace, pod.name, hostname)
            for pod, hostname, _task, _seq in entries
        ]
        version = self._snapshot_version
        if faults.should_fire("federation.stale_assign"):
            version = 0  # deliberately ancient: forces the conflict path
        what = f"gang <{entries[0][2].job}> ({len(entries)} pod(s))"
        delay = 0.02
        conflicts = 0
        with obs.span(
            "gang.bind", parent=ctx, gang=str(entries[0][2].job), pods=len(entries),
        ) as gspan:
            while True:
                try:
                    self._write_with_retry(
                        "bind",
                        what,
                        lambda v=version: self.binder.bind_many_versioned(bindings, v),
                    )
                    gspan.set_attr("outcome", "won" if conflicts else "clean")
                    gspan.set_attr("conflicts", conflicts)
                    metrics.register_federation_conflict(
                        "won" if conflicts else "clean",
                        exemplar=gspan.trace_id,
                    )
                    for _pod, _hostname, _task, seq in entries:
                        self._journal_confirm(seq)
                    return
                except StaleWrite as e:
                    conflicts += 1
                    # per-node conflict accounting: the fleet heatmap
                    # ranks contended nodes from deltas of this counter
                    for node in sorted({h for _ns, _n, h in bindings}):
                        metrics.register_federation_node_conflict(node)
                    if conflicts > self._conflict_max_retries:
                        gspan.set_attr("outcome", "lost")
                        gspan.set_attr("conflicts", conflicts)
                        metrics.register_federation_conflict(
                            "lost", exemplar=gspan.trace_id
                        )
                        log.errorf(
                            "bind of %s lost the conflict after %d retr%s (%s); "
                            "accepting store truth and resyncing the gang",
                            what, conflicts - 1, "y" if conflicts == 2 else "ies", e,
                        )
                        for _pod, _hostname, task, seq in entries:
                            self._journal_confirm(seq)
                            self.resync_task(task)
                        return
                    gspan.event("conflict", retry=conflicts, error=str(e))
                    metrics.register_federation_conflict(
                        "retried", exemplar=gspan.trace_id
                    )
                    metrics.register_bind_retry()
                    log.warningf(
                        "bind of %s conflicted (%s), retry %d/%d with fresh version",
                        what, e, conflicts, self._conflict_max_retries,
                    )
                    time.sleep(delay * (0.5 + random.random()))
                    delay = min(delay * 2.0, 0.5)
                    version = getattr(self.store, "version", version)
                except Exception as e:  # noqa: BLE001 - infrastructure failure
                    # unchanged rung 2: the intents stay unconfirmed, the
                    # resync path (or a takeover reconciliation) re-drives
                    gspan.set_attr("outcome", "error")
                    log.errorf("Failed to bind %s: %s", what, e)
                    for _pod, _hostname, task, _seq in entries:
                        self.resync_task(task)
                    return

    def _write_with_retry(self, op: str, what: str, fn) -> None:
        """Bounded in-place retry with exponential backoff + jitter for
        transient write-side failures, before the errTasks resync path
        takes over. The reference fires a goroutine per bind and routes
        any failure straight to resync (cache.go:439-448) — a full
        re-sync plus a whole scheduling cycle of latency for what is
        usually a blip; retrying the write first keeps the bind landing
        in this cycle (degradation-ladder rung 1), with resync as the
        unchanged rung 2. Fault points ``{bind,evict}.write`` (rejected
        write) and ``bind.slow`` (stalled binder) inject per attempt."""
        delay = 0.02
        attempt = 0
        while True:
            try:
                if op == "bind" and faults.should_fire("bind.slow"):
                    time.sleep(0.05)
                if faults.should_fire(f"{op}.write"):
                    raise faults.FaultInjected(f"{op}.write")
                fn()
                return
            except StaleWrite:
                # optimistic conflict, not a transient infrastructure
                # failure: re-sending the same snapshot version would
                # lose again — the caller refreshes the version first
                raise
            except Exception as e:
                attempt += 1
                if attempt > self._write_retries:
                    raise
                metrics.register_write_retry(op)
                log.warningf(
                    "%s of %s failed (attempt %d/%d), retrying: %s",
                    op, what, attempt, self._write_retries + 1, e,
                )
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, 0.5)

    def _do_bind(self, pod: Pod, hostname: str, task: TaskInfo, seq=None) -> None:
        try:
            self._write_with_retry(
                "bind",
                f"<{pod.namespace}/{pod.name}>",
                lambda: self.binder.bind(pod, hostname),
            )
            self._journal_confirm(seq)
        except Exception as e:  # noqa: BLE001 - any write failure resyncs
            # the journal intent stays unconfirmed: either the resync
            # path lands the write later or the next takeover's
            # reconciliation re-drives it (both idempotent)
            log.errorf("Failed to bind pod <%s/%s>: %s", pod.namespace, pod.name, e)
            self.resync_task(task)

    def evict(self, ti: TaskInfo, reason: str) -> None:
        """reference cache.go:369-401."""
        with self._mutex:
            job, task = self._find_job_and_task(ti)
            node = self.nodes.get(task.node_name)
            if node is None:
                raise KeyError(f"failed to evict task {task.uid}: host {task.node_name} missing")
            job.update_task_status(task, TaskStatus.RELEASING)
            node.update_task(task)
            pod = task.pod
        seqs = self._journal_intents(
            "evict", [(task.job, f"{pod.namespace}/{pod.name}", "")]
        )
        self._submit_write(self._do_evict, pod, task, seqs[0])

    def _do_evict(self, pod: Pod, task: TaskInfo, seq=None) -> None:
        conditional = self._conditional_binds and hasattr(
            self.evictor, "evict_versioned"
        )
        version = self._snapshot_version
        if conditional and faults.should_fire("federation.stale_assign"):
            version = 0
        try:
            if conditional:
                self._write_with_retry(
                    "evict",
                    f"<{pod.namespace}/{pod.name}>",
                    lambda: self.evictor.evict_versioned(pod, version),
                )
            else:
                self._write_with_retry(
                    "evict",
                    f"<{pod.namespace}/{pod.name}>",
                    lambda: self.evictor.evict(pod),
                )
            self._journal_confirm(seq)
        except StaleWrite as e:
            # an evict that lost the race is moot: whatever placement won
            # invalidated the preemption plan — accept store truth now
            # (no blind retry loop; the next cycle re-solves)
            metrics.register_federation_conflict("lost")
            log.errorf(
                "Evict of <%s/%s> lost the conflict (%s); accepting store truth",
                pod.namespace, pod.name, e,
            )
            self._journal_confirm(seq)
            self.resync_task(task)
        except Exception as e:  # noqa: BLE001
            log.errorf("Failed to evict pod <%s/%s>: %s", pod.namespace, pod.name, e)
            self.resync_task(task)

    def _submit_write(self, fn, *args) -> None:
        if self._writer is not None:
            self._writer.submit(fn, *args)
        else:
            fn(*args)  # run() not started (unit tests): write inline

    def submit_dispatch(self, fn):
        """Run a deferred post-solve dispatch closure on the kb-write
        pool, returning its Future (kube_batch_tpu.pipeline rides this
        for KBT_PIPELINE cycles). Unlike `_submit_write`, the caller
        needs the Future: the dispatch fence joins it before the next
        cycle's snapshot. With the pool off (run() not started), the
        closure runs inline and the returned Future is already done —
        the pipelined path degenerates to the synchronous one."""
        from concurrent.futures import Future

        if self._writer is not None:
            return self._writer.submit(fn)
        fut: Future = Future()
        try:
            fut.set_result(fn())
        except BaseException as e:  # noqa: BLE001 - carried by the future
            fut.set_exception(e)
        return fut

    # -- resync + GC workers (reference cache.go:480-534) ------------------

    def resync_task(self, task: TaskInfo) -> None:
        self._err_tasks.add_rate_limited(task)

    def _process_resync_task(self) -> None:
        task = self._err_tasks.get(timeout=0.2)
        if task is None:
            return
        try:
            self._sync_task(task)
            self._err_tasks.forget(task)
        except Exception as e:  # noqa: BLE001
            # Per-task retry budget: a permanently-rejected write (pod
            # poisoned, store rejecting the key forever) must not ride
            # the queue forever — after the budget it drops terminally,
            # metered and narrated; the task's pod stays whatever the
            # store says it is, which a later event or takeover
            # reconciliation can still repair.
            if self._err_tasks.failures(task) >= self._resync_max_retries:
                metrics.register_resync_drop()
                log.errorf(
                    "Giving up on resync of pod <%s/%s> after %d attempts "
                    "(terminal drop): %s",
                    task.namespace, task.name, self._resync_max_retries, e,
                )
                self._err_tasks.forget(task)
            else:
                log.errorf(
                    "Failed to sync pod <%s/%s>, retry: %s",
                    task.namespace, task.name, e,
                )
                self._err_tasks.add_rate_limited(task)
        finally:
            self._err_tasks.done(task)

    def _delete_job(self, job: JobInfo) -> None:
        log.V(3).infof("Try to delete job <%s>", job.uid)
        self._deleted_jobs.add_rate_limited(job)

    def _process_cleanup_job(self) -> None:
        job = self._deleted_jobs.get(timeout=0.2)
        if job is None:
            return
        try:
            with self._mutex:
                if job_terminated(job):
                    self.jobs.pop(job.uid, None)
                    self._deleted_jobs.forget(job)
                    log.V(3).infof("Job <%s> deleted from cache", job.uid)
                else:
                    self._deleted_jobs.add_rate_limited(job)
        finally:
            self._deleted_jobs.done(job)

    # -- snapshot (reference cache.go:535-585) -----------------------------

    def snapshot(self) -> ClusterInfo:
        reset = getattr(self.volume_binder, "reset", None)
        if reset is not None:
            reset()  # assumptions never outlive a session (see reset())
        with self._mutex:
            snapshot = ClusterInfo()
            # Stamp the store version this snapshot solves over — every
            # conditional dispatch until the next snapshot carries it.
            self._snapshot_version = getattr(self.store, "version", 0)
            for name, node in self.nodes.items():
                snapshot.nodes[name] = node.clone()
            for name, q in self.queues.items():
                snapshot.queues[name] = q.clone()
            for uid, job in self.jobs.items():
                if job.pod_group is None and job.pdb is None:
                    log.V(4).infof("Job <%s> has no scheduling spec, ignored", uid)
                    continue
                if job.queue not in snapshot.queues:
                    log.V(3).infof(
                        "Queue <%s> of job <%s/%s> does not exist, ignored",
                        job.queue, job.namespace, job.name,
                    )
                    continue
                if job.pod_group is not None:
                    job.priority = self._default_priority
                    pc = self.priority_classes.get(job.pod_group.spec.priority_class_name)
                    if pc is not None:
                        job.priority = pc.value
                snapshot.jobs[uid] = job.clone()
            log.V(3).infof(
                "Snapshot: %d jobs, %d queues, %d nodes",
                len(snapshot.jobs), len(snapshot.queues), len(snapshot.nodes),
            )
            return snapshot

    def clone_jobs_for_stream(
        self, job_keys
    ) -> tuple[dict[str, JobInfo], set[str]]:
        """Fresh clones of just the named jobs, with exactly snapshot()'s
        admission filters and priority resolution — the streaming
        micro-cycle's restricted job view (streaming.py). Returns
        ``(jobs, missing)``: keys the mirror does not track at all land
        in ``missing`` (the gang is gone — prune it from the backlog);
        jobs that merely fail an admission filter are omitted from both
        (not schedulable this micro-cycle; the full cycle decides)."""
        with self._mutex:
            out: dict[str, JobInfo] = {}
            missing: set[str] = set()
            for uid in job_keys:
                job = self.jobs.get(uid)
                if job is None:
                    missing.add(uid)
                    continue
                if job.pod_group is None and job.pdb is None:
                    continue
                if job.queue not in self.queues:
                    continue
                if job.pod_group is not None:
                    job.priority = self._default_priority
                    pc = self.priority_classes.get(job.pod_group.spec.priority_class_name)
                    if pc is not None:
                        job.priority = pc.value
                out[uid] = job.clone()
            return out, missing

    def clone_queues_for_stream(self) -> dict[str, QueueInfo]:
        """All queues, cloned under the mutex (snapshot()'s queue leg)."""
        with self._mutex:
            return {name: q.clone() for name, q in self.queues.items()}

    # -- status write-back (reference cache.go:621-666) --------------------

    def _task_unschedulable(self, task: TaskInfo, message: str) -> None:
        self.status_updater.update_pod_condition(
            task.pod,
            PodCondition(
                type="PodScheduled",
                status="False",
                reason="Unschedulable",
                message=message,
            ),
        )

    def record_job_status_event(self, job: JobInfo) -> None:
        job_err_msg = job.fit_error()
        for status in (TaskStatus.ALLOCATED, TaskStatus.PENDING):
            # list(): the condition write can re-enter as a pod update
            # event and re-index this very job when ``job`` is the live
            # mirror object rather than a snapshot clone.
            for task in list(job.task_status_index.get(status, {}).values()):
                try:
                    self._task_unschedulable(task, job_err_msg)
                except Exception as e:  # noqa: BLE001
                    log.errorf(
                        "Failed to update unschedulable task status <%s/%s>: %s",
                        task.namespace, task.name, e,
                    )

    def update_job_status(self, job: JobInfo) -> JobInfo:
        if not shadow_pod_group(job.pod_group):
            self.status_updater.update_pod_group(job.pod_group)
        self.record_job_status_event(job)
        return job

    # -- volume hooks ------------------------------------------------------

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)
