"""L2: the event-driven cluster cache (reference pkg/scheduler/cache)."""

from kube_batch_tpu.cache.backend import (
    BackendPartitioned,
    InProcessBackend,
    LoopbackBackend,
    StoreBackend,
)
from kube_batch_tpu.cache.cache import (
    NoopVolumeBinder,
    SchedulerCache,
    StoreBinder,
    StoreEvictor,
    StoreStatusUpdater,
    create_shadow_pod_group,
    job_terminated,
    shadow_pod_group,
)
from kube_batch_tpu.cache.store import (
    KINDS,
    NODES,
    PDBS,
    POD_GROUPS,
    PODS,
    PRIORITY_CLASSES,
    QUEUES,
    ClusterStore,
    EventHandler,
    StaleWrite,
)

__all__ = [
    "BackendPartitioned",
    "ClusterStore",
    "EventHandler",
    "InProcessBackend",
    "LoopbackBackend",
    "StaleWrite",
    "StoreBackend",
    "KINDS",
    "NODES",
    "NoopVolumeBinder",
    "PDBS",
    "POD_GROUPS",
    "PODS",
    "PRIORITY_CLASSES",
    "QUEUES",
    "SchedulerCache",
    "StoreBinder",
    "StoreEvictor",
    "StoreStatusUpdater",
    "create_shadow_pod_group",
    "job_terminated",
    "shadow_pod_group",
]
