"""Store backends: the ClusterStore surface behind an interface.

PR 10 tentpole (ISSUE.md): everything SchedulerCache needs from "the
cluster" is a narrow surface — subscribe (``add_event_handler`` with
initial replay), read (``get`` / ``list`` / ``get_pod``), write
(``update_pod`` / ``delete_pod`` / ``update_pod_group`` /
``update_persistent_volume`` / ``update_persistent_volume_claim``),
optimistic transactions (``conditional_bind_many`` /
``conditional_evict``) and the monotonic ``version`` those transactions
are checked against. This module names that surface (``StoreBackend``)
and provides both implementations:

- ``InProcessBackend``: the ClusterStore itself (zero behavior change —
  the single-process fast path every existing test runs on);
- ``LoopbackBackend``: the same surface over the scheduler server's
  ``/backend/v1/`` HTTP protocol — full-fidelity wire objects
  (apis/wire.py), list+watch with per-kind cursors and the 410-Gone
  re-list contract, and conditional writes whose 409 replies are
  reconstructed into the same typed ``StaleWrite`` the in-process store
  raises, so the cache's conflict dispatch is backend-agnostic.

Federation (federation.py) runs N schedulers, each over its own
LoopbackBackend against one shared store process: Omega-style shared
state with optimistic concurrency instead of pessimistic partitioning.

The mirror is pulled, not pushed: ``pump()`` executes one deterministic
poll pass over every subscribed kind (tests and the interleave explorer
call it explicitly; ``start()`` runs it on a background thread for real
deployments). Staleness is first-class — ``snapshot_age()`` reports
seconds since the last fully-successful pump, and the cache's
refuse-to-schedule guard (KBT_MAX_SNAPSHOT_AGE_S) consumes it via the
``staleness_fn`` hook.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional

from kube_batch_tpu import faults, log, metrics, obs
from kube_batch_tpu.apis import wire
from kube_batch_tpu.cache.store import (
    KINDS,
    NODES,
    PODS,
    PRIORITY_CLASSES,
    PVCS,
    PVS,
    POD_GROUPS,
    QUEUES,
    ClusterStore,
    EventHandler,
    StaleWrite,
    obj_key,
)

__all__ = [
    "StoreBackend",
    "InProcessBackend",
    "LoopbackBackend",
    "BackendPartitioned",
]


class BackendPartitioned(ConnectionError):
    """The store backend is unreachable (real transport failure or the
    ``federation.partition`` fault). Transient by contract: the cache's
    ``_write_with_retry`` retries it, the pump skips the round and lets
    ``snapshot_age`` grow until the partition heals."""


class StoreBackend:
    """The surface SchedulerCache (and its default write-side helpers)
    requires from a cluster store. Documentation-by-interface: both
    implementations duck-type it, nothing isinstance-checks it.

    Required:
      add_event_handler(kind, EventHandler)  # + initial-list replay
      get(kind, key) / list(kind) / get_pod(namespace, name)
      update_pod(pod) / delete_pod(namespace, name)
      update_pod_group(pg)
      update_persistent_volume(pv) / update_persistent_volume_claim(pvc)
      conditional_bind_many(bindings, snapshot_version) -> applied pods
      conditional_evict(namespace, name, snapshot_version)
      version  # monotonic store version (int property)
    """


class InProcessBackend(ClusterStore):
    """The in-process store IS the backend — the single-process fast
    path. A distinct class (rather than an alias) so deployments can
    name which backend they constructed in logs and bench rows."""


class LoopbackBackend:
    """StoreBackend over the scheduler server's ``/backend/v1/`` HTTP
    protocol (server.py). Reads come from a local mirror fed by
    full-fidelity list+watch; writes go over the wire; conditional
    writes re-raise the server's typed 409 as ``StaleWrite``."""

    def __init__(
        self,
        base_url: str,
        kinds: tuple = KINDS,
        timeout: float = 5.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.kinds = tuple(kinds)
        self.timeout = timeout
        self._lock = threading.RLock()
        self._mirror: dict[str, dict[str, Any]] = {k: {} for k in self.kinds}
        self._handlers: dict[str, list[EventHandler]] = {k: [] for k in self.kinds}
        # Per-kind watch cursor: the server's rv is a global sequence but
        # rings are per kind, so a cursor advanced by one kind's poll must
        # never be reused for another kind (it would skip that kind's
        # events below it).
        self._cursor: dict[str, int] = {k: 0 for k in self.kinds}
        self._synced: dict[str, bool] = {k: False for k in self.kinds}
        # Last storeVersion any reply carried: the `version` property's
        # fallback when the backend is partitioned (snapshot() must not
        # fail just because version couldn't be refreshed).
        self._store_version = 0
        self._last_pump_ok = time.monotonic()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- transport ---------------------------------------------------------

    def _request(self, op: str, method: str, path: str, body: Optional[dict] = None):
        """One metered round-trip. Raises BackendPartitioned on transport
        failure (injected or real), StaleWrite on a conflict 409."""
        if faults.should_fire("federation.partition"):
            raise BackendPartitioned(
                f"federation.partition: injected transport drop ({op})"
            )
        data = json.dumps(body).encode() if body is not None else None
        headers = {"Content-Type": "application/json"}
        # trace propagation (kube_batch_tpu.obs): the current span's ids
        # ride as headers so the store arbiter's server-side span joins
        # this scheduler's trace — a federated conflict's full retry
        # story renders as ONE trace across N processes
        headers.update(obs.current_headers())
        req = urllib.request.Request(
            f"{self.base_url}{path}",
            data=data,
            headers=headers,
            method=method,
        )
        start = time.perf_counter()
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read())
            except Exception:  # noqa: BLE001 - non-JSON error body
                payload = {}
            if e.code == 409 and "conflict" in payload:
                c = payload["conflict"]
                raise StaleWrite(
                    c.get("kind", ""),
                    c.get("key", ""),
                    c.get("reason", "conflict"),
                    int(c.get("expected", 0)),
                    int(c.get("actual", 0)),
                ) from None
            if e.code == 410:
                raise _Gone(int(payload.get("resourceVersion", 0))) from None
            raise BackendPartitioned(f"{op}: HTTP {e.code}") from e
        except OSError as e:  # connection refused/reset, timeout
            raise BackendPartitioned(f"{op}: {e}") from e
        finally:
            metrics.observe_store_backend_rtt(op, time.perf_counter() - start)
        if isinstance(payload, dict) and "storeVersion" in payload:
            with self._lock:
                self._store_version = max(
                    self._store_version, int(payload["storeVersion"])
                )
        return payload

    # -- subscribe ---------------------------------------------------------

    def add_event_handler(self, kind: str, handler: EventHandler) -> None:
        """Register + initial replay of the current mirror, matching the
        in-process store's informer contract. The first subscription of a
        kind lists it over the wire to seed the mirror."""
        with self._lock:
            synced = self._synced[kind]
        listing = None if synced else self._fetch_list(kind)
        with self._lock:
            if listing is not None and not self._synced[kind]:
                self._mirror[kind], self._cursor[kind] = listing
                self._synced[kind] = True
            self._handlers[kind].append(handler)
            replay = list(self._mirror[kind].values())
        for obj in replay:
            handler.add(obj)

    def _fetch_list(self, kind: str) -> tuple[dict, int]:
        """Blocking list over the wire — never called under _lock (the
        round trip can stall for the full transport timeout)."""
        payload = self._request("list", "GET", f"/backend/v1/{kind}")
        mirror = {
            obj_key(kind, obj): obj
            for obj in (wire.decode_kind(kind, d) for d in payload["items"])
        }
        # rv was read BEFORE the server listed: resuming the watch from it
        # re-delivers anything concurrent with the list (at-least-once);
        # redelivery is diffed against the mirror, so it degrades to a
        # no-op update, never a lost event.
        return mirror, int(payload["resourceVersion"])

    # -- pump (watch -> mirror -> handlers) --------------------------------

    def pump(self, timeout: float = 0.0) -> int:
        """One deterministic poll pass over every subscribed kind;
        returns the number of events dispatched. A partition skips the
        round (mirror stales, snapshot_age grows) instead of raising."""
        dispatched = 0
        try:
            for kind in self.kinds:
                with self._lock:
                    if not self._synced[kind]:
                        continue
                    since = self._cursor[kind]
                try:
                    payload = self._request(
                        "watch",
                        "GET",
                        f"/backend/v1/watch/{kind}?since={since}&timeout={timeout}",
                    )
                except _Gone:
                    # 410: our cursor fell out of the ring — re-list and
                    # synthesize the diff so handlers still see every
                    # transition exactly once from their point of view.
                    dispatched += self._relist(kind)
                    continue
                events = payload.get("events", [])
                batch: list[tuple] = []
                with self._lock:
                    for ev in events:
                        obj = wire.decode_kind(kind, ev["object"])
                        key = obj_key(kind, obj)
                        old = self._mirror[kind].get(key)
                        if ev["type"] == "DELETED":
                            if old is not None:
                                del self._mirror[kind][key]
                                batch.append(("delete", old, None))
                        elif old is None:
                            self._mirror[kind][key] = obj
                            batch.append(("add", None, obj))
                        else:
                            self._mirror[kind][key] = obj
                            batch.append(("update", old, obj))
                    self._cursor[kind] = int(payload["resourceVersion"])
                    handlers = list(self._handlers[kind])
                dispatched += self._dispatch(handlers, batch)
        except BackendPartitioned as e:
            log.V(3).infof("backend pump skipped: %s", e)
            return dispatched
        self._last_pump_ok = time.monotonic()
        return dispatched

    def _relist(self, kind: str) -> int:
        """410 heal: list, diff against the mirror, dispatch the delta."""
        after, rv = self._fetch_list(kind)
        with self._lock:
            before = dict(self._mirror[kind])
            self._mirror[kind] = after
            self._cursor[kind] = rv
            self._synced[kind] = True
            handlers = list(self._handlers[kind])
            batch: list[tuple] = []
            for key, obj in after.items():
                old = before.get(key)
                if old is None:
                    batch.append(("add", None, obj))
                elif old is not obj:
                    batch.append(("update", old, obj))
            for key, old in before.items():
                if key not in after:
                    batch.append(("delete", old, None))
        return self._dispatch(handlers, batch)

    @staticmethod
    def _dispatch(handlers: list[EventHandler], batch: list[tuple]) -> int:
        for verb, old, new in batch:
            for h in handlers:
                if verb == "add":
                    h.add(new)
                elif verb == "update":
                    h.update(old, new)
                else:
                    h.delete(old)
        return len(batch)

    def start(self, period: float = 0.2) -> None:
        """Background pump for real deployments (tests call pump())."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                self.pump(timeout=period)

        self._thread = threading.Thread(target=loop, name="kb-backend", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def snapshot_age(self) -> float:
        """Seconds since the last fully-successful pump — the
        staleness_fn the cache's refuse-to-schedule guard reads."""
        return max(0.0, time.monotonic() - self._last_pump_ok)

    # -- reads (mirror) ----------------------------------------------------

    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._mirror[kind].get(key)

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._mirror[kind].values())

    def get_pod(self, namespace: str, name: str):
        return self.get(PODS, f"{namespace}/{name}")

    # -- writes (wire) -----------------------------------------------------

    @property
    def version(self) -> int:
        """Current store version; last-seen fallback under partition so
        snapshot() keeps working while the transport heals (a stale
        version only makes this scheduler's next dispatch MORE likely to
        lose a conflict — safe by construction)."""
        try:
            payload = self._request("version", "GET", "/backend/v1/version")
            return int(payload["storeVersion"])
        except (BackendPartitioned, StaleWrite, KeyError, ValueError):
            with self._lock:
                return self._store_version

    def conditional_bind_many(
        self, bindings: list[tuple[str, str, str]], snapshot_version: int
    ) -> int:
        payload = self._request(
            "bind",
            "POST",
            "/backend/v1/bind",
            {"bindings": [list(b) for b in bindings],
             "snapshotVersion": snapshot_version},
        )
        return int(payload.get("applied", 0))

    def conditional_evict(self, namespace: str, name: str, snapshot_version: int):
        payload = self._request(
            "evict",
            "POST",
            "/backend/v1/evict",
            {"namespace": namespace, "name": name,
             "snapshotVersion": snapshot_version},
        )
        return payload.get("evicted")

    def _lease_verb(self, name: str, verb: str, body: dict) -> Any:
        """POST the arbiter's lease endpoint and reconstruct the Lease
        the store returned, so callers (ShardSlotManager, electors) see
        the same object shape from an HTTP arbiter as from an in-process
        ClusterStore. The name is percent-encoded whole (safe="") — a
        raw '/' would smear across path segments and arbitrate the
        wrong scope."""
        from kube_batch_tpu.apis.types import Lease, ObjectMeta

        quoted = urllib.parse.quote(name, safe="")
        payload = self._request(
            f"lease.{verb}", "POST", f"/apis/v1alpha1/leases/{quoted}/{verb}", body
        )
        return Lease(
            metadata=ObjectMeta(name=payload.get("name", name)),
            holder_identity=str(payload.get("holder", "")),
            lease_duration_seconds=float(payload.get("lease_duration", 0.0)),
            renew_time=float(payload.get("renew_time", 0.0)),
            lease_transitions=int(payload.get("transitions", 0)),
        )

    def try_acquire_lease(
        self, name: str, identity: str, lease_duration: float = 15.0
    ) -> Any:
        """Acquire-or-renew through the arbiter (store.py semantics, the
        arbiter's clock). Raises BackendPartitioned on transport failure
        — the caller treats that as 'did not acquire this round'."""
        return self._lease_verb(
            name, "acquire", {"identity": identity, "lease_duration": lease_duration}
        )

    def release_lease(self, name: str, identity: str) -> Any:
        return self._lease_verb(name, "release", {"identity": identity})

    def _crud(self, kind: str, verb: str, obj=None, key: Optional[str] = None) -> None:
        body: dict[str, Any] = {"verb": verb}
        if obj is not None:
            body["object"] = wire.encode_kind(kind, obj)
        if key is not None:
            body["key"] = key
        self._request(f"{verb}.{kind}", "POST", f"/backend/v1/{kind}", body)

    def create(self, kind: str, obj) -> Any:
        self._crud(kind, "create", obj)
        return obj

    def update(self, kind: str, obj) -> Any:
        self._crud(kind, "update", obj)
        return obj

    def delete(self, kind: str, key: str) -> None:
        self._crud(kind, "delete", key=key)

    def update_pod(self, pod) -> Any:
        return self.update(PODS, pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.delete(PODS, f"{namespace}/{name}")

    def create_pod(self, pod) -> Any:
        return self.create(PODS, pod)

    def update_pod_group(self, pg) -> Any:
        return self.update(POD_GROUPS, pg)

    def update_persistent_volume(self, pv) -> Any:
        return self.update(PVS, pv)

    def update_persistent_volume_claim(self, pvc) -> Any:
        return self.update(PVCS, pvc)

    # The typed conveniences the server's workload API handler calls, so
    # a federated scheduler's own HTTP endpoint proxies mutations through
    # to the store process instead of 500ing on a missing method.

    def create_queue(self, q) -> Any:
        return self.create(QUEUES, q)

    def delete_queue(self, name: str) -> None:
        self.delete(QUEUES, name)

    def create_node(self, n) -> Any:
        return self.create(NODES, n)

    def delete_node(self, name: str) -> None:
        self.delete(NODES, name)

    def create_pod_group(self, pg) -> Any:
        return self.create(POD_GROUPS, pg)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self.delete(POD_GROUPS, f"{namespace}/{name}")

    def create_priority_class(self, pc) -> Any:
        return self.create(PRIORITY_CLASSES, pc)

    def delete_priority_class(self, name: str) -> None:
        self.delete(PRIORITY_CLASSES, name)

    def create_persistent_volume(self, pv) -> Any:
        return self.create(PVS, pv)

    def delete_persistent_volume(self, name: str) -> None:
        self.delete(PVS, name)

    def create_persistent_volume_claim(self, pvc) -> Any:
        return self.create(PVCS, pvc)

    def delete_persistent_volume_claim(self, namespace: str, name: str) -> None:
        self.delete(PVCS, f"{namespace}/{name}")


class _Gone(Exception):
    """Internal: the watch cursor fell behind the server ring (410)."""

    def __init__(self, rv: int) -> None:
        super().__init__(f"410 gone (rv {rv})")
        self.rv = rv
