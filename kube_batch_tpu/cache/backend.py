"""Store backends: the ClusterStore surface behind an interface.

PR 10 tentpole (ISSUE.md): everything SchedulerCache needs from "the
cluster" is a narrow surface — subscribe (``add_event_handler`` with
initial replay), read (``get`` / ``list`` / ``get_pod``), write
(``update_pod`` / ``delete_pod`` / ``update_pod_group`` /
``update_persistent_volume`` / ``update_persistent_volume_claim``),
optimistic transactions (``conditional_bind_many`` /
``conditional_evict``) and the monotonic ``version`` those transactions
are checked against. This module names that surface (``StoreBackend``)
and provides both implementations:

- ``InProcessBackend``: the ClusterStore itself (zero behavior change —
  the single-process fast path every existing test runs on);
- ``LoopbackBackend``: the same surface over the scheduler server's
  ``/backend/v1/`` HTTP protocol — full-fidelity wire objects
  (apis/wire.py), list+watch with per-kind cursors and the 410-Gone
  re-list contract, and conditional writes whose 409 replies are
  reconstructed into the same typed ``StaleWrite`` the in-process store
  raises, so the cache's conflict dispatch is backend-agnostic.

Federation (federation.py) runs N schedulers, each over its own
LoopbackBackend against one shared store process: Omega-style shared
state with optimistic concurrency instead of pessimistic partitioning.

The mirror is pulled, not pushed: ``pump()`` executes one deterministic
poll pass over every subscribed kind (tests and the interleave explorer
call it explicitly; ``start()`` runs it on a background thread for real
deployments). Staleness is first-class — ``snapshot_age()`` reports
seconds since the last fully-successful pump, and the cache's
refuse-to-schedule guard (KBT_MAX_SNAPSHOT_AGE_S) consumes it via the
``staleness_fn`` hook.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Optional

from kube_batch_tpu import faults, log, metrics, obs
from kube_batch_tpu.apis import wire
from kube_batch_tpu.cache.store import (
    KINDS,
    NODES,
    PODS,
    PRIORITY_CLASSES,
    PVCS,
    PVS,
    POD_GROUPS,
    QUEUES,
    ClusterStore,
    EventHandler,
    StaleWrite,
    obj_key,
)

__all__ = [
    "StoreBackend",
    "InProcessBackend",
    "LoopbackBackend",
    "BackendPartitioned",
]


class BackendPartitioned(ConnectionError):
    """The store backend is unreachable (real transport failure or the
    ``federation.partition`` fault). Transient by contract: the cache's
    ``_write_with_retry`` retries it, the pump skips the round and lets
    ``snapshot_age`` grow until the partition heals."""


# Keep-alive pool size per backend (wire protocol v2). One connection
# serves the pump; the rest absorb concurrent write-side dispatches.
POOL_ENV = "KBT_BACKEND_POOL"
# Client codec preference (negotiated down to what the server offers).
CODEC_ENV = "KBT_WIRE_CODEC"


def _pool_size() -> int:
    try:
        return max(1, int(os.environ.get(POOL_ENV, "") or 4))
    except ValueError:
        log.errorf("%s=%r is not an integer; using 4", POOL_ENV, os.environ.get(POOL_ENV))
        return 4


class _ConnectionPool:
    """Bounded keep-alive ``http.client`` connection pool — the v2
    transport. Checkout is health-checked (a connection whose socket
    died idle is discarded, never handed out); a request that fails on
    a REUSED connection is the keep-alive race (the server closed the
    socket between our requests) and is retried once on a fresh
    connection for idempotent GETs only — POSTs surface the failure to
    the caller's retry ladder, which is conflict-safe by versioning."""

    def __init__(self, host: str, port: int, size: int, timeout: float) -> None:
        self._host, self._port = host, port
        self._size = size
        self._timeout = timeout
        self._lock = threading.Lock()
        self._idle: list[http.client.HTTPConnection] = []
        self._in_use = 0

    def acquire(self) -> tuple[http.client.HTTPConnection, bool]:
        """-> (connection, reused). Dead idle sockets are discarded."""
        conn = None
        with self._lock:
            while self._idle:
                c = self._idle.pop()
                if c.sock is not None:
                    conn = c
                    break
                c.close()
            self._in_use += 1
            in_use = self._in_use
        metrics.set_backend_pool_in_use(in_use)
        if conn is not None:
            return conn, True
        fresh = http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout
        )
        fresh.connect()
        # TCP_NODELAY: without it, the second request on a kept-alive
        # connection sits out Nagle vs delayed-ACK (~40ms) — more than
        # the whole round trip this pool exists to amortize.
        fresh.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return fresh, False

    def release(self, conn: http.client.HTTPConnection, discard: bool = False) -> None:
        with self._lock:
            self._in_use = max(0, self._in_use - 1)
            in_use = self._in_use
            if not discard and conn.sock is not None and len(self._idle) < self._size:
                self._idle.append(conn)
                conn = None  # type: ignore[assignment]
        metrics.set_backend_pool_in_use(in_use)
        if conn is not None:
            conn.close()

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
        for c in idle:
            c.close()


class StoreBackend:
    """The surface SchedulerCache (and its default write-side helpers)
    requires from a cluster store. Documentation-by-interface: both
    implementations duck-type it, nothing isinstance-checks it.

    Required:
      add_event_handler(kind, EventHandler)  # + initial-list replay
      get(kind, key) / list(kind) / get_pod(namespace, name)
      update_pod(pod) / delete_pod(namespace, name)
      update_pod_group(pg)
      update_persistent_volume(pv) / update_persistent_volume_claim(pvc)
      conditional_bind_many(bindings, snapshot_version) -> applied pods
      conditional_evict(namespace, name, snapshot_version)
      version  # monotonic store version (int property)
    """


class InProcessBackend(ClusterStore):
    """The in-process store IS the backend — the single-process fast
    path. A distinct class (rather than an alias) so deployments can
    name which backend they constructed in logs and bench rows."""


class LoopbackBackend:
    """StoreBackend over the scheduler server's ``/backend/v1/`` HTTP
    protocol (server.py). Reads come from a local mirror fed by
    full-fidelity list+watch; writes go over the wire; conditional
    writes re-raise the server's typed 409 as ``StaleWrite``."""

    def __init__(
        self,
        base_url: str,
        kinds: tuple = KINDS,
        timeout: float = 5.0,
        protocol: Optional[int] = None,
        codec: Optional[str] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.kinds = tuple(kinds)
        self.timeout = timeout
        # Wire protocol v2 negotiation. `protocol`/`codec` cap what this
        # client will ASK for; what it actually RUNS is the min with what
        # the server's /version advertises — a v1-only arbiter answers
        # with a bare storeVersion and that reply IS the downgrade signal.
        self._protocol_pref = int(protocol) if protocol else 2
        self._codec_pref = codec or os.environ.get(CODEC_ENV, "") or "binary"
        if self._codec_pref not in wire.CODECS:
            log.errorf(
                "%s=%r is not one of %s; using json",
                CODEC_ENV, self._codec_pref, "/".join(wire.CODECS),
            )
            self._codec_pref = "json"
        self._protocol: Optional[int] = None  #: guarded_by _lock (None = not yet negotiated)
        self._codec = "json"  #: guarded_by _lock
        self._features: frozenset[str] = frozenset()  #: guarded_by _lock
        # Any partition (real or injected) forces renegotiation on the
        # next request: the peer we reconnect to after a partition may be
        # a different (older or newer) server build.
        self._needs_negotiation = True  #: guarded_by _lock
        parsed = urllib.parse.urlsplit(self.base_url)
        self._pool = _ConnectionPool(
            parsed.hostname or "localhost",
            parsed.port or 80,
            _pool_size(),
            timeout,
        )
        # Cumulative protocol bytes (tx/rx) for bench rows; the metric
        # family store_backend_bytes_total is process-global, these are
        # per-backend so a bench can report wire_bytes_per_bind per row.
        self.bytes_tx = 0  #: guarded_by _lock
        self.bytes_rx = 0  #: guarded_by _lock
        self._lock = threading.RLock()
        self._mirror: dict[str, dict[str, Any]] = {k: {} for k in self.kinds}  #: guarded_by _lock
        self._handlers: dict[str, list[EventHandler]] = {k: [] for k in self.kinds}
        # Per-kind watch cursor: the server's rv is a global sequence but
        # rings are per kind, so a cursor advanced by one kind's poll must
        # never be reused for another kind (it would skip that kind's
        # events below it).
        self._cursor: dict[str, int] = {k: 0 for k in self.kinds}  #: guarded_by _lock
        self._synced: dict[str, bool] = {k: False for k in self.kinds}  #: guarded_by _lock
        # Last storeVersion any reply carried: the `version` property's
        # fallback when the backend is partitioned (snapshot() must not
        # fail just because version couldn't be refreshed).
        self._store_version = 0  #: guarded_by _lock
        self._last_pump_ok = time.monotonic()  #: guarded_by _lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- transport ---------------------------------------------------------

    def _send_urllib(
        self, method: str, path: str, data: Optional[bytes], headers: dict
    ) -> tuple[int, str, bytes]:
        """v1 transport: one urllib round trip per op (pre-v2 semantics,
        byte-for-byte). OSError propagates — the caller maps it."""
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=data, headers=headers, method=method
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.headers.get("Content-Type", ""), resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get("Content-Type", ""), e.read()

    def _send_pooled(
        self, method: str, path: str, data: Optional[bytes], headers: dict
    ) -> tuple[int, str, bytes]:
        """v2 transport: keep-alive round trip on a pooled connection.
        A failure on a REUSED connection is the keep-alive race (server
        closed the socket between our requests): retried once on a fresh
        connection for idempotent GETs only — a POST replayed blind could
        double-apply a conditional write, so POSTs surface the failure to
        the version-checked retry ladder instead."""
        retried = False
        while True:
            conn, reused = self._pool.acquire()
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                ctype = resp.headers.get("Content-Type", "")
                status, will_close = resp.status, resp.will_close
            except (http.client.HTTPException, OSError):
                self._pool.release(conn, discard=True)
                if reused and method == "GET" and not retried:
                    retried = True
                    continue
                raise
            self._pool.release(conn, discard=will_close)
            return status, ctype, raw

    def _negotiate(self) -> None:
        """Settle protocol/codec/features from GET /backend/v1/version.
        Always plain-JSON urllib (no pooled transport, no codec
        assumptions — this must work against any server generation). A
        v1 server's bare ``{"storeVersion": N}`` reply IS the downgrade
        signal; no extra round trip, no error path."""
        status, _, raw = self._send_urllib(
            "GET", "/backend/v1/version", None, {"Accept": wire.JSON_CONTENT_TYPE}
        )
        if status != 200:
            raise BackendPartitioned(f"negotiate: HTTP {status}")
        payload = json.loads(raw)
        proto = min(self._protocol_pref, int(payload.get("protocol", 1)))
        offered = payload.get("codecs", ["json"]) if proto >= 2 else ["json"]
        codec = (
            "binary"
            if proto >= 2 and self._codec_pref == "binary" and "binary" in offered
            else "json"
        )
        features = frozenset(payload.get("features", ())) if proto >= 2 else frozenset()
        with self._lock:
            changed = (proto, codec) != (self._protocol, self._codec)
            self._protocol, self._codec, self._features = proto, codec, features
            self._needs_negotiation = False
            if "storeVersion" in payload:
                self._store_version = max(
                    self._store_version, int(payload["storeVersion"])
                )
        if changed:
            log.infof(
                "store backend %s negotiated protocol v%d codec=%s features=%s",
                self.base_url, proto, codec, ",".join(sorted(features)) or "-",
            )

    def _mark_renegotiate(self) -> None:
        """The peer we talk to next may be a different server generation
        (partition heal, arbiter restart, rolling upgrade) — re-run
        version negotiation before the next request."""
        with self._lock:
            self._needs_negotiation = True

    def _request(
        self,
        op: str,
        method: str,
        path: str,
        body: Optional[dict] = None,
        not_found_ok: bool = False,
    ):
        """One metered round-trip over the negotiated transport. Raises
        BackendPartitioned on transport failure (injected or real),
        StaleWrite on a conflict 409, _Unsupported on a 404 the caller
        opted into (v2-only route against a v1 server)."""
        if faults.should_fire("federation.partition"):
            self._mark_renegotiate()
            raise BackendPartitioned(
                f"federation.partition: injected transport drop ({op})"
            )
        with self._lock:
            negotiate = self._protocol is None or self._needs_negotiation
        if negotiate:
            try:
                self._negotiate()
            except OSError as e:
                raise BackendPartitioned(f"{op}: negotiate: {e}") from e
        with self._lock:
            proto, codec = self._protocol or 1, self._codec
        if body is not None:
            if codec == "binary":
                data = wire.dumps_binary(body)
                req_ctype = wire.BINARY_CONTENT_TYPE
            else:
                data = json.dumps(body).encode()
                req_ctype = wire.JSON_CONTENT_TYPE
        else:
            data, req_ctype = None, wire.JSON_CONTENT_TYPE
        headers = {"Content-Type": req_ctype}
        if proto >= 2:
            headers["Accept"] = (
                wire.BINARY_CONTENT_TYPE if codec == "binary"
                else wire.JSON_CONTENT_TYPE
            )
        # trace propagation (kube_batch_tpu.obs): the current span's ids
        # ride as headers so the store arbiter's server-side span joins
        # this scheduler's trace — a federated conflict's full retry
        # story renders as ONE trace across N processes
        headers.update(obs.current_headers())
        start = time.perf_counter()
        try:
            send = self._send_pooled if proto >= 2 else self._send_urllib
            status, resp_ctype, raw = send(method, path, data, headers)
        except OSError as e:  # connection refused/reset, timeout
            self._mark_renegotiate()
            raise BackendPartitioned(f"{op}: {e}") from e
        finally:
            metrics.observe_store_backend_rtt(op, time.perf_counter() - start)
        rx_codec = (
            "binary" if wire.BINARY_CONTENT_TYPE in (resp_ctype or "") else "json"
        )
        if data is not None:
            metrics.register_store_backend_bytes(
                "tx", "binary" if req_ctype == wire.BINARY_CONTENT_TYPE else "json",
                len(data),
            )
        metrics.register_store_backend_bytes("rx", rx_codec, len(raw))
        with self._lock:
            self.bytes_tx += len(data) if data is not None else 0
            self.bytes_rx += len(raw)
        try:
            if rx_codec == "binary":
                payload = wire.loads_binary(raw)
            else:
                payload = json.loads(raw) if raw else {}
        except ValueError as e:
            if status == 200:
                self._mark_renegotiate()
                raise BackendPartitioned(f"{op}: undecodable reply: {e}") from e
            payload = {}
        if status == 409 and isinstance(payload, dict) and "conflict" in payload:
            c = payload["conflict"]
            raise StaleWrite(
                c.get("kind", ""),
                c.get("key", ""),
                c.get("reason", "conflict"),
                int(c.get("expected", 0)),
                int(c.get("actual", 0)),
            )
        if status == 410:
            raise _Gone(int(payload.get("resourceVersion", 0)))
        if status == 404 and not_found_ok:
            raise _Unsupported(path)
        if status >= 400:
            self._mark_renegotiate()
            raise BackendPartitioned(f"{op}: HTTP {status}")
        if isinstance(payload, dict) and "storeVersion" in payload:
            with self._lock:
                self._store_version = max(
                    self._store_version, int(payload["storeVersion"])
                )
        return payload

    # -- subscribe ---------------------------------------------------------

    def add_event_handler(self, kind: str, handler: EventHandler) -> None:
        """Register + initial replay of the current mirror, matching the
        in-process store's informer contract. The first subscription of a
        kind lists it over the wire to seed the mirror."""
        with self._lock:
            synced = self._synced[kind]
        listing = None if synced else self._fetch_list(kind)
        with self._lock:
            if listing is not None and not self._synced[kind]:
                self._mirror[kind], self._cursor[kind] = listing
                self._synced[kind] = True
            self._handlers[kind].append(handler)
            replay = list(self._mirror[kind].values())
        for obj in replay:
            handler.add(obj)

    def _fetch_list(self, kind: str) -> tuple[dict, int]:
        """Blocking list over the wire — never called under _lock (the
        round trip can stall for the full transport timeout)."""
        payload = self._request("list", "GET", f"/backend/v1/{kind}")
        mirror = {
            obj_key(kind, obj): obj
            for obj in (wire.decode_kind(kind, d) for d in payload["items"])
        }
        # rv was read BEFORE the server listed: resuming the watch from it
        # re-delivers anything concurrent with the list (at-least-once);
        # redelivery is diffed against the mirror, so it degrades to a
        # no-op update, never a lost event.
        return mirror, int(payload["resourceVersion"])

    # -- pump (watch -> mirror -> handlers) --------------------------------

    def pump(self, timeout: float = 0.0) -> int:
        """One deterministic poll pass over every subscribed kind;
        returns the number of events dispatched. Under negotiated
        protocol v2 this is a single combined long-poll (watchall) whose
        MODIFIED events arrive as field-level deltas; under v1 it is the
        original per-kind cursor poll. A partition skips the round
        (mirror stales, snapshot_age grows) instead of raising."""
        if faults.should_fire("stream.pump"):
            # injected pump drop (streaming-federation drills): the round
            # is skipped whole — no partial event batch — so the mirror
            # simply ages and the staleness guard / backstop full cycle
            # own the degradation, exactly as for a real partition
            log.V(3).infof("stream.pump: injected watch-pump drop")
            self._stop.wait(0.02)  # keep an armed drill from spinning hot
            return 0
        with self._lock:
            use_v2 = (
                self._protocol is not None
                and not self._needs_negotiation
                and self._protocol >= 2
                and "longpoll" in self._features
            )
        if use_v2:
            try:
                return self._pump_v2(timeout)
            except _Unsupported:
                # Mid-run downgrade: the arbiter we reconnected to after a
                # partition is v1-only. Renegotiate, fall back this round.
                self._mark_renegotiate()
        return self._pump_v1(timeout)

    def _apply_events(self, kind: str, events: list[dict]) -> int:
        """Decode wire payloads OUTSIDE the mirror lock — a fat gang's
        payload decode under ``_lock`` would stall every concurrent
        mirror read (snapshot, conflict resync) for the duration — then
        apply the prepared batch under it. Delta events (v2) patch the
        mirror object in place; a delta for a key the mirror doesn't
        hold means its ADDED was missed — heal by re-list."""
        prepared: list[tuple] = []
        for ev in events:
            if "delta" in ev:
                prepared.append(("patch", ev["delta"]))
            elif ev["type"] == "DELETED" and "object" not in ev:
                prepared.append(("delkey", ev["key"]))
            else:
                prepared.append((ev["type"], wire.decode_kind(kind, ev["object"])))
        need_relist = False
        batch: list[tuple] = []
        with self._lock:
            mirror = self._mirror[kind]
            for verb, arg in prepared:
                if verb == "patch":
                    key = arg["key"]
                    old = mirror.get(key)
                    if old is None:
                        need_relist = True
                        continue
                    new = wire.apply_delta(kind, old, arg)
                    mirror[key] = new
                    batch.append(("update", old, new))
                elif verb == "delkey" or verb == "DELETED":
                    key = arg if verb == "delkey" else obj_key(kind, arg)
                    old = mirror.pop(key, None)
                    if old is not None:
                        batch.append(("delete", old, None))
                else:
                    obj = arg
                    key = obj_key(kind, obj)
                    old = mirror.get(key)
                    mirror[key] = obj
                    batch.append(
                        ("add", None, obj) if old is None else ("update", old, obj)
                    )
            handlers = list(self._handlers[kind])
        dispatched = self._dispatch(handlers, batch)
        if need_relist:
            dispatched += self._relist(kind)
        return dispatched

    def _pump_v1(self, timeout: float = 0.0) -> int:
        """Per-kind cursor poll — the pre-v2 pass, byte-for-byte on the
        wire (full objects, one request per kind)."""
        dispatched = 0
        try:
            for kind in self.kinds:
                with self._lock:
                    if not self._synced[kind]:
                        continue
                    since = self._cursor[kind]
                try:
                    payload = self._request(
                        "watch",
                        "GET",
                        f"/backend/v1/watch/{kind}?since={since}&timeout={timeout}",
                    )
                except _Gone:
                    # 410: our cursor fell out of the ring — re-list and
                    # synthesize the diff so handlers still see every
                    # transition exactly once from their point of view.
                    dispatched += self._relist(kind)
                    continue
                dispatched += self._apply_events(kind, payload.get("events", []))
                with self._lock:
                    # absolute server-issued rv; only the pump thread
                    # advances cursors between list re-seeds
                    self._cursor[kind] = int(payload["resourceVersion"])  # noqa: KBT-T003
        except BackendPartitioned as e:
            log.V(3).infof("backend pump skipped: %s", e)
            return dispatched
        with self._lock:
            self._last_pump_ok = time.monotonic()
        return dispatched

    def _pump_v2(self, timeout: float = 0.0) -> int:
        """One combined long-poll over every synced kind: the server
        parks the request until ANY kind has events past its cursor, so
        an idle federation costs one parked request per window instead
        of len(kinds) polls per period. Raises _Unsupported on 404 (v1
        server behind this URL now) for pump() to downgrade."""
        with self._lock:
            cursors = {k: self._cursor[k] for k in self.kinds if self._synced[k]}
            delta = "delta" in self._features
        if not cursors:
            return 0
        qs = ",".join(f"{k}:{since}" for k, since in cursors.items())
        path = f"/backend/v1/watchall?cursors={qs}&timeout={timeout}"
        if delta:
            path += "&delta=1"
        dispatched = 0
        try:
            payload = self._request("watch", "GET", path, not_found_ok=True)
            rv = int(payload["resourceVersion"])
            for kind, res in payload.get("kinds", {}).items():
                if kind not in self.kinds:  # mirror keys == kinds, fixed at init
                    continue
                if res.get("status") == "gone":
                    dispatched += self._relist(kind)
                    continue
                dispatched += self._apply_events(kind, res.get("events", []))
                # rv was read under the same hub lock that collected
                # every kind's events — safe to advance all polled
                # cursors to it in one go.
                with self._lock:
                    self._cursor[kind] = rv  # noqa: KBT-T003 (absolute server rv)
        except BackendPartitioned as e:
            log.V(3).infof("backend pump skipped: %s", e)
            return dispatched
        with self._lock:
            self._last_pump_ok = time.monotonic()
        return dispatched

    def _relist(self, kind: str) -> int:
        """410 heal: list, diff against the mirror, dispatch the delta."""
        after, rv = self._fetch_list(kind)
        with self._lock:
            before = dict(self._mirror[kind])
            self._mirror[kind] = after
            self._cursor[kind] = rv
            self._synced[kind] = True
            handlers = list(self._handlers[kind])
            batch: list[tuple] = []
            for key, obj in after.items():
                old = before.get(key)
                if old is None:
                    batch.append(("add", None, obj))
                elif old is not obj:
                    batch.append(("update", old, obj))
            for key, old in before.items():
                if key not in after:
                    batch.append(("delete", old, None))
        return self._dispatch(handlers, batch)

    @staticmethod
    def _dispatch(handlers: list[EventHandler], batch: list[tuple]) -> int:
        for verb, old, new in batch:
            for h in handlers:
                # A handler raising must not kill the pump thread: the
                # pump is shared infrastructure, and one bad object
                # stalling EVERY kind's watch silently is the worst
                # failure mode a shard has. Log and keep pumping — the
                # mirror itself is already updated, so a later relist or
                # event for the same key re-converges the handler state.
                try:
                    if verb == "add":
                        h.add(new)
                    elif verb == "update":
                        h.update(old, new)
                    else:
                        h.delete(old)
                except Exception as e:  # noqa: BLE001 — pump survival
                    log.errorf(
                        "watch handler %s failed (%s): %s", verb,
                        type(e).__name__, e,
                    )
        return len(batch)

    def start(self, period: float = 0.2) -> None:
        """Background pump for real deployments (tests call pump())."""
        if self._thread is not None:
            return
        self._stop.clear()
        # v2 long-poll window: park on the server as long as possible
        # while staying safely under the transport read timeout (or
        # urlopen/pool would kill an intentionally-parked request).
        longpoll = max(period, min(10.0, max(0.5, self.timeout - 1.0)))

        def loop() -> None:
            while not self._stop.is_set():
                with self._lock:
                    parked = (
                        self._protocol is not None
                        and self._protocol >= 2
                        and "longpoll" in self._features
                    )
                self.pump(timeout=longpoll if parked else period)

        self._thread = threading.Thread(target=loop, name="kb-backend", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._pool.close()

    def snapshot_age(self) -> float:
        """Seconds since the last fully-successful pump — the
        staleness_fn the cache's refuse-to-schedule guard reads."""
        with self._lock:
            return max(0.0, time.monotonic() - self._last_pump_ok)

    # -- reads (mirror) ----------------------------------------------------

    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._mirror[kind].get(key)

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._mirror[kind].values())

    def get_pod(self, namespace: str, name: str):
        return self.get(PODS, f"{namespace}/{name}")

    # -- writes (wire) -----------------------------------------------------

    @property
    def version(self) -> int:
        """Current store version; last-seen fallback under partition so
        snapshot() keeps working while the transport heals (a stale
        version only makes this scheduler's next dispatch MORE likely to
        lose a conflict — safe by construction)."""
        try:
            payload = self._request("version", "GET", "/backend/v1/version")
            return int(payload["storeVersion"])
        except (BackendPartitioned, StaleWrite, KeyError, ValueError):
            with self._lock:
                return self._store_version

    def conditional_bind_many(
        self, bindings: list[tuple[str, str, str]], snapshot_version: int
    ) -> int:
        payload = self._request(
            "bind",
            "POST",
            "/backend/v1/bind",
            {"bindings": [list(b) for b in bindings],
             "snapshotVersion": snapshot_version},
        )
        return int(payload.get("applied", 0))

    def conditional_evict(self, namespace: str, name: str, snapshot_version: int):
        payload = self._request(
            "evict",
            "POST",
            "/backend/v1/evict",
            {"namespace": namespace, "name": name,
             "snapshotVersion": snapshot_version},
        )
        return payload.get("evicted")

    # -- coalesced conditional txns (wire protocol v2) ---------------------

    def supports_txn(self) -> bool:
        """True when the negotiated protocol carries /backend/v1/txn.
        False before first contact or after a partition — the cache
        falls back to per-gang writes until negotiation settles."""
        with self._lock:
            return (
                self._protocol is not None
                and not self._needs_negotiation
                and self._protocol >= 2
                and "txn" in self._features
            )

    def submit_txn(self, txns: list[dict]) -> list[dict]:
        """Batch of conditional txns in ONE round trip; returns per-txn
        results (``{"applied": N}`` | ``{"evicted": bool}`` |
        ``{"conflict": {...}}``) in submission order. A 404 means the
        server downgraded mid-run: renegotiate and surface a partition
        so the caller degrades to per-gang v1 writes."""
        try:
            payload = self._request(
                "txn", "POST", "/backend/v1/txn", {"txns": txns}, not_found_ok=True
            )
        except _Unsupported:
            self._mark_renegotiate()
            raise BackendPartitioned(
                "txn: endpoint gone (server downgraded?); renegotiating"
            ) from None
        results = payload.get("results", [])
        if len(results) != len(txns):
            raise BackendPartitioned(
                f"txn: {len(results)} results for {len(txns)} txns"
            )
        return results

    def _lease_verb(self, name: str, verb: str, body: dict) -> Any:
        """POST the arbiter's lease endpoint and reconstruct the Lease
        the store returned, so callers (ShardSlotManager, electors) see
        the same object shape from an HTTP arbiter as from an in-process
        ClusterStore. The name is percent-encoded whole (safe="") — a
        raw '/' would smear across path segments and arbitrate the
        wrong scope."""
        from kube_batch_tpu.apis.types import Lease, ObjectMeta

        quoted = urllib.parse.quote(name, safe="")
        payload = self._request(
            f"lease.{verb}", "POST", f"/apis/v1alpha1/leases/{quoted}/{verb}", body
        )
        return Lease(
            metadata=ObjectMeta(name=payload.get("name", name)),
            holder_identity=str(payload.get("holder", "")),
            lease_duration_seconds=float(payload.get("lease_duration", 0.0)),
            renew_time=float(payload.get("renew_time", 0.0)),
            lease_transitions=int(payload.get("transitions", 0)),
        )

    def try_acquire_lease(
        self, name: str, identity: str, lease_duration: float = 15.0
    ) -> Any:
        """Acquire-or-renew through the arbiter (store.py semantics, the
        arbiter's clock). Raises BackendPartitioned on transport failure
        — the caller treats that as 'did not acquire this round'."""
        return self._lease_verb(
            name, "acquire", {"identity": identity, "lease_duration": lease_duration}
        )

    def release_lease(self, name: str, identity: str) -> Any:
        return self._lease_verb(name, "release", {"identity": identity})

    def _crud(self, kind: str, verb: str, obj=None, key: Optional[str] = None) -> None:
        body: dict[str, Any] = {"verb": verb}
        if obj is not None:
            body["object"] = wire.encode_kind(kind, obj)
        if key is not None:
            body["key"] = key
        self._request(f"{verb}.{kind}", "POST", f"/backend/v1/{kind}", body)

    def create(self, kind: str, obj) -> Any:
        self._crud(kind, "create", obj)
        return obj

    def update(self, kind: str, obj) -> Any:
        self._crud(kind, "update", obj)
        return obj

    def delete(self, kind: str, key: str) -> None:
        self._crud(kind, "delete", key=key)

    def update_pod(self, pod) -> Any:
        return self.update(PODS, pod)

    def delete_pod(self, namespace: str, name: str) -> None:
        self.delete(PODS, f"{namespace}/{name}")

    def create_pod(self, pod) -> Any:
        return self.create(PODS, pod)

    def update_pod_group(self, pg) -> Any:
        return self.update(POD_GROUPS, pg)

    def update_persistent_volume(self, pv) -> Any:
        return self.update(PVS, pv)

    def update_persistent_volume_claim(self, pvc) -> Any:
        return self.update(PVCS, pvc)

    # The typed conveniences the server's workload API handler calls, so
    # a federated scheduler's own HTTP endpoint proxies mutations through
    # to the store process instead of 500ing on a missing method.

    def create_queue(self, q) -> Any:
        return self.create(QUEUES, q)

    def delete_queue(self, name: str) -> None:
        self.delete(QUEUES, name)

    def create_node(self, n) -> Any:
        return self.create(NODES, n)

    def delete_node(self, name: str) -> None:
        self.delete(NODES, name)

    def create_pod_group(self, pg) -> Any:
        return self.create(POD_GROUPS, pg)

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self.delete(POD_GROUPS, f"{namespace}/{name}")

    def create_priority_class(self, pc) -> Any:
        return self.create(PRIORITY_CLASSES, pc)

    def delete_priority_class(self, name: str) -> None:
        self.delete(PRIORITY_CLASSES, name)

    def create_persistent_volume(self, pv) -> Any:
        return self.create(PVS, pv)

    def delete_persistent_volume(self, name: str) -> None:
        self.delete(PVS, name)

    def create_persistent_volume_claim(self, pvc) -> Any:
        return self.create(PVCS, pvc)

    def delete_persistent_volume_claim(self, namespace: str, name: str) -> None:
        self.delete(PVCS, f"{namespace}/{name}")


class _Gone(Exception):
    """Internal: the watch cursor fell behind the server ring (410)."""

    def __init__(self, rv: int) -> None:
        super().__init__(f"410 gone (rv {rv})")
        self.rv = rv


class _Unsupported(Exception):
    """Internal: a v2-only route 404ed — the server behind this URL is a
    v1 generation (rolling downgrade, partition heal to an older peer).
    Callers renegotiate and take their v1 path."""

    def __init__(self, path: str) -> None:
        super().__init__(f"unsupported route {path} (v1 server?)")
        self.path = path
