"""In-process cluster store: the API-server + informer substitute.

The reference's cache subscribes nine client-go informers to the API
server (cache/cache.go:233-301) and receives add/update/delete callbacks
as the watch stream delivers deltas. TPU-native kube-batch runs against
an in-process object store instead: callers (tests, the simulator, a
future external bridge) mutate the store through k8s-shaped CRUD calls,
and the store dispatches the same add/update/delete callbacks to every
registered handler — including an initial-list replay on registration,
which is what makes ``has_synced`` true (the WaitForCacheSync
equivalent, cache/cache.go:327-348).

Event dispatch is synchronous in the mutating caller's thread, ordered
per object, outside the store lock (so a handler may re-enter the
store). That preserves the informer contract the cache depends on —
events for one object arrive in order — without a background pump
thread per kind.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from kube_batch_tpu import log
from kube_batch_tpu.utils.locking import assume_locked
from kube_batch_tpu.apis.types import (
    Lease,
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    PriorityClass,
    Queue,
    StorageClass,
)

PODS = "pods"
NODES = "nodes"
POD_GROUPS = "podgroups"
QUEUES = "queues"
PDBS = "poddisruptionbudgets"
PRIORITY_CLASSES = "priorityclasses"
PVS = "persistentvolumes"
PVCS = "persistentvolumeclaims"
STORAGE_CLASSES = "storageclasses"
LEASES = "leases"

KINDS = (
    PODS, NODES, POD_GROUPS, QUEUES, PDBS, PRIORITY_CLASSES,
    PVS, PVCS, STORAGE_CLASSES, LEASES,
)

# Kinds whose objects are cluster-scoped (keyed by name, not ns/name).
_CLUSTER_SCOPED = {NODES, QUEUES, PRIORITY_CLASSES, PVS, STORAGE_CLASSES, LEASES}


class AlreadyExists(KeyError):
    """create() of a key already present — typed so API layers can map
    it to HTTP 409 without string-matching the message."""


def obj_key(kind: str, obj: Any) -> str:
    meta = obj.metadata
    if kind in _CLUSTER_SCOPED:
        return meta.name
    return f"{meta.namespace}/{meta.name}"


@dataclass
class EventHandler:
    """One informer subscription (client-go ResourceEventHandlerFuncs +
    the optional FilterFunc of FilteringResourceEventHandler)."""

    on_add: Optional[Callable[[Any], None]] = None
    on_update: Optional[Callable[[Any, Any], None]] = None
    on_delete: Optional[Callable[[Any], None]] = None
    filter: Optional[Callable[[Any], bool]] = None

    def _passes(self, obj: Any) -> bool:
        return self.filter is None or self.filter(obj)

    def add(self, obj: Any) -> None:
        if self.on_add and self._passes(obj):
            self.on_add(obj)

    def update(self, old: Any, new: Any) -> None:
        # client-go FilteringResourceEventHandler semantics: an update
        # whose old object was filtered out is delivered as an Add, and
        # one whose new object is filtered out as a Delete.
        old_ok, new_ok = self._passes(old), self._passes(new)
        if old_ok and new_ok:
            if self.on_update:
                self.on_update(old, new)
        elif new_ok:
            if self.on_add:
                self.on_add(new)
        elif old_ok:
            if self.on_delete:
                self.on_delete(old)

    def delete(self, obj: Any) -> None:
        if self.on_delete and self._passes(obj):
            self.on_delete(obj)


@dataclass
class _KindStore:
    objects: dict[str, Any] = field(default_factory=dict)
    handlers: list[EventHandler] = field(default_factory=list)


class ClusterStore:
    """Thread-safe object store with informer-style event fan-out."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._kinds: dict[str, _KindStore] = {k: _KindStore() for k in KINDS}
        # Events are appended under _lock (atomically with the mutation)
        # and drained FIFO under _dispatch_lock, so handlers observe
        # every event exactly once, in mutation order, even under
        # concurrent writers — the informer delivery contract. The
        # dispatch lock is re-entrant: a handler may mutate the store,
        # and the nested event is delivered inline.
        self._dispatch_lock = threading.RLock()
        self._events: deque = deque()  # (verb, handlers, old, new)

    # -- event pump --------------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._dispatch_lock:
                with self._lock:
                    if not self._events:
                        return
                    verb, handlers, old, new = self._events.popleft()
                for h in handlers:
                    if verb == "add":
                        h.add(new)
                    elif verb == "update":
                        h.update(old, new)
                    else:
                        h.delete(old)

    # -- subscription ------------------------------------------------------

    def add_event_handler(self, kind: str, handler: EventHandler) -> None:
        """Register + initial-list replay (informer.AddEventHandler).
        Registration and replay enqueue atomically with respect to
        concurrent mutations, so the handler sees each object exactly
        once — either via replay or via the mutation's own event."""
        with self._lock:
            ks = self._kinds[kind]
            ks.handlers.append(handler)
            for obj in ks.objects.values():
                self._events.append(("add", [handler], None, obj))
        self._drain()

    # -- CRUD --------------------------------------------------------------

    @assume_locked
    def _ks(self, kind: str) -> _KindStore:
        ks = self._kinds.get(kind)
        if ks is None:
            raise KeyError(f"unknown kind {kind!r}")
        return ks

    def create(self, kind: str, obj: Any) -> Any:
        key = obj_key(kind, obj)
        with self._lock:
            ks = self._ks(kind)
            if key in ks.objects:
                raise AlreadyExists(f"{kind} {key!r} already exists")
            ks.objects[key] = obj
            self._events.append(("add", list(ks.handlers), None, obj))
        log.V(4).infof("store: created %s %s", kind, key)
        self._drain()
        return obj

    def update(self, kind: str, obj: Any) -> Any:
        key = obj_key(kind, obj)
        with self._lock:
            ks = self._ks(kind)
            old = ks.objects.get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            ks.objects[key] = obj
            self._events.append(("update", list(ks.handlers), old, obj))
        log.V(4).infof("store: updated %s %s", kind, key)
        self._drain()
        return obj

    def delete(self, kind: str, key: str) -> Any:
        with self._lock:
            ks = self._ks(kind)
            obj = ks.objects.pop(key, None)
            if obj is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._events.append(("delete", list(ks.handlers), obj, None))
        log.V(4).infof("store: deleted %s %s", kind, key)
        self._drain()
        return obj

    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._ks(kind).objects.get(key)

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._ks(kind).objects.values())

    # -- leader-election arbiter -------------------------------------------

    def try_acquire_lease(
        self,
        name: str,
        identity: str,
        lease_duration: float = 15.0,
        now: Optional[float] = None,
    ) -> Lease:
        """Atomic acquire-or-renew of the named Lease; returns the lease
        as it stands after the attempt (caller checks ``holder_identity``
        to learn whether it leads). The arbitration ladder matches
        client-go's leaderelection.tryAcquireOrRenew
        (the reference drives it via leaderelection.RunOrDie,
        cmd/kube-batch/app/server.go:127-139):

        - no lease, or holder released (empty), or lease expired
          (``now > renew_time + lease_duration_seconds``): take it —
          transitions+1 when taking over from a different holder;
        - held by us: renew (refresh renew_time);
        - held by someone else and fresh: no mutation.

        All times are THIS store's clock, so two candidates on hosts
        with skewed clocks still agree on expiry."""
        import math
        import time as _time

        if not identity:
            # "" is the released sentinel — accepting it would report
            # acquired=true while leaving the lease free for anyone
            # (split-brain)
            raise ValueError("lease identity must be non-empty")
        if not (
            isinstance(lease_duration, (int, float))
            and math.isfinite(lease_duration)
            and 0 < lease_duration <= 86400
        ):
            # NaN/inf never expire (blocking failover forever after the
            # holder dies); <=0 is instantly stealable from a live leader
            raise ValueError("lease_duration must be in (0, 86400] seconds")
        now = _time.time() if now is None else now
        with self._lock:
            ks = self._ks(LEASES)
            cur: Optional[Lease] = ks.objects.get(name)
            if cur is not None and cur.holder_identity not in ("", identity):
                expired = now > cur.renew_time + cur.lease_duration_seconds
                if not expired:
                    return cur
            new = Lease(
                metadata=ObjectMeta(name=name),
                holder_identity=identity,
                lease_duration_seconds=lease_duration,
                acquire_time=(
                    cur.acquire_time
                    if cur is not None and cur.holder_identity == identity
                    else now
                ),
                renew_time=now,
                lease_transitions=(
                    cur.lease_transitions
                    + (1 if cur.holder_identity != identity else 0)
                    if cur is not None
                    else 0
                ),
            )
            ks.objects[name] = new
            if cur is None:
                self._events.append(("add", list(ks.handlers), None, new))
            else:
                self._events.append(("update", list(ks.handlers), cur, new))
        if cur is None or cur.holder_identity != identity:
            log.infof("lease %s acquired by %s", name, identity)
        self._drain()
        return new

    def release_lease(self, name: str, identity: str) -> Optional[Lease]:
        """Graceful hand-off: the holder clears its identity so a standby
        can take over immediately instead of waiting out the lease (the
        client-go ReleaseOnCancel behavior). No-op unless ``identity``
        currently holds the lease."""
        if not identity:
            # "" is the released sentinel; '""' == already-released holder
            # would otherwise pass the holder check below
            raise ValueError("lease identity must be non-empty")
        with self._lock:
            ks = self._ks(LEASES)
            cur: Optional[Lease] = ks.objects.get(name)
            if cur is None or cur.holder_identity != identity:
                return cur
            new = Lease(
                metadata=cur.metadata,
                holder_identity="",
                lease_duration_seconds=cur.lease_duration_seconds,
                acquire_time=cur.acquire_time,
                renew_time=cur.renew_time,
                lease_transitions=cur.lease_transitions,
            )
            ks.objects[name] = new
            self._events.append(("update", list(ks.handlers), cur, new))
        log.infof("lease %s released by %s", name, identity)
        self._drain()
        return new

    # -- typed conveniences (what tests and the simulator use) -------------

    def create_pod(self, pod: Pod) -> Pod:
        return self.create(PODS, pod)

    def update_pod(self, pod: Pod) -> Pod:
        return self.update(PODS, pod)

    def delete_pod(self, namespace: str, name: str) -> Pod:
        return self.delete(PODS, f"{namespace}/{name}")

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self.get(PODS, f"{namespace}/{name}")

    def create_node(self, node: Node) -> Node:
        return self.create(NODES, node)

    def update_node(self, node: Node) -> Node:
        return self.update(NODES, node)

    def delete_node(self, name: str) -> Node:
        return self.delete(NODES, name)

    def create_pod_group(self, pg: PodGroup) -> PodGroup:
        return self.create(POD_GROUPS, pg)

    def update_pod_group(self, pg: PodGroup) -> PodGroup:
        return self.update(POD_GROUPS, pg)

    def delete_pod_group(self, namespace: str, name: str) -> PodGroup:
        return self.delete(POD_GROUPS, f"{namespace}/{name}")

    def create_queue(self, q: Queue) -> Queue:
        return self.create(QUEUES, q)

    def delete_queue(self, name: str) -> Queue:
        return self.delete(QUEUES, name)

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        return self.create(PDBS, pdb)

    def create_priority_class(self, pc: PriorityClass) -> PriorityClass:
        return self.create(PRIORITY_CLASSES, pc)

    def delete_priority_class(self, name: str) -> PriorityClass:
        return self.delete(PRIORITY_CLASSES, name)

    def create_persistent_volume(self, pv: PersistentVolume) -> PersistentVolume:
        return self.create(PVS, pv)

    def update_persistent_volume(self, pv: PersistentVolume) -> PersistentVolume:
        return self.update(PVS, pv)

    def delete_persistent_volume(self, name: str) -> PersistentVolume:
        return self.delete(PVS, name)

    def create_persistent_volume_claim(
        self, pvc: PersistentVolumeClaim
    ) -> PersistentVolumeClaim:
        return self.create(PVCS, pvc)

    def update_persistent_volume_claim(
        self, pvc: PersistentVolumeClaim
    ) -> PersistentVolumeClaim:
        return self.update(PVCS, pvc)

    def delete_persistent_volume_claim(
        self, namespace: str, name: str
    ) -> PersistentVolumeClaim:
        return self.delete(PVCS, f"{namespace}/{name}")

    def create_storage_class(self, sc: StorageClass) -> StorageClass:
        return self.create(STORAGE_CLASSES, sc)
