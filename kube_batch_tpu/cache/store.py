"""In-process cluster store: the API-server + informer substitute.

The reference's cache subscribes nine client-go informers to the API
server (cache/cache.go:233-301) and receives add/update/delete callbacks
as the watch stream delivers deltas. TPU-native kube-batch runs against
an in-process object store instead: callers (tests, the simulator, a
future external bridge) mutate the store through k8s-shaped CRUD calls,
and the store dispatches the same add/update/delete callbacks to every
registered handler — including an initial-list replay on registration,
which is what makes ``has_synced`` true (the WaitForCacheSync
equivalent, cache/cache.go:327-348).

Event dispatch is synchronous in the mutating caller's thread, ordered
per object, outside the store lock (so a handler may re-enter the
store). That preserves the informer contract the cache depends on —
events for one object arrive in order — without a background pump
thread per kind.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from kube_batch_tpu import log
from kube_batch_tpu.utils.locking import assume_locked
from kube_batch_tpu.apis.types import (
    Lease,
    Node,
    ObjectMeta,
    PersistentVolume,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    PodPhase,
    PriorityClass,
    Queue,
    StorageClass,
)

PODS = "pods"
NODES = "nodes"
POD_GROUPS = "podgroups"
QUEUES = "queues"
PDBS = "poddisruptionbudgets"
PRIORITY_CLASSES = "priorityclasses"
PVS = "persistentvolumes"
PVCS = "persistentvolumeclaims"
STORAGE_CLASSES = "storageclasses"
LEASES = "leases"

KINDS = (
    PODS, NODES, POD_GROUPS, QUEUES, PDBS, PRIORITY_CLASSES,
    PVS, PVCS, STORAGE_CLASSES, LEASES,
)

# Kinds whose objects are cluster-scoped (keyed by name, not ns/name).
_CLUSTER_SCOPED = {NODES, QUEUES, PRIORITY_CLASSES, PVS, STORAGE_CLASSES, LEASES}


class AlreadyExists(KeyError):
    """create() of a key already present — typed so API layers can map
    it to HTTP 409 without string-matching the message."""


class StaleWrite(RuntimeError):
    """Optimistic-concurrency rejection (Omega-style): a conditional
    write carried a snapshot version older than the store state it would
    overwrite, or the write no longer applies to current truth. Typed —
    and carrying the conflicted object — so the losing scheduler can
    resync just the conflicted gang and retry, instead of treating the
    rejection like an infrastructure write failure."""

    def __init__(
        self, kind: str, key: str, reason: str, expected: int, actual: int
    ) -> None:
        super().__init__(
            f"stale write on {kind} {key!r}: {reason} "
            f"(snapshot v{expected}, store v{actual})"
        )
        self.kind = kind
        self.key = key
        self.reason = reason
        self.expected = expected
        self.actual = actual


def obj_key(kind: str, obj: Any) -> str:
    meta = obj.metadata
    if kind in _CLUSTER_SCOPED:
        return meta.name
    return f"{meta.namespace}/{meta.name}"


@dataclass
class EventHandler:
    """One informer subscription (client-go ResourceEventHandlerFuncs +
    the optional FilterFunc of FilteringResourceEventHandler)."""

    on_add: Optional[Callable[[Any], None]] = None
    on_update: Optional[Callable[[Any, Any], None]] = None
    on_delete: Optional[Callable[[Any], None]] = None
    filter: Optional[Callable[[Any], bool]] = None

    def _passes(self, obj: Any) -> bool:
        return self.filter is None or self.filter(obj)

    def add(self, obj: Any) -> None:
        if self.on_add and self._passes(obj):
            self.on_add(obj)

    def update(self, old: Any, new: Any) -> None:
        # client-go FilteringResourceEventHandler semantics: an update
        # whose old object was filtered out is delivered as an Add, and
        # one whose new object is filtered out as a Delete.
        old_ok, new_ok = self._passes(old), self._passes(new)
        if old_ok and new_ok:
            if self.on_update:
                self.on_update(old, new)
        elif new_ok:
            if self.on_add:
                self.on_add(new)
        elif old_ok:
            if self.on_delete:
                self.on_delete(old)

    def delete(self, obj: Any) -> None:
        if self.on_delete and self._passes(obj):
            self.on_delete(obj)


@dataclass
class _KindStore:
    objects: dict[str, Any] = field(default_factory=dict)
    handlers: list[EventHandler] = field(default_factory=list)


class ClusterStore:
    """Thread-safe object store with informer-style event fan-out."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._kinds: dict[str, _KindStore] = {k: _KindStore() for k in KINDS}
        # Events are appended under _lock (atomically with the mutation)
        # and drained FIFO under _dispatch_lock, so handlers observe
        # every event exactly once, in mutation order, even under
        # concurrent writers — the informer delivery contract. The
        # dispatch lock is re-entrant: a handler may mutate the store,
        # and the nested event is delivered inline.
        self._dispatch_lock = threading.RLock()
        self._events: deque = deque()  # (verb, handlers, old, new)
        # Optimistic-concurrency state (all #: guarded_by _lock):
        # _version counts every committed mutation; a scheduler stamps
        # it into its snapshot and sends it back with each conditional
        # write. _placement_version[node] is the store version of the
        # last placement write touching that node — the conflict check
        # is per node, not global, so schedulers binding onto disjoint
        # nodes never conflict. _node_alloc[node] is the running sum of
        # bound, non-terminal pod requests, maintained incrementally so
        # the conditional commit can reject an over-capacity bind in
        # O(gang) instead of O(pods).
        self._version = 0
        self._placement_version: dict[str, int] = {}
        self._node_alloc: dict[str, Any] = {}

    # -- event pump --------------------------------------------------------

    def _drain(self) -> None:
        while True:
            with self._dispatch_lock:
                with self._lock:
                    if not self._events:
                        return
                    verb, handlers, old, new = self._events.popleft()
                for h in handlers:
                    if verb == "add":
                        h.add(new)
                    elif verb == "update":
                        h.update(old, new)
                    else:
                        h.delete(old)

    # -- subscription ------------------------------------------------------

    def add_event_handler(self, kind: str, handler: EventHandler) -> None:
        """Register + initial-list replay (informer.AddEventHandler).
        Registration and replay enqueue atomically with respect to
        concurrent mutations, so the handler sees each object exactly
        once — either via replay or via the mutation's own event."""
        with self._lock:
            ks = self._kinds[kind]
            ks.handlers.append(handler)
            for obj in ks.objects.values():
                self._events.append(("add", [handler], None, obj))
        self._drain()

    # -- optimistic-concurrency bookkeeping --------------------------------

    @property
    def version(self) -> int:
        """Monotonic store version: bumps once per committed mutation.
        Schedulers stamp it into their snapshot and send it back with
        every conditional write (conditional_bind_many / _unbind)."""
        with self._lock:
            return self._version

    def placement_version(self, node: str) -> int:
        """Store version of the last placement write touching ``node``
        (0 = never placed on). The per-node conflict granularity."""
        with self._lock:
            return self._placement_version.get(node, 0)

    def node_allocated(self, node: str) -> Any:
        """Clone of the incremental allocated-resource sum for ``node``
        (bound, non-terminal pods). Bench/fsck introspection."""
        from kube_batch_tpu.api.resource_info import Resource

        with self._lock:
            alloc = self._node_alloc.get(node)
            return alloc.clone() if alloc is not None else Resource.empty()

    @assume_locked
    def _bump_locked(self) -> int:
        self._version += 1
        return self._version

    @assume_locked
    def _account_locked(self, kind: str, old: Any, new: Any) -> None:
        """Maintain _node_alloc/_placement_version across one committed
        pod mutation. Runs AFTER _bump_locked so the placement version
        recorded is the mutation's own version. A pod contributes to its
        node's allocation while bound and non-terminal; any transition
        in or out of that state is a placement write on the node."""
        if kind != PODS:
            return
        from kube_batch_tpu.api.helpers import get_pod_resource_request
        from kube_batch_tpu.api.resource_info import Resource

        for pod, sign in ((old, -1), (new, +1)):
            if pod is None or not pod.node_name:
                continue
            if pod.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            node = pod.node_name
            req = get_pod_resource_request(pod)
            alloc = self._node_alloc.setdefault(node, Resource.empty())
            if sign > 0:
                alloc.add(req)
            else:
                # tolerant subtract (Resource.sub raises on epsilon
                # underflow; symmetric add/remove must never throw here)
                alloc.milli_cpu -= req.milli_cpu
                alloc.memory -= req.memory
                for name, q in req.scalars.items():
                    alloc.scalars[name] = alloc.scalars.get(name, 0.0) - q
            self._placement_version[node] = self._version

    # -- CRUD --------------------------------------------------------------

    @assume_locked
    def _ks(self, kind: str) -> _KindStore:
        ks = self._kinds.get(kind)
        if ks is None:
            raise KeyError(f"unknown kind {kind!r}")
        return ks

    def create(self, kind: str, obj: Any) -> Any:
        key = obj_key(kind, obj)
        with self._lock:
            ks = self._ks(kind)
            if key in ks.objects:
                raise AlreadyExists(f"{kind} {key!r} already exists")
            ks.objects[key] = obj
            self._bump_locked()
            self._account_locked(kind, None, obj)
            self._events.append(("add", list(ks.handlers), None, obj))
        log.V(4).infof("store: created %s %s", kind, key)
        self._drain()
        return obj

    def update(self, kind: str, obj: Any) -> Any:
        key = obj_key(kind, obj)
        with self._lock:
            ks = self._ks(kind)
            old = ks.objects.get(key)
            if old is None:
                raise KeyError(f"{kind} {key!r} not found")
            ks.objects[key] = obj
            self._bump_locked()
            self._account_locked(kind, old, obj)
            self._events.append(("update", list(ks.handlers), old, obj))
        log.V(4).infof("store: updated %s %s", kind, key)
        self._drain()
        return obj

    def delete(self, kind: str, key: str) -> Any:
        with self._lock:
            ks = self._ks(kind)
            obj = ks.objects.pop(key, None)
            if obj is None:
                raise KeyError(f"{kind} {key!r} not found")
            self._bump_locked()
            self._account_locked(kind, obj, None)
            self._events.append(("delete", list(ks.handlers), obj, None))
        log.V(4).infof("store: deleted %s %s", kind, key)
        self._drain()
        return obj

    # -- conditional writes (Omega-style optimistic concurrency) -----------

    def conditional_bind_many(
        self, bindings: list[tuple[str, str, str]], snapshot_version: int
    ) -> list[Pod]:
        """Transactionally bind ``[(namespace, name, hostname)]`` against
        the snapshot the scheduler solved over. Every entry is checked
        under ONE lock hold before ANY entry is applied — all-or-nothing
        per call, so the caller dispatches one gang per transaction and a
        rejected gang needs no rollback. Rejection reasons (StaleWrite):

        - ``missing``       the pod was deleted since the snapshot
        - ``already_bound`` another scheduler placed the pod first
        - ``no_node``       the target node is gone
        - ``stale_node``    the node took a placement write the snapshot
                            never saw (per-node version check)
        - ``capacity``      store-side admission: requests no longer fit
        - ``injected``      the ``store.conflict`` fault drill

        A pod already bound to the SAME host is skipped, not rejected —
        that is the idempotent journal re-dispatch case."""
        from kube_batch_tpu import faults
        from kube_batch_tpu.api.helpers import get_pod_resource_request
        from kube_batch_tpu.api.resource_info import Resource

        with self._lock:
            if faults.should_fire("store.conflict"):
                ns, name, _h = bindings[0] if bindings else ("", "", "")
                raise StaleWrite(
                    PODS, f"{ns}/{name}", "injected", snapshot_version, self._version
                )
            ks = self._ks(PODS)
            nodes = self._ks(NODES).objects
            staged: list[tuple[str, Pod, str]] = []
            batch_alloc: dict[str, Resource] = {}
            for ns, name, hostname in bindings:
                key = f"{ns}/{name}"
                old = ks.objects.get(key)
                if old is None:
                    raise StaleWrite(
                        PODS, key, "missing", snapshot_version, self._version
                    )
                if old.node_name:
                    if old.node_name == hostname:
                        continue  # journal re-dispatch: already landed
                    raise StaleWrite(
                        PODS, key, "already_bound", snapshot_version, self._version
                    )
                node = nodes.get(hostname)
                if node is None:
                    raise StaleWrite(
                        NODES, hostname, "no_node", snapshot_version, self._version
                    )
                node_v = self._placement_version.get(hostname, 0)
                if node_v > snapshot_version:
                    raise StaleWrite(
                        NODES, hostname, "stale_node", snapshot_version, node_v
                    )
                req = get_pod_resource_request(old)
                pending = batch_alloc.setdefault(hostname, Resource.empty())
                have = self._node_alloc.get(hostname)
                total = have.clone() if have is not None else Resource.empty()
                total.add(pending).add(req)
                if not total.less_equal(Resource.from_resource_list(node.allocatable)):
                    raise StaleWrite(
                        NODES, hostname, "capacity", snapshot_version, self._version
                    )
                pending.add(req)
                staged.append((key, old, hostname))
            applied: list[Pod] = []
            for key, old, hostname in staged:
                new = dataclasses.replace(old, node_name=hostname)
                ks.objects[key] = new
                self._bump_locked()
                self._account_locked(PODS, old, new)
                self._events.append(("update", list(ks.handlers), old, new))
                applied.append(new)
        log.V(4).infof(
            "store: conditionally bound %d pod(s) at snapshot v%d",
            len(applied), snapshot_version,
        )
        self._drain()
        return applied

    def conditional_unbind(
        self, namespace: str, name: str, snapshot_version: int
    ) -> Optional[Pod]:
        """Optimistic evict twin of conditional_bind_many: clear the
        pod's placement iff its node took no placement write since the
        snapshot. An already-unbound pod is the idempotent re-dispatch
        case and returns the current object unchanged."""
        from kube_batch_tpu import faults

        key = f"{namespace}/{name}"
        with self._lock:
            if faults.should_fire("store.conflict"):
                raise StaleWrite(
                    PODS, key, "injected", snapshot_version, self._version
                )
            ks = self._ks(PODS)
            old = ks.objects.get(key)
            if old is None:
                raise StaleWrite(PODS, key, "missing", snapshot_version, self._version)
            if not old.node_name:
                return old  # journal re-dispatch: already unbound
            node_v = self._placement_version.get(old.node_name, 0)
            if node_v > snapshot_version:
                raise StaleWrite(
                    NODES, old.node_name, "stale_node", snapshot_version, node_v
                )
            new = dataclasses.replace(old, node_name="")
            ks.objects[key] = new
            self._bump_locked()
            self._account_locked(PODS, old, new)
            self._events.append(("update", list(ks.handlers), old, new))
        log.V(4).infof(
            "store: conditionally unbound %s at snapshot v%d", key, snapshot_version
        )
        self._drain()
        return new

    def conditional_evict(
        self, namespace: str, name: str, snapshot_version: int
    ) -> Optional[Pod]:
        """Optimistic delete (the evictor's transaction): remove the pod
        iff its node took no placement write since the snapshot — a
        preemption decision solved over a stale view must not kill a pod
        another scheduler just placed around. A pod already gone is the
        idempotent re-dispatch case."""
        from kube_batch_tpu import faults

        key = f"{namespace}/{name}"
        with self._lock:
            if faults.should_fire("store.conflict"):
                raise StaleWrite(
                    PODS, key, "injected", snapshot_version, self._version
                )
            ks = self._ks(PODS)
            old = ks.objects.get(key)
            if old is None:
                return None  # journal re-dispatch: already evicted
            if old.node_name:
                node_v = self._placement_version.get(old.node_name, 0)
                if node_v > snapshot_version:
                    raise StaleWrite(
                        NODES, old.node_name, "stale_node", snapshot_version, node_v
                    )
            ks.objects.pop(key)
            self._bump_locked()
            self._account_locked(PODS, old, None)
            self._events.append(("delete", list(ks.handlers), old, None))
        log.V(4).infof(
            "store: conditionally evicted %s at snapshot v%d", key, snapshot_version
        )
        self._drain()
        return old

    def get(self, kind: str, key: str) -> Optional[Any]:
        with self._lock:
            return self._ks(kind).objects.get(key)

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._ks(kind).objects.values())

    # -- leader-election arbiter -------------------------------------------

    def try_acquire_lease(
        self,
        name: str,
        identity: str,
        lease_duration: float = 15.0,
        now: Optional[float] = None,
    ) -> Lease:
        """Atomic acquire-or-renew of the named Lease; returns the lease
        as it stands after the attempt (caller checks ``holder_identity``
        to learn whether it leads). The arbitration ladder matches
        client-go's leaderelection.tryAcquireOrRenew
        (the reference drives it via leaderelection.RunOrDie,
        cmd/kube-batch/app/server.go:127-139):

        - no lease, or holder released (empty), or lease expired
          (``now > renew_time + lease_duration_seconds``): take it —
          transitions+1 when taking over from a different holder;
        - held by us: renew (refresh renew_time);
        - held by someone else and fresh: no mutation.

        All times are THIS store's clock, so two candidates on hosts
        with skewed clocks still agree on expiry."""
        import math
        import time as _time

        if not identity:
            # "" is the released sentinel — accepting it would report
            # acquired=true while leaving the lease free for anyone
            # (split-brain)
            raise ValueError("lease identity must be non-empty")
        if not (
            isinstance(lease_duration, (int, float))
            and math.isfinite(lease_duration)
            and 0 < lease_duration <= 86400
        ):
            # NaN/inf never expire (blocking failover forever after the
            # holder dies); <=0 is instantly stealable from a live leader
            raise ValueError("lease_duration must be in (0, 86400] seconds")
        now = _time.time() if now is None else now
        with self._lock:
            ks = self._ks(LEASES)
            cur: Optional[Lease] = ks.objects.get(name)
            if cur is not None and cur.holder_identity not in ("", identity):
                expired = now > cur.renew_time + cur.lease_duration_seconds
                if not expired:
                    return cur
            new = Lease(
                metadata=ObjectMeta(name=name),
                holder_identity=identity,
                lease_duration_seconds=lease_duration,
                acquire_time=(
                    cur.acquire_time
                    if cur is not None and cur.holder_identity == identity
                    else now
                ),
                renew_time=now,
                lease_transitions=(
                    cur.lease_transitions
                    + (1 if cur.holder_identity != identity else 0)
                    if cur is not None
                    else 0
                ),
            )
            ks.objects[name] = new
            self._bump_locked()
            if cur is None:
                self._events.append(("add", list(ks.handlers), None, new))
            else:
                self._events.append(("update", list(ks.handlers), cur, new))
        if cur is None or cur.holder_identity != identity:
            log.infof("lease %s acquired by %s", name, identity)
        self._drain()
        return new

    def release_lease(self, name: str, identity: str) -> Optional[Lease]:
        """Graceful hand-off: the holder clears its identity so a standby
        can take over immediately instead of waiting out the lease (the
        client-go ReleaseOnCancel behavior). No-op unless ``identity``
        currently holds the lease."""
        if not identity:
            # "" is the released sentinel; '""' == already-released holder
            # would otherwise pass the holder check below
            raise ValueError("lease identity must be non-empty")
        with self._lock:
            ks = self._ks(LEASES)
            cur: Optional[Lease] = ks.objects.get(name)
            if cur is None or cur.holder_identity != identity:
                return cur
            new = Lease(
                metadata=cur.metadata,
                holder_identity="",
                lease_duration_seconds=cur.lease_duration_seconds,
                acquire_time=cur.acquire_time,
                renew_time=cur.renew_time,
                lease_transitions=cur.lease_transitions,
            )
            ks.objects[name] = new
            self._bump_locked()
            self._events.append(("update", list(ks.handlers), cur, new))
        log.infof("lease %s released by %s", name, identity)
        self._drain()
        return new

    # -- typed conveniences (what tests and the simulator use) -------------

    def create_pod(self, pod: Pod) -> Pod:
        return self.create(PODS, pod)

    def update_pod(self, pod: Pod) -> Pod:
        return self.update(PODS, pod)

    def delete_pod(self, namespace: str, name: str) -> Pod:
        return self.delete(PODS, f"{namespace}/{name}")

    def get_pod(self, namespace: str, name: str) -> Optional[Pod]:
        return self.get(PODS, f"{namespace}/{name}")

    def create_node(self, node: Node) -> Node:
        return self.create(NODES, node)

    def update_node(self, node: Node) -> Node:
        return self.update(NODES, node)

    def delete_node(self, name: str) -> Node:
        return self.delete(NODES, name)

    def create_pod_group(self, pg: PodGroup) -> PodGroup:
        return self.create(POD_GROUPS, pg)

    def update_pod_group(self, pg: PodGroup) -> PodGroup:
        return self.update(POD_GROUPS, pg)

    def delete_pod_group(self, namespace: str, name: str) -> PodGroup:
        return self.delete(POD_GROUPS, f"{namespace}/{name}")

    def create_queue(self, q: Queue) -> Queue:
        return self.create(QUEUES, q)

    def delete_queue(self, name: str) -> Queue:
        return self.delete(QUEUES, name)

    def create_pdb(self, pdb: PodDisruptionBudget) -> PodDisruptionBudget:
        return self.create(PDBS, pdb)

    def create_priority_class(self, pc: PriorityClass) -> PriorityClass:
        return self.create(PRIORITY_CLASSES, pc)

    def delete_priority_class(self, name: str) -> PriorityClass:
        return self.delete(PRIORITY_CLASSES, name)

    def create_persistent_volume(self, pv: PersistentVolume) -> PersistentVolume:
        return self.create(PVS, pv)

    def update_persistent_volume(self, pv: PersistentVolume) -> PersistentVolume:
        return self.update(PVS, pv)

    def delete_persistent_volume(self, name: str) -> PersistentVolume:
        return self.delete(PVS, name)

    def create_persistent_volume_claim(
        self, pvc: PersistentVolumeClaim
    ) -> PersistentVolumeClaim:
        return self.create(PVCS, pvc)

    def update_persistent_volume_claim(
        self, pvc: PersistentVolumeClaim
    ) -> PersistentVolumeClaim:
        return self.update(PVCS, pvc)

    def delete_persistent_volume_claim(
        self, namespace: str, name: str
    ) -> PersistentVolumeClaim:
        return self.delete(PVCS, f"{namespace}/{name}")

    def create_storage_class(self, sc: StorageClass) -> StorageClass:
        return self.create(STORAGE_CLASSES, sc)
