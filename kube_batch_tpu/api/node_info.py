"""NodeInfo: per-node resource accounting
(reference pkg/scheduler/api/node_info.go:26-198)."""

from __future__ import annotations

from typing import Optional

from kube_batch_tpu.apis.types import Node
from kube_batch_tpu.api.job_info import TaskInfo, pod_key
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus


class NodeInfo:
    """Idle/Used/Releasing/Allocatable/Capability accounting plus the task
    map. Tasks are stored as clones so later status changes on the caller's
    TaskInfo cannot corrupt node accounting (reference node_info.go:117)."""

    def __init__(self, node: Optional[Node] = None) -> None:
        self.name = ""
        self.node: Optional[Node] = None
        self.releasing = Resource.empty()
        self.idle = Resource.empty()
        self.used = Resource.empty()
        self.allocatable = Resource.empty()
        self.capability = Resource.empty()
        self.tasks: dict[str, TaskInfo] = {}
        self.other = None
        if node is not None:
            self.name = node.name
            self.node = node
            self.idle = Resource.from_resource_list(node.allocatable)
            self.allocatable = Resource.from_resource_list(node.allocatable)
            self.capability = Resource.from_resource_list(node.capacity)

    def clone(self) -> "NodeInfo":
        """reference node_info.go:77-86.

        Resident tasks are committed facts; replay them with overcommit
        tolerance so cloning (the per-cycle snapshot) of a node two
        shards raced binds onto reproduces the negative idle instead of
        aborting the whole scheduling cycle."""
        res = NodeInfo(self.node)
        for task in self.tasks.values():
            res.add_task(task, overcommit=True)
        res.other = self.other
        return res

    def set_node(self, node: Node) -> None:
        """Reset accounting from a fresh node object, replaying resident
        tasks (reference node_info.go:89-105). Overcommit-tolerant for
        the same reason as clone(): the replay records facts."""
        self.name = node.name
        self.node = node
        self.allocatable = Resource.from_resource_list(node.allocatable)
        self.capability = Resource.from_resource_list(node.capacity)
        self.idle = Resource.from_resource_list(node.allocatable)
        self.used = Resource.empty()
        self.releasing = Resource.empty()
        for task in self.tasks.values():
            if task.status == TaskStatus.RELEASING:
                self.releasing.add(task.resreq)
            self.idle.sub_overcommit(task.resreq)
            self.used.add(task.resreq)

    def add_task(self, task: TaskInfo, overcommit: bool = False) -> None:
        """Status-dependent accounting (reference node_info.go:108-136):
        Releasing consumes Idle but is also tracked as Releasing; Pipelined
        rides on resources still being released (subtracts Releasing, not
        Idle); everything else consumes Idle. Used grows in all cases.

        ``overcommit=True`` records the task even when idle cannot cover
        it (idle goes negative). The cache's watch-event path uses this:
        a bound pod delivered by the store is a committed fact — two
        federated shards racing binds onto one node must not kill the
        pump with an accounting assertion. Allocation paths keep the
        strict raise."""
        key = pod_key(task.pod)
        if key in self.tasks:
            raise KeyError(
                f"task <{task.namespace}/{task.name}> already on node <{self.name}>"
            )
        ti = task.clone()
        if self.node is not None:
            sub = Resource.sub_overcommit if overcommit else Resource.sub
            if ti.status == TaskStatus.RELEASING:
                self.releasing.add(ti.resreq)
                sub(self.idle, ti.resreq)
            elif ti.status == TaskStatus.PIPELINED:
                sub(self.releasing, ti.resreq)
            else:
                sub(self.idle, ti.resreq)
            self.used.add(ti.resreq)
        self.tasks[key] = ti

    def remove_task(self, ti: TaskInfo) -> None:
        """Inverse of add_task (reference node_info.go:139-165)."""
        key = pod_key(ti.pod)
        task = self.tasks.get(key)
        if task is None:
            raise KeyError(
                f"failed to find task <{ti.namespace}/{ti.name}> on host <{self.name}>"
            )
        if self.node is not None:
            if task.status == TaskStatus.RELEASING:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
            elif task.status == TaskStatus.PIPELINED:
                self.releasing.add(task.resreq)
            else:
                self.idle.add(task.resreq)
            self.used.sub(task.resreq)
        del self.tasks[key]

    def update_task(self, ti: TaskInfo) -> None:
        """reference node_info.go:168-174."""
        self.remove_task(ti)
        self.add_task(ti)

    def pods(self) -> list:
        return [t.pod for t in self.tasks.values()]

    def __repr__(self) -> str:
        return (
            f"Node ({self.name}): idle <{self.idle}>, used <{self.used}>, "
            f"releasing <{self.releasing}>, tasks {len(self.tasks)}"
        )
