"""Model helpers (reference pkg/scheduler/api/helpers.go and
pkg/scheduler/api/helpers/helpers.go)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from kube_batch_tpu.apis.types import Pod, PodPhase
from kube_batch_tpu.api.resource_info import Resource
from kube_batch_tpu.api.types import TaskStatus


def get_task_status(pod: Pod) -> TaskStatus:
    """Pod phase -> task status (reference helpers.go:35-61)."""
    deleting = getattr(pod.metadata, "deletion_timestamp", None) is not None
    if pod.phase == PodPhase.RUNNING:
        return TaskStatus.RELEASING if deleting else TaskStatus.RUNNING
    if pod.phase == PodPhase.PENDING:
        if deleting:
            return TaskStatus.RELEASING
        return TaskStatus.PENDING if not pod.node_name else TaskStatus.BOUND
    if pod.phase == PodPhase.SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if pod.phase == PodPhase.FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


def merge_errors(*errs: Optional[Exception]) -> Optional[Exception]:
    """Collapse many errors into one (reference helpers.go:74-95)."""
    msgs = [str(e) for e in errs if e is not None]
    if not msgs:
        return None
    return RuntimeError("errors: " + "; ".join(msgs))


def min_resource(l: Resource, r: Resource) -> Resource:
    """Elementwise min (reference api/helpers/helpers.go:28-44). Go nil-map
    parity ({} == nil, see resource_info module docstring): when either
    side has no scalars the result has none — zero-filled entries would
    flip later nil-sensitive less/less_equal policy checks (e.g.
    proportion's overused gate)."""
    out = Resource(
        milli_cpu=min(l.milli_cpu, r.milli_cpu),
        memory=min(l.memory, r.memory),
    )
    if not l.scalars or not r.scalars:
        return out
    for name, q in l.scalars.items():
        out.scalars[name] = min(q, r.scalars.get(name, 0.0))
    return out


def share(l: float, r: float) -> float:
    """DRF share division: 0/0 -> 0, x/0 -> 1
    (reference api/helpers/helpers.go:43-60).

    The quotient is computed in the comparison dtype (api/numerics.py):
    f32 when the kernels solve f32, so share ties break identically in
    the serial oracle and on device."""
    if r == 0:
        return 0.0 if l == 0 else 1.0
    from kube_batch_tpu.api.numerics import comparison_dtype

    if comparison_dtype() is np.float64:
        return l / r  # python floats ARE f64: no boxing on the fast path
    return float(np.float32(l) / np.float32(r))


def get_pod_resource_without_init_containers(pod: Pod) -> Resource:
    """Sum of container requests (reference pod_info.go:66-73)."""
    result = Resource.empty()
    for c in pod.containers:
        result.add(Resource.from_resource_list(c.requests))
    return result


def get_pod_resource_request(pod: Pod) -> Resource:
    """max(sum of containers, each init container) (reference pod_info.go:53-62)."""
    result = get_pod_resource_without_init_containers(pod)
    for c in pod.init_containers:
        result.set_max_resource(Resource.from_resource_list(c.requests))
    return result
