"""The resource vector (reference pkg/scheduler/api/resource_info.go:30-339).

``Resource`` keeps milli-CPU and memory as dedicated floats plus a dict of
scalar resources (GPUs, TPUs, extended resources) in milli-units, exactly
like the reference. Epsilon thresholds match resource_info.go:70-72:
quantities below (10 mCPU, 10 MiB, 10 milli-scalar) are treated as zero.

This struct is also the contract for the TPU path: ``to_vector`` /
``from_vector`` lay a Resource out as one row of the dense float32
task x resource and node x resource tensors built by
kube_batch_tpu.ops.encode (SURVEY.md section 7 step 1).

Nil-map parity (round-2 decision, tested in tests/test_resource_info.py):
Go distinguishes a nil ScalarResources map from an empty one, and that
distinction *does* gate policy — ``Less`` returns False when both maps are
nil even if cpu/memory are strictly less (resource_info.go:234-239), and
``Less`` guards preempt's validateVictims (preempt.go:268), reclaim
(reclaim.go:156) and enqueue's overcommit brake (enqueue.go:88). In Go a
scalar map is nil iff no scalar was ever added (NewResource/AddScalar
initialize lazily), so an empty Python dict maps exactly onto a nil Go
map: ``{} == nil``. less/less_equal/sub below implement the Go branches
under that identification, bit-for-bit.
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence

# reference resource_info.go:44
GPU_RESOURCE_NAME = "nvidia.com/gpu"
# TPU-native addition: Google TPU extended resource, first-class scalar slot.
TPU_RESOURCE_NAME = "google.com/tpu"

# Epsilons (reference resource_info.go:70-72).
MIN_MILLI_CPU = 10.0
MIN_MILLI_SCALAR = 10.0
MIN_MEMORY = 10.0 * 1024 * 1024

_CPU = "cpu"
_MEMORY = "memory"
_PODS = "pods"


def is_scalar_resource_name(name: str) -> bool:
    """Extended-resource-style names (domain-prefixed) and hugepages count
    as scalar resources, mirroring k8s v1helper.IsScalarResourceName as
    used by the reference (resource_info.go:85-88)."""
    return "/" in name or name.startswith("hugepages-")


class Resource:
    """Mutable resource vector with kube-batch arithmetic semantics."""

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num")

    def __init__(
        self,
        milli_cpu: float = 0.0,
        memory: float = 0.0,
        scalars: Optional[dict[str, float]] = None,
        max_task_num: int = 0,
    ) -> None:
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: dict[str, float] = dict(scalars) if scalars else {}
        # Pods capacity; predicates-only, excluded from arithmetic
        # (reference resource_info.go:38-39).
        self.max_task_num = int(max_task_num)

    # -- construction -------------------------------------------------------

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_resource_list(cls, rl: Optional[Mapping[str, float]]) -> "Resource":
        """Build from a resource list dict: "cpu" in cores, "memory" in bytes,
        "pods" as count, scalar resources in natural units — cpu and scalars
        are converted to milli-units (reference NewResource,
        resource_info.go:74-91, mirroring Quantity.MilliValue)."""
        r = cls()
        if not rl:
            return r
        for name, quant in rl.items():
            if name == _CPU:
                r.milli_cpu += float(quant) * 1000.0
            elif name == _MEMORY:
                r.memory += float(quant)
            elif name == _PODS:
                r.max_task_num += int(quant)
            elif is_scalar_resource_name(name):
                # Gated like the reference's IsScalarResourceName check
                # (resource_info.go:85-88): only extended resources
                # (domain-prefixed, e.g. nvidia.com/gpu) and hugepages are
                # tracked as scalars; other core names (ephemeral-storage)
                # are ignored.
                r.add_scalar(name, float(quant) * 1000.0)
        return r

    def clone(self) -> "Resource":
        r = Resource.__new__(Resource)
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.scalars = dict(self.scalars)
        r.max_task_num = self.max_task_num
        return r

    # -- predicates ---------------------------------------------------------

    def is_empty(self) -> bool:
        """True when every dimension is below its epsilon
        (reference resource_info.go:94-106)."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        return all(q < MIN_MILLI_SCALAR for q in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        """True when the named dimension is below its epsilon
        (reference resource_info.go:109-126). Unknown scalar -> KeyError,
        matching the reference panic; a scalar never set reads as zero."""
        if name == _CPU:
            return self.milli_cpu < MIN_MILLI_CPU
        if name == _MEMORY:
            return self.memory < MIN_MEMORY
        if not self.scalars:
            return True
        if name not in self.scalars:
            raise KeyError(f"unknown resource {name!r}")
        return self.scalars[name] < MIN_MILLI_SCALAR

    def less(self, rr: "Resource") -> bool:
        """Strictly less in every dimension (reference resource_info.go:228-252).

        Go nil-map parity ({} == nil): when neither side has scalars the
        result is False even if cpu/memory are strictly less — this quirk
        gates preempt.validateVictims / reclaim / enqueue upstream."""
        if not (self.milli_cpu < rr.milli_cpu and self.memory < rr.memory):
            return False
        if not self.scalars:
            return bool(rr.scalars)
        for name, q in self.scalars.items():
            if not rr.scalars:
                return False
            if q >= rr.scalars.get(name, 0.0):
                return False
        return True

    def less_equal(self, rr: "Resource", dtype=None) -> bool:
        """Less-or-equal within epsilon per dimension — the admission check
        (reference resource_info.go:255-278). Go nil-map parity: a scalar
        entry on the left with no scalars at all on the right fails, even
        a zero-valued one.

        ``dtype`` (optional, e.g. numpy.float32) quantizes BOTH operands
        before comparing — the proportion overused/reclaimable gates pass
        the comparison dtype (api/numerics.py) so the serial gate rounds
        exactly as the f32 device gate does; one-sided rounding of a
        water-filled deserved against an on-grid allocated could
        otherwise flip the gate between the two paths."""
        if dtype is None:
            lc, rc_, lm, rm = self.milli_cpu, rr.milli_cpu, self.memory, rr.memory
        else:
            lc, rc_ = float(dtype(self.milli_cpu)), float(dtype(rr.milli_cpu))
            lm, rm = float(dtype(self.memory)), float(dtype(rr.memory))
        if not (lc < rc_ or abs(rc_ - lc) < MIN_MILLI_CPU):
            return False
        if not (lm < rm or abs(rm - lm) < MIN_MEMORY):
            return False
        for name, q in self.scalars.items():
            if not rr.scalars:
                return False
            rrq = rr.scalars.get(name, 0.0)
            if dtype is not None:
                q, rrq = float(dtype(q)), float(dtype(rrq))
            if not (q < rrq or abs(rrq - q) < MIN_MILLI_SCALAR):
                return False
        return True

    # -- arithmetic (mutating, returning self, like the reference) ----------

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for name, q in rr.scalars.items():
            self.scalars[name] = self.scalars.get(name, 0.0) + q
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; raises if rr does not fit (reference resource_info.go:146-166).

        Go nil-map parity: when the receiver has no scalars at all, scalar
        subtraction is skipped entirely (Sub's early return at :151-153) —
        no negative residue is ever created on a scalar-free receiver."""
        if not rr.less_equal(self):
            raise ValueError(
                f"Resource is not sufficient to do operation: <{self}> sub <{rr}>"
            )
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if self.scalars:
            for name, q in rr.scalars.items():
                self.scalars[name] = self.scalars.get(name, 0.0) - q
        return self

    def sub_overcommit(self, rr: "Resource") -> "Resource":
        """Subtract WITHOUT the fitness assertion — fields may go
        negative. For recording facts the store already committed (a
        bound pod arriving over the watch): two federated shards can
        race binds onto one node, and the mirror must reflect the
        overcommit rather than reject it. Negative idle reads as unfit
        to every less_equal admission check, so the local allocator
        naturally backs off the oversubscribed node."""
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        if self.scalars:
            for name, q in rr.scalars.items():
                self.scalars[name] = self.scalars.get(name, 0.0) - q
        return self

    def set_max_resource(self, rr: "Resource") -> None:
        """Elementwise max, in place (reference resource_info.go:169-196)."""
        if rr is None:
            return
        self.milli_cpu = max(self.milli_cpu, rr.milli_cpu)
        self.memory = max(self.memory, rr.memory)
        for name, q in rr.scalars.items():
            if q > self.scalars.get(name, 0.0):
                self.scalars[name] = q

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Subtract rr plus the per-dimension epsilon for every requested
        dimension; negative fields afterwards mean "insufficient"
        (reference resource_info.go:198-221). Used for NodesFitDelta
        diagnostics."""
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        for name, q in rr.scalars.items():
            if q > 0:
                self.scalars[name] = self.scalars.get(name, 0.0) - (q + MIN_MILLI_SCALAR)
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for name in self.scalars:
            self.scalars[name] *= ratio
        return self

    # -- access -------------------------------------------------------------

    def get(self, name: str) -> float:
        """reference resource_info.go:293-305."""
        if name == _CPU:
            return self.milli_cpu
        if name == _MEMORY:
            return self.memory
        return self.scalars.get(name, 0.0)

    def resource_names(self) -> list[str]:
        return [_CPU, _MEMORY, *self.scalars.keys()]

    def add_scalar(self, name: str, quantity: float) -> None:
        self.scalars[name] = self.scalars.get(name, 0.0) + quantity

    def set_scalar(self, name: str, quantity: float) -> None:
        self.scalars[name] = quantity

    # -- tensor interface (TPU path) ----------------------------------------

    def to_vector(self, scalar_names: Sequence[str]) -> list[float]:
        """Lay out as one dense row [milli_cpu, memory, *scalars] following a
        fixed scalar-slot ordering. This is the Resource -> tensor-row
        contract of the XLA path (SURVEY.md section 7 step 1)."""
        return [self.milli_cpu, self.memory, *(self.scalars.get(n, 0.0) for n in scalar_names)]

    @classmethod
    def from_vector(cls, vec: Iterable[float], scalar_names: Sequence[str]) -> "Resource":
        it = list(vec)
        scalars = {n: v for n, v in zip(scalar_names, it[2:]) if v != 0.0}
        return cls(milli_cpu=it[0], memory=it[1], scalars=scalars)

    @staticmethod
    def vector_epsilons(scalar_names: Sequence[str]) -> list[float]:
        """Per-slot epsilon vector aligned with ``to_vector`` layout."""
        return [MIN_MILLI_CPU, MIN_MEMORY, *([MIN_MILLI_SCALAR] * len(scalar_names))]

    # -- dunder -------------------------------------------------------------

    def __repr__(self) -> str:
        s = f"cpu {self.milli_cpu:.2f}, memory {self.memory:.2f}"
        for name, q in self.scalars.items():
            s += f", {name} {q:.2f}"
        return s

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        mine = {k: v for k, v in self.scalars.items() if v != 0.0}
        theirs = {k: v for k, v in other.scalars.items() if v != 0.0}
        return (
            self.milli_cpu == other.milli_cpu
            and self.memory == other.memory
            and mine == theirs
        )

    def __hash__(self):  # pragma: no cover - mutable; not hashable
        raise TypeError("Resource is mutable and unhashable")
