"""Task status state machine (reference pkg/scheduler/api/types.go:26-84)."""

from __future__ import annotations

from enum import IntEnum


class TaskStatus(IntEnum):
    """10-state task lifecycle (reference types.go:26-58). IntEnum so the
    status doubles as the tensor encoding on the XLA path."""

    PENDING = 0      # waiting in queue
    ALLOCATED = 1    # resources assigned, not dispatched (gang barrier holds it)
    PIPELINED = 2    # assigned onto releasing resources; dispatch when freed
    BINDING = 3      # bind RPC in flight
    BOUND = 4        # bound to host, kubelet not started it yet
    RUNNING = 5
    RELEASING = 6    # being deleted / preempted
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9

    def __str__(self) -> str:  # "Pending" etc., matching reference labels
        return self.name.capitalize()


# Statuses that count as "holding resources" (reference helpers.go:64-71).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED}
)


def allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


def validate_status_update(old: TaskStatus, new: TaskStatus) -> None:
    """All transitions permitted (reference types.go:82-84)."""
    return None
