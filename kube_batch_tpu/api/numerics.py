"""Comparison-dtype policy: the one place that decides the precision in
which COMPARISON-FEEDING derived quantities (DRF/proportion shares,
balanced-resource fractions, water-filled deserved vectors) are
computed.

Raw resource quantities live on the milli-CPU/byte integer grid and are
exact in every dtype in play. The derived quotients are not on the grid,
so the dtype they are computed in decides how ties break. The TPU
kernels solve in float32 when jax x64 is off (the production
configuration — float64 on TPU is slow emulation); if the serial oracle
computed the same quotients in float64 it would disagree with the
kernels on sub-f32-ulp boundaries — ~0.5% of placements at the
multi_tenant_ml scale (round-4 verdict, weak #3). Both sides therefore
compute these quantities in THIS dtype: float32 when jax runs f32,
float64 when x64 is enabled. numpy scalar ops and the kernels'
`ieee_div` are both correctly rounded, so serial == kernel holds
bit-for-bit in either mode, at every scale — the divergence cannot
reappear as the cluster grows.

The reference computes in float64 unconditionally (Go); behavior
differs only where two float64 quotients straddle within one f32 ulp,
where either choice is equally fair (drf.go:161-171,
proportion.go:101-144 define the POLICY, not the ulp).
"""

from __future__ import annotations

import numpy as np

_jax_config = None  # resolved once; the x64 flag itself is read per call
_no_jax = False     # (it can flip between test sessions)


def comparison_dtype():
    """np.float32 when the framework solves in f32 (jax x64 off), else
    np.float64. Falls back to float64 when jax is absent (pure-serial
    installs have no kernel to agree with). Hot-path cheap: the jax
    import resolves once, leaving one attribute read per call."""
    global _jax_config, _no_jax
    if _jax_config is None:
        if _no_jax:
            return np.float64
        try:
            import jax

            _jax_config = jax.config
        except Exception:
            _no_jax = True
            return np.float64
    return np.float64 if _jax_config.jax_enable_x64 else np.float32


def quantize(value: float, dtype=None) -> float:
    """Round one derived scalar to the comparison dtype (exact no-op for
    on-grid quantities and in float64 mode)."""
    return float((dtype or comparison_dtype())(value))
