"""L6: the scheduler loop (reference pkg/scheduler/scheduler.go:35-102).

``Scheduler`` owns a cache and drives the session pipeline on a fixed
period: every cycle it (re-)loads the scheduler configuration, opens a
session over a fresh ``cache.snapshot()``, runs the configured actions
in order, and records per-action and end-to-end latency — the metric
families the reference emits from the same spot
(scheduler.go:88-102).

Divergences from the reference, by design:

- the conf file is re-read **every cycle** (the reference loads it once
  at startup, scheduler.go:63-85); a conf push takes effect on the next
  cycle without a restart, and a broken conf falls back to the previous
  good one rather than killing the loop;
- the default action pipeline is ``enqueue, allocate, backfill``: the
  reference's ``allocate, backfill`` default (util.go:31-42) relies on
  Go's zero-value PodGroup phase ("") passing allocate's Pending gate
  (allocate.go:52); our object model defaults the phase to Pending, so
  the enqueue action (enqueue.go:66-119) owns that gate explicitly.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import kube_batch_tpu.actions  # noqa: F401  (registers the action pipeline)
import kube_batch_tpu.plugins  # noqa: F401  (registers the plugin builders)
from kube_batch_tpu import faults, log, metrics, obs, pipeline
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.obs import explain as _obs_explain
from kube_batch_tpu.obs import fleet as _obs_fleet
from kube_batch_tpu.conf import (
    load_scheduler_conf,
    parse_scheduler_conf,
    read_scheduler_conf,
)
from kube_batch_tpu.faults import mutation_detector
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.recovery.budget import CycleBudget, CycleDeadlineExceeded


def _env_float(name: str, default: float) -> float:
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        log.errorf(
            "%s=%r is not a number; using %g", name, os.environ.get(name), default
        )
        return default

DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


class Scheduler:
    """reference scheduler.go:35-61."""

    def __init__(
        self,
        cache,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = 1.0,
    ) -> None:
        # A scheduler process wants the persistent XLA compile cache
        # (restart/failover skips the bucket compiles); the call is lazy
        # so embedders who configure jax themselves are never overridden.
        from kube_batch_tpu.ops import enable_compilation_cache

        enable_compilation_cache()
        self.cache = cache
        self.scheduler_conf = scheduler_conf  # path; None -> default conf
        self.schedule_period = schedule_period
        self.actions = []
        self.plugins = []
        self.action_arguments: dict[str, dict[str, str]] = {}
        self._conf_cache: Optional[str] = None
        # Cycle deadline budget (recovery/budget.py): soft overruns arm
        # a solver-tier downgrade through the ladder breakers; a hard
        # overrun aborts the cycle pre-dispatch. 0/unset = no deadline.
        self._soft_deadline = _env_float("KBT_CYCLE_SOFT_DEADLINE_S", 0.0) or None
        self._hard_deadline = _env_float("KBT_CYCLE_HARD_DEADLINE_S", 0.0) or None
        # Bounded-staleness guard: refuse to schedule over a snapshot
        # older than this (watch-fed caches report real age; the
        # in-process store reports 0). 0 = guard off.
        self._max_snapshot_age = _env_float("KBT_MAX_SNAPSHOT_AGE_S", 0.0)
        # Consecutive soft overruns — tracked here, NOT via breaker
        # record_failure: a slow-but-successful solve records a breaker
        # success every cycle, which would reset per-call failures and
        # make the downgrade unreachable.
        self._soft_overruns = 0
        # Streaming mode (streaming.py): event-driven micro-cycles
        # between periodic full cycles. Armed by the conf `streaming:`
        # key or KBT_STREAMING; _stream_state is non-None only while
        # _run_streaming is live, and run_once harvests its resident
        # node table through it.
        self._conf_streaming = False
        self._conf_trace = ""
        self._conf_explain = ""
        self._conf_fleet = ""
        self._stream_state = None
        self._stream_trigger = None
        self.micro_cycles_run = 0
        self._load_conf()

    def _load_conf(self) -> None:
        """Load (or re-load) the conf; on failure keep the last good one
        (reference scheduler.go:69-85 falls back to the default)."""
        conf_str = DEFAULT_SCHEDULER_CONF
        if self.scheduler_conf:
            try:
                conf_str = read_scheduler_conf(self.scheduler_conf)
            except OSError as e:
                log.errorf(
                    "Failed to read scheduler configuration %r, using %s: %s",
                    self.scheduler_conf,
                    "previous" if self._conf_cache else "default",
                    e,
                )
                conf_str = self._conf_cache or DEFAULT_SCHEDULER_CONF
        if conf_str == self._conf_cache:
            # env flips (KBT_TRACE/KBT_EXPLAIN/KBT_FLEET) still apply
            # between conf pushes; the conf value, when set, wins
            obs.configure(self._conf_trace)
            _obs_explain.configure(self._conf_explain)
            _obs_fleet.configure(self._conf_fleet)
            return
        try:
            self.actions, self.plugins, self.action_arguments = load_scheduler_conf(
                conf_str
            )
            self._conf_cache = conf_str
            parsed = parse_scheduler_conf(conf_str)
            self._conf_streaming = parsed.streaming
            self._conf_trace = parsed.trace
            obs.configure(parsed.trace)
            self._conf_explain = parsed.explain
            _obs_explain.configure(parsed.explain)
            self._conf_fleet = parsed.fleet
            _obs_fleet.configure(parsed.fleet)
            # Conf-driven fault drills (the `faults:` key, same grammar as
            # KBT_FAULTS): armed only when the conf actually changed, so a
            # drill's fire counts are not re-armed every cycle.
            if parsed.faults:
                faults.registry.configure(parsed.faults)
        except Exception as e:  # noqa: BLE001 - bad conf must not kill the loop
            if self._conf_cache is None:
                raise
            log.errorf("Failed to load scheduler configuration, keeping previous: %s", e)

    def run(self, stop: threading.Event) -> None:
        """Start the cache and loop run_once until stopped
        (reference scheduler.go:63-86). When streaming mode is armed
        (conf `streaming:` key or KBT_STREAMING), the fixed-period sleep
        is replaced by the event-driven micro-cycle loop; flipping the
        conf key off returns here on the next iteration."""
        self.cache.run()
        self.cache.wait_for_cache_sync()
        while not stop.is_set():
            if self._streaming_on():
                self._run_streaming(stop)
                continue
            start = time.perf_counter()
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 - a bad cycle must not kill the loop
                log.errorf("scheduling cycle failed: %s", e)
            elapsed = time.perf_counter() - start
            stop.wait(max(0.0, self.schedule_period - elapsed))

    def _streaming_on(self) -> bool:
        from kube_batch_tpu import streaming

        return streaming.enabled() or self._conf_streaming

    def _run_streaming(self, stop: threading.Event) -> None:
        """The streaming loop (streaming.py): full cycles keep running
        every schedule_period as the fairness/preemption backstop; in
        between, the trigger wakes on store churn and micro-cycles
        drain the dirty-gang backlog against the resident node table.
        Any micro-cycle that cannot complete degrades to an immediate
        full cycle — arrivals are never dropped, only served slower."""
        from kube_batch_tpu import streaming

        # Federated cache (duck-typed by its slot-ownership surface):
        # peer shards' binds cross the pod filter as bound-pod
        # adds/deletes — absorb them as occupancy patches instead of
        # degrading to a full cycle per peer bind. Safe because a
        # federated cache forces conditional binds: if the absorbed view
        # ever lags, the store rejects and the retry ladder resyncs.
        absorb = hasattr(self.cache, "set_owned_slots")
        trigger = streaming.StreamTrigger(absorb_external=absorb)
        state = streaming.StreamState()
        self._stream_trigger = trigger
        self._stream_state = state
        log.infof(
            "streaming mode on: micro-cycles between full cycles every %.2fs",
            self.schedule_period,
        )
        # attach immediately before the try: anything between the
        # registration and the protecting finally is one exception away
        # from a leaked listener firing into a dead loop (KBT-C005)
        trigger.attach()
        try:
            next_full = time.monotonic()  # first full cycle immediately
            while not stop.is_set() and self._streaming_on():
                now = time.monotonic()
                if now >= next_full:
                    try:
                        self.run_once()  # harvests the resident table
                    except Exception as e:  # noqa: BLE001
                        log.errorf("scheduling cycle failed: %s", e)
                        state.invalidate("full cycle failed")
                    next_full = time.monotonic() + self.schedule_period
                    continue
                if not trigger.wait(min(next_full - now, 0.5)):
                    continue
                work = trigger.drain()
                handled = False
                try:
                    handled = self.run_micro(work)
                except Exception as e:  # noqa: BLE001
                    log.errorf(
                        "micro-cycle failed: %s; degrading to a full cycle", e
                    )
                    state.invalidate("micro-cycle failed")
                    metrics.register_micro_cycle("degraded")
                if not handled:
                    next_full = time.monotonic()  # backstop now, not in period
        finally:
            trigger.detach()
            self._stream_trigger = None
            self._stream_state = None
            log.infof("streaming mode off: back to the fixed-period loop")

    def on_owned_slots_changed(self, adopted_keys, removed_keys=()) -> None:
        """Shard-slot ownership changed mid-run (federation.py
        ShardSlotManager adoption/handoff). In streaming mode, seed the
        adopted gang keys into the trigger and prune the handed-off
        ones — the resident node table stays valid (node state did not
        change), so only the adopted keys' gangs need solving and the
        next micro-cycle serves exactly them. In periodic mode the next
        full cycle re-snapshots the widened mirror; nothing to do."""
        trigger = self._stream_trigger
        if trigger is None:
            return
        if removed_keys:
            trigger.prune(set(removed_keys))
        if adopted_keys:
            trigger.seed(set(adopted_keys))

    def run_micro(self, work) -> bool:
        """One micro-cycle over the drained churn. Returns True when the
        backlog was served (or there was nothing to solve); False means
        the caller must run a full cycle now — the resident table was
        stale/invalid, a fault fired, or the cycle aborted on deadline.
        Either way no arrival is lost: the trigger keeps every gang
        until ``prune`` sees it bound or gone."""
        from kube_batch_tpu import streaming  # noqa: F401  (docs pair this file)

        st = self._stream_state
        trigger = self._stream_trigger
        if st is None or trigger is None:
            return False
        # A previous full cycle's deferred dispatch must land before the
        # micro-cycle clones jobs (micro-cycles themselves never defer —
        # their outcome accounting reads the session synchronously).
        if not pipeline.fence.wait():
            metrics.register_micro_cycle("fence")
            log.errorf(
                "dispatch fence did not clear before micro-cycle; degrading "
                "to a full cycle (pipeline degraded: %s)",
                pipeline.fence.degraded_reason,
            )
            return False
        if not st.valid:
            metrics.register_micro_cycle("stale")
            log.V(4).infof("micro-cycle skipped: resident table invalid (%s)", st.reason)
            return False
        if work.stale:
            st.invalidate(work.stale_reason)
            metrics.register_micro_cycle("stale")
            log.infof(
                "resident table stale (%s); degrading to a full cycle",
                work.stale_reason,
            )
            return False
        with obs.span("micro_cycle", gangs=len(work.gangs)) as mspan:
            if faults.should_fire("stream.micro_cycle"):
                # injected micro-solve failure: invalidate and degrade to the
                # backstop full cycle — the backlog is untouched, no pod drops
                st.invalidate("stream.micro_cycle fault")
                metrics.register_micro_cycle("fault")
                return False
            # no _load_conf() here: conf reload (a file read + parse) stays a
            # full-cycle affair — the backstop cycle picks up pushes within
            # one schedule_period, and the micro hot path stays disk-free
            detector = None
            if mutation_detector.enabled():
                store = getattr(self.cache, "store", None)
                if store is not None:
                    detector = mutation_detector.MutationDetector(store)
                    detector.snapshot()
            if hasattr(self.cache, "cycle"):
                self.cache.cycle += 1
                mspan.set_attr("cycle", self.cache.cycle)
            st.apply_node_patches(work.node_patches)
            if work.bound_patches and not st.apply_bound_patches(work.bound_patches):
                # peer-shard occupancy churn the resident table could not
                # absorb: degrade to the backstop full cycle, backlog kept
                metrics.register_micro_cycle("stale")
                log.infof(
                    "resident table could not absorb bound-pod churn (%s); "
                    "degrading to a full cycle", st.reason,
                )
                return False
            cloned, missing = self.cache.clone_jobs_for_stream(work.gangs)
            # A gang is solvable only once enough of it exists: the podgroup
            # add event lands before its member pods, and a mid-burst drain
            # sees a partial gang — opening a session for either wastes a
            # full micro-cycle (the gang gate would discard it anyway). A
            # deferred gang stays in the backlog; its remaining pod arrivals
            # re-wake the trigger, and the backstop full cycle catches any
            # gang that never completes.
            jobs = {}
            settled = set(missing)
            for uid, job in cloned.items():
                pending = job.task_status_index.get(TaskStatus.PENDING)
                if not pending:
                    settled.add(uid)  # fully placed (or empty): nothing to solve
                elif len(job.tasks) >= job.min_available:
                    jobs[uid] = job
            if settled:
                trigger.prune(settled)
            if not jobs:
                metrics.register_micro_cycle("empty")
                return True
            from kube_batch_tpu.streaming import open_micro_session

            budget = CycleBudget(self._soft_deadline, self._hard_deadline)
            ssn = open_micro_session(
                self.cache, self.plugins, self.action_arguments,
                jobs, st.nodes, self.cache.clone_queues_for_stream(),
            )
            ssn.cycle_budget = budget
            ssn.micro_cycle = True  # xla_allocate reads this for the
            # resident-interpod hint; tests read it to prove the micro path ran
            aborted: Optional[CycleDeadlineExceeded] = None
            failed = True
            try:
                for action in self.actions:
                    try:
                        action_start = time.perf_counter()
                        action.execute(ssn)
                        metrics.update_action_duration(
                            action.name, time.perf_counter() - action_start
                        )
                        budget.check(f"after action {action.name}")
                    except CycleDeadlineExceeded as e:
                        aborted = e
                        break
                failed = False
            finally:
                if failed or aborted is not None:
                    # the session may have mutated the resident table before
                    # dying — rebuild it from the next full snapshot
                    st.invalidate("micro-cycle aborted" if aborted else "micro-cycle failed")
                else:
                    done = {
                        uid
                        for uid, job in ssn.jobs.items()
                        if not job.task_status_index.get(TaskStatus.PENDING)
                    }
                    trigger.prune(done)
                close_session(ssn, discard=failed or aborted is not None)
                self.micro_cycles_run += 1
            if aborted is not None:
                metrics.register_micro_cycle("aborted")
                metrics.register_cycle_overrun("hard")
                mspan.set_attr("aborted", str(aborted))
                obs.recorder.dump(reason="hard_deadline", min_interval_s=1.0)
                log.errorf(
                    "micro-cycle aborted: %s (session discarded; degrading to a "
                    "full cycle)", aborted,
                )
                return False
            if detector is not None:
                detector.verify()  # raises CacheMutationError on violation
            metrics.register_micro_cycle("ok")
            return True

    def run_once(self) -> None:
        """One scheduling cycle (reference scheduler.go:88-102)."""
        log.V(4).infof("Start scheduling ...")
        cycle_start = time.perf_counter()
        self._load_conf()  # before the span: a conf push may flip tracing

        with obs.span("cycle") as cspan:
            # Dispatch fence (pipeline.py, KBT_PIPELINE): the previous
            # cycle's deferred dispatch must land before this cycle
            # snapshots — same ordering the synchronous path gets for
            # free. A timeout degrades the pipeline to synchronous
            # cycles (sticky, loud) and skips this cycle; the wedged
            # dispatch stays armed so the next cycle re-joins it.
            if not pipeline.fence.wait():
                cspan.set_attr("skipped", "pipeline_fence")
                log.errorf(
                    "dispatch fence did not clear; skipping this cycle "
                    "(pipeline degraded: %s)", pipeline.fence.degraded_reason,
                )
                return

            # Bounded-staleness guard: scheduling over a stale mirror binds
            # pods onto nodes that may no longer exist — refuse the cycle
            # and let the watch client catch up (the k8s contract is the
            # same: a scheduler partitioned from the apiserver stops).
            if self._max_snapshot_age > 0:
                age_fn = getattr(self.cache, "snapshot_age", None)
                age = age_fn() if age_fn is not None else 0.0
                if age > self._max_snapshot_age:
                    metrics.register_stale_cycle_skip()
                    cspan.set_attr("skipped", "stale_snapshot")
                    log.errorf(
                        "snapshot is %.1fs stale (threshold %.1fs); refusing to "
                        "schedule this cycle", age, self._max_snapshot_age,
                    )
                    return

            # Cycle id for the write-intent journal (recovery/journal.py):
            # every bind/evict this cycle dispatches carries it, so a
            # takeover can group in-flight intents by statement.
            if hasattr(self.cache, "cycle"):
                self.cache.cycle += 1
                cspan.set_attr("cycle", self.cache.cycle)

            # Cache-mutation detector (VERDICT row 58): when enabled (tier-1
            # runs set KBT_CACHE_MUTATION_DETECTOR), digest the store's
            # objects before plugin+action execution and verify after — any
            # plugin/action mutating shared cluster state in place fires.
            detector = None
            if mutation_detector.enabled():
                store = getattr(self.cache, "store", None)
                if store is not None:
                    detector = mutation_detector.MutationDetector(store)
                    detector.snapshot()

            budget = CycleBudget(self._soft_deadline, self._hard_deadline)
            ssn = open_session(self.cache, self.plugins, self.action_arguments)
            # Actions read the budget off the session (xla_allocate threads
            # the remaining budget into its solver entry and checks it at
            # every pre-dispatch boundary).
            ssn.cycle_budget = budget
            aborted: Optional[CycleDeadlineExceeded] = None
            deferred_finish = False
            try:
                for action in self.actions:
                    try:
                        # a previous action's deferred dispatch must land
                        # before the next action reads the session
                        if ssn.deferred_dispatch is not None:
                            pipeline.join_session(ssn)
                        action_start = time.perf_counter()
                        action.execute(ssn)
                        metrics.update_action_duration(
                            action.name, time.perf_counter() - action_start
                        )
                        # post-action gate: a cycle already past its hard
                        # budget must not start the next action
                        budget.check(f"after action {action.name}")
                    except CycleDeadlineExceeded as e:
                        aborted = e
                        break
            finally:
                if ssn.deferred_dispatch is not None and aborted is None:
                    # Pipelined cycle: the last action's dispatch is in
                    # flight on the kb-write pool. Chain the cycle's tail
                    # (streaming harvest, close, e2e metrics, detector
                    # verify) behind its Future so run_once returns and
                    # the next cycle's encode/solve overlaps the
                    # dispatch; the fence keeps the cycles ordered.
                    deferred_finish = True
                    self._finish_deferred(ssn, cycle_start, detector)
                else:
                    # streaming harvest: grab the session's node table BEFORE
                    # close_session rebinds it — micro-cycles solve against this
                    # resident state until the next full cycle replaces it
                    if self._stream_state is not None:
                        self._stream_state.adopt_full_cycle(ssn, aborted=aborted is not None)
                    # discard on abort: skip the status write-back so the
                    # store stays byte-identical to the cycle's start (every
                    # abort point is pre-dispatch)
                    close_session(ssn, discard=aborted is not None)
                    metrics.update_e2e_duration(time.perf_counter() - cycle_start)
                    metrics.schedule_attempts.inc()
                    log.V(4).infof("End scheduling ...")
            if aborted is not None:
                metrics.register_cycle_overrun("hard")
                cspan.set_attr("aborted", str(aborted))
                # the interrupted cycle's spans are exactly what a
                # post-mortem needs — dump the ring (throttled)
                obs.recorder.dump(reason="hard_deadline", min_interval_s=1.0)
                log.errorf(
                    "scheduling cycle aborted: %s (session discarded; pending "
                    "gangs reschedule next cycle)", aborted,
                )
            elif budget.soft_exceeded():
                self._arm_tier_downgrade(budget)
            else:
                self._soft_overruns = 0  # a within-budget cycle clears the streak
            if detector is not None and not deferred_finish:
                detector.verify()  # raises CacheMutationError on violation

    def _finish_deferred(self, ssn, cycle_start: float, detector) -> None:
        """Chain a pipelined cycle's tail behind its deferred dispatch.
        Runs on the kb-write pool thread when the dispatch lands; any
        failure is logged and degrades the pipeline (the synchronous
        path would have surfaced it through run()'s catch-log)."""
        stream_state = self._stream_state

        def _finish(_fut) -> None:
            try:
                if stream_state is not None:
                    stream_state.adopt_full_cycle(ssn, aborted=False)
                close_session(ssn)  # joins the (now done) deferred future
                metrics.update_e2e_duration(time.perf_counter() - cycle_start)
                metrics.schedule_attempts.inc()
                if detector is not None:
                    detector.verify()  # raises CacheMutationError on violation
                log.V(4).infof("End scheduling ...")
            except Exception as e:  # noqa: BLE001 - must not kill the pool thread
                log.errorf("pipelined cycle tail failed: %s", e)
                pipeline.fence.degrade(f"cycle tail raised {type(e).__name__}: {e}")

        ssn.deferred_dispatch.add_done_callback(_finish)

    def _arm_tier_downgrade(self, budget: CycleBudget) -> None:
        """Soft overrun: consecutive slow cycles trip the breaker of the
        tier that ran them (faults/ladder.py), routing the next cycles
        one rung down — instead of the cycle stalling until the lease
        watchdog calls a healthy leader dead. The streak is counted
        here (see __init__) and the trip reuses the breaker automaton:
        open -> backoff -> half-open probe -> close."""
        metrics.register_cycle_overrun("soft")
        self._soft_overruns += 1
        tier = next(
            (
                getattr(a, "last_solver_tier", None)
                for a in self.actions
                if getattr(a, "last_solver_tier", None) not in (None, "none")
            ),
            None,
        )
        if tier == "sharded_xla":
            tier = "xla"  # the sharded rung shares the xla breaker
        ladder = faults.solver_ladder
        breaker = ladder.breakers.get(tier)
        if breaker is None:
            log.warningf(
                "cycle exceeded soft deadline (%.2fs > %.2fs) on tier %s "
                "(no breaker to arm)", budget.elapsed(), budget.soft_s, tier,
            )
            return
        if self._soft_overruns >= breaker.failure_threshold:
            ladder.trip(tier)
            self._soft_overruns = 0
            log.warningf(
                "cycle exceeded soft deadline (%.2fs > %.2fs) repeatedly; "
                "tripped solver tier %s (ladder downgrades until the "
                "recovery probe closes it)", budget.elapsed(), budget.soft_s, tier,
            )
        else:
            log.warningf(
                "cycle exceeded soft deadline (%.2fs > %.2fs) on tier %s "
                "(downgrade trips after %d consecutive overruns)",
                budget.elapsed(), budget.soft_s, tier, breaker.failure_threshold,
            )
