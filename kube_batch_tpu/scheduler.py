"""L6: the scheduler loop (reference pkg/scheduler/scheduler.go:35-102).

``Scheduler`` owns a cache and drives the session pipeline on a fixed
period: every cycle it (re-)loads the scheduler configuration, opens a
session over a fresh ``cache.snapshot()``, runs the configured actions
in order, and records per-action and end-to-end latency — the metric
families the reference emits from the same spot
(scheduler.go:88-102).

Divergences from the reference, by design:

- the conf file is re-read **every cycle** (the reference loads it once
  at startup, scheduler.go:63-85); a conf push takes effect on the next
  cycle without a restart, and a broken conf falls back to the previous
  good one rather than killing the loop;
- the default action pipeline is ``enqueue, allocate, backfill``: the
  reference's ``allocate, backfill`` default (util.go:31-42) relies on
  Go's zero-value PodGroup phase ("") passing allocate's Pending gate
  (allocate.go:52); our object model defaults the phase to Pending, so
  the enqueue action (enqueue.go:66-119) owns that gate explicitly.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import kube_batch_tpu.actions  # noqa: F401  (registers the action pipeline)
import kube_batch_tpu.plugins  # noqa: F401  (registers the plugin builders)
from kube_batch_tpu import faults, log, metrics
from kube_batch_tpu.conf import (
    load_scheduler_conf,
    parse_scheduler_conf,
    read_scheduler_conf,
)
from kube_batch_tpu.faults import mutation_detector
from kube_batch_tpu.framework import close_session, open_session

DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


class Scheduler:
    """reference scheduler.go:35-61."""

    def __init__(
        self,
        cache,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = 1.0,
    ) -> None:
        # A scheduler process wants the persistent XLA compile cache
        # (restart/failover skips the bucket compiles); the call is lazy
        # so embedders who configure jax themselves are never overridden.
        from kube_batch_tpu.ops import enable_compilation_cache

        enable_compilation_cache()
        self.cache = cache
        self.scheduler_conf = scheduler_conf  # path; None -> default conf
        self.schedule_period = schedule_period
        self.actions = []
        self.plugins = []
        self.action_arguments: dict[str, dict[str, str]] = {}
        self._conf_cache: Optional[str] = None
        self._load_conf()

    def _load_conf(self) -> None:
        """Load (or re-load) the conf; on failure keep the last good one
        (reference scheduler.go:69-85 falls back to the default)."""
        conf_str = DEFAULT_SCHEDULER_CONF
        if self.scheduler_conf:
            try:
                conf_str = read_scheduler_conf(self.scheduler_conf)
            except OSError as e:
                log.errorf(
                    "Failed to read scheduler configuration %r, using %s: %s",
                    self.scheduler_conf,
                    "previous" if self._conf_cache else "default",
                    e,
                )
                conf_str = self._conf_cache or DEFAULT_SCHEDULER_CONF
        if conf_str == self._conf_cache:
            return
        try:
            self.actions, self.plugins, self.action_arguments = load_scheduler_conf(
                conf_str
            )
            self._conf_cache = conf_str
            # Conf-driven fault drills (the `faults:` key, same grammar as
            # KBT_FAULTS): armed only when the conf actually changed, so a
            # drill's fire counts are not re-armed every cycle.
            spec = parse_scheduler_conf(conf_str).faults
            if spec:
                faults.registry.configure(spec)
        except Exception as e:  # noqa: BLE001 - bad conf must not kill the loop
            if self._conf_cache is None:
                raise
            log.errorf("Failed to load scheduler configuration, keeping previous: %s", e)

    def run(self, stop: threading.Event) -> None:
        """Start the cache and loop run_once until stopped
        (reference scheduler.go:63-86)."""
        self.cache.run()
        self.cache.wait_for_cache_sync()
        while not stop.is_set():
            start = time.perf_counter()
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 - a bad cycle must not kill the loop
                log.errorf("scheduling cycle failed: %s", e)
            elapsed = time.perf_counter() - start
            stop.wait(max(0.0, self.schedule_period - elapsed))

    def run_once(self) -> None:
        """One scheduling cycle (reference scheduler.go:88-102)."""
        log.V(4).infof("Start scheduling ...")
        cycle_start = time.perf_counter()
        self._load_conf()

        # Cache-mutation detector (VERDICT row 58): when enabled (tier-1
        # runs set KBT_CACHE_MUTATION_DETECTOR), digest the store's
        # objects before plugin+action execution and verify after — any
        # plugin/action mutating shared cluster state in place fires.
        detector = None
        if mutation_detector.enabled():
            store = getattr(self.cache, "store", None)
            if store is not None:
                detector = mutation_detector.MutationDetector(store)
                detector.snapshot()

        ssn = open_session(self.cache, self.plugins, self.action_arguments)
        try:
            for action in self.actions:
                action_start = time.perf_counter()
                action.execute(ssn)
                metrics.update_action_duration(
                    action.name, time.perf_counter() - action_start
                )
        finally:
            close_session(ssn)
            metrics.update_e2e_duration(time.perf_counter() - cycle_start)
            metrics.schedule_attempts.inc()
            log.V(4).infof("End scheduling ...")
        if detector is not None:
            detector.verify()  # raises CacheMutationError on violation
