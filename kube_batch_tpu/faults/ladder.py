"""Degradation ladder: health-scored circuit breakers per solver tier.

The pre-ladder fallback chain was one-way inside a cycle (pallas solve
raises -> XLA twin; XLA raises -> the cycle is lost) and carried no
health state across cycles: a tier that failed once was retried blindly
every cycle, and a tier demoted by a construction failure gave no signal
beyond a log line. The ladder replaces that with the standard breaker
automaton per tier:

- CLOSED: healthy; every cycle may use the tier.
- OPEN: after ``failure_threshold`` consecutive failures the tier sits
  out ``reset_timeout`` seconds (the backoff), during which ``allow()``
  is False and callers route to the next rung down.
- HALF_OPEN: once the backoff elapses, exactly one probe is allowed
  through. Probe success -> CLOSED (backoff resets); probe failure ->
  OPEN again with the backoff doubled (``backoff_factor``), capped at
  ``max_backoff``.

The solver ladder runs mesh_pallas (blocked sharded-Pallas, when a mesh
is resolved) -> pallas (single-chip fused kernel) -> xla (the while-loop
twin) -> serial.

Every transition emits a metric (breaker_transitions counter +
breaker_state gauge) and a glog line, so a drill — or a real outage —
is visible on ``/metrics`` as open -> half_open -> closed history.

The bottom rung of a ``DegradationLadder`` has no breaker: serial is
the correctness oracle and must always be available.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from kube_batch_tpu import log, metrics

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

_GAUGE = {CLOSED: 0.0, HALF_OPEN: 1.0, OPEN: 2.0}


class CircuitBreaker:
    """One tier's health automaton (see module docstring)."""

    def __init__(
        self,
        name: str,
        failure_threshold: int = 3,
        reset_timeout: float = 30.0,
        backoff_factor: float = 2.0,
        max_backoff: float = 600.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = float(reset_timeout)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff = float(max_backoff)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.failures = 0
        self._backoff = self.reset_timeout
        self._opened_at = 0.0
        metrics.set_breaker_state(name, _GAUGE[CLOSED])

    def _transition(self, to: str) -> None:
        # lock held by caller
        frm, self.state = self.state, to
        metrics.register_breaker_transition(self.name, frm, to)
        metrics.set_breaker_state(self.name, _GAUGE[to])
        extra = f" (recovery probe in {self._backoff:.1f}s)" if to == OPEN else ""
        log.warningf("breaker %s: %s -> %s%s", self.name, frm, to, extra)

    def allow(self) -> bool:
        """May the tier be used right now? An OPEN breaker whose backoff
        has elapsed transitions to HALF_OPEN and admits the caller as the
        recovery probe."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if self._clock() - self._opened_at >= self._backoff:
                    self._transition(HALF_OPEN)
                    return True
                return False
            # HALF_OPEN: a probe is in flight; the solve path is driven
            # by the single scheduler loop, so admitting the caller is
            # the probe continuing, not a thundering herd.
            return True

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._backoff = self.reset_timeout
            if self.state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == HALF_OPEN:
                # failed probe: back off harder before the next one
                self._backoff = min(self._backoff * self.backoff_factor, self.max_backoff)
                self._opened_at = self._clock()
                self._transition(OPEN)
            elif self.state == CLOSED and self.failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(OPEN)

    def trip(self) -> None:
        """Force-open regardless of the failure count — for evidence the
        automaton's own counters cannot see, e.g. the scheduler's
        consecutive soft-deadline overruns (a slow-but-*successful*
        solve records a success each cycle, so per-call failures never
        accumulate). Recovery is the normal half-open probe path."""
        with self._lock:
            self.failures = self.failure_threshold
            if self.state != OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN)

    def reset(self) -> None:
        with self._lock:
            self.failures = 0
            self._backoff = self.reset_timeout
            if self.state != CLOSED:
                self._transition(CLOSED)


class DegradationLadder:
    """Ordered tiers, best first; a breaker per tier except the last
    (the always-available floor — serial, the correctness oracle)."""

    def __init__(
        self, tiers=("mesh_pallas", "pallas", "xla", "serial"), **breaker_kw
    ) -> None:
        self.tiers = tuple(tiers)
        self.breakers: dict[str, CircuitBreaker] = {
            t: CircuitBreaker(t, **breaker_kw) for t in self.tiers[:-1]
        }

    def allow(self, tier: str) -> bool:
        b = self.breakers.get(tier)
        return True if b is None else b.allow()

    def record_success(self, tier: str) -> None:
        b = self.breakers.get(tier)
        if b is not None:
            b.record_success()

    def record_failure(self, tier: str) -> None:
        b = self.breakers.get(tier)
        if b is not None:
            b.record_failure()

    def trip(self, tier: str) -> None:
        b = self.breakers.get(tier)
        if b is not None:
            b.trip()

    def state(self, tier: str) -> str:
        b = self.breakers.get(tier)
        return CLOSED if b is None else b.state

    def reset(self) -> None:
        for b in self.breakers.values():
            b.reset()
