"""Fault injection + degradation ladder for the solve/bind/lease pipeline.

Production schedulers of this class treat controlled degradation and
failure drills as first-class (Kant, arxiv 2510.01256; RLScheduler,
arxiv 1910.08925); this package gives kube-batch-tpu the same
discipline:

- a deterministic, env/conf-driven **fault registry** (`registry`):
  named injection points with probability / count / seed semantics,
  armed via ``KBT_FAULTS`` or the scheduler conf's ``faults:`` key and
  checked at the five places failures actually happen — solver entry
  (actions/xla_allocate), the cache write side (cache/cache), the watch
  hub and lease elector (server), and the native extension boundary
  (ops / the bulk replay);
- a **degradation ladder** (`ladder.DegradationLadder`): a health-scored
  circuit breaker per solver tier (blocked sharded-Pallas -> pallas ->
  XLA twin -> serial) with
  exponential-backoff recovery probes, replacing the old one-way
  exception fallback (a single pallas failure used to demote the tier
  for the process lifetime with no recovery signal);
- a **cache-mutation detector** (`mutation_detector.MutationDetector`):
  the role of the reference's ``KUBE_CACHE_MUTATION_DETECTOR=true`` gate
  (hack/make-rules/test.sh:27-28), enabled in tier-1 runs via
  ``KBT_CACHE_MUTATION_DETECTOR``.

Every injected fault and every breaker transition emits a metric
(metrics.fault_injections / breaker_transitions / breaker_state) and a
glog line, so a drill is observable end to end on ``/metrics``.

Spec grammar (``KBT_FAULTS`` env var or conf ``faults:`` string)::

    point[:probability[:count[:seed]]][,point2...]

    KBT_FAULTS="bind.write:1:2"          # first two bind writes fail
    KBT_FAULTS="solve.xla,watch.drop:0.5"  # every xla solve; half of polls
    KBT_FAULTS="lease.renew:1:3:42"      # 3 renewals fail, RNG seed 42

``probability`` defaults to 1, ``count`` (max fires) to unlimited, and
``off`` as the probability disarms the point. Probability draws come
from a per-point RNG seeded from (global seed, point name) — a drill
replays identically given the same spec and call sequence.
"""

from __future__ import annotations

import os
import random
import threading
import zlib
from dataclasses import dataclass
from typing import Optional

from kube_batch_tpu import log, metrics
from kube_batch_tpu.faults.ladder import CircuitBreaker, DegradationLadder  # noqa: F401

__all__ = [
    "POINTS",
    "FaultInjected",
    "FaultRegistry",
    "registry",
    "should_fire",
    "CircuitBreaker",
    "DegradationLadder",
    "solver_ladder",
]


class FaultInjected(RuntimeError):
    """Raised by call sites when their injection point fires — typed so
    chaos tests can tell an injected failure from an organic one."""


# The named injection points, one cluster per subsystem where failures
# actually happen. configure()/arm() reject unknown names so a typo in a
# drill spec is loud instead of silently never firing.
POINTS = (
    # solver entry (actions/xla_allocate.py)
    "solve.mesh_pallas",  # blocked sharded-Pallas raises -> mesh XLA rung
    "solve.pallas",     # pallas compile/solve raises -> XLA twin
    "solve.xla",        # XLA twin solve raises -> serial for the cycle
    "solve.nan",        # NaN poisons a score tensor -> finite guard -> serial
    "solve.class_table",  # poisoned/stale class table -> uncompressed solve, loud
    # cache write side (cache/cache.py)
    "bind.write",       # binder write rejected -> retry w/ jitter -> errTasks
    "bind.slow",        # slow binder (50ms stall per attempt)
    "evict.write",      # evictor write rejected -> retry -> errTasks
    # watch hub (server.py)
    "watch.drop",       # stream drop: poll returns 410-Gone, client re-lists
    # lease elector (server.py)
    "lease.renew",      # renewal round-trip fails (arbiter partition/timeout)
    # crash-consistent failover (recovery/)
    "journal.append",   # WAL append fails -> write dispatches unjournaled, loudly
    "journal.replay",   # journal unreadable at takeover -> resync self-heal
    "reconcile.scan",   # takeover scan dies mid-way -> partial, rescheduling heals
    "cycle.overrun",    # injected wedged solve -> hard-deadline abort pre-dispatch
    # incremental encode cache (ops/encode_cache.py)
    "encode.cache",     # cache poisoned -> state dropped, encode runs cold
    # streaming micro-cycles (scheduler.py run_micro)
    "stream.micro_cycle",  # micro-cycle solve fails -> degrade to full cycle, no pod dropped
    # pipelined cycles (pipeline.py DispatchFence)
    "pipeline.fence",   # deferred dispatch wedged -> fence timeout -> sync degrade
    # sharded federation (cache/store.py, cache/backend.py, federation.py)
    "store.conflict",      # conditional write rejected -> loser resyncs gang + retries
    "store.txn_batch",     # coalesced txn round trip fails -> per-gang v1 writes, loudly
    "federation.partition",  # loopback backend transport drops -> backoff + relist heal
    "federation.stale_assign",  # dispatch carries a stale snapshot version on purpose
    # leased shard slots (federation.py ShardSlotManager)
    "shard.adopt",      # adoption takeover fails -> breaker-backed retry next probe
    "shard.handoff",    # graceful handoff aborts mid-drain -> slot kept, loudly
    "shard.lease_flap",  # own slot renewal dropped once -> reacquire, no double-adopt
    # native extension boundary (ops/, the bulk replay)
    "native.load",      # extension unavailable for the cycle -> Python twins
    "native.prepass",   # bulk_assign prepass raises -> Python replay
    "native.dispatch",  # bulk_dispatch raises -> Python dispatch barrier
    "native.class_dedup",  # class_dedup unavailable -> np.unique fallback
    # streaming federation watch pump (cache/backend.py pump)
    "stream.pump",      # pump round dropped -> mirror ages, backstop full cycle
    # admission control plane (admission.py, server.py front door)
    "admission.shed",   # gate sheds an admit that would have passed -> 429
    "admission.controller",  # controller tick dies -> fail-static last outputs
)


@dataclass
class _Rule:
    point: str
    probability: float = 1.0
    count: Optional[int] = None  # max fires; None = unlimited
    fired: int = 0
    rng: Optional[random.Random] = None


class FaultRegistry:
    """Thread-safe registry of armed injection points."""

    def __init__(self, spec: Optional[str] = None, seed: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._rules: dict[str, _Rule] = {}
        self._seed = seed if seed is not None else int(
            os.environ.get("KBT_FAULTS_SEED", "0") or 0
        )
        if spec is None:
            spec = os.environ.get("KBT_FAULTS", "")
        if spec:
            self.configure(spec)

    # -- arming --------------------------------------------------------------

    def _point_rng(self, point: str, seed: Optional[int]) -> random.Random:
        if seed is None:
            seed = self._seed ^ zlib.crc32(point.encode())
        return random.Random(seed)

    def arm(
        self,
        point: str,
        probability: float = 1.0,
        count: Optional[int] = None,
        seed: Optional[int] = None,
    ) -> None:
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r} (known: {', '.join(POINTS)})")
        with self._lock:
            self._rules[point] = _Rule(
                point=point,
                probability=float(probability),
                count=count,
                rng=self._point_rng(point, seed),
            )
        log.infof(
            "fault point %s armed (p=%g count=%s)",
            point, probability, "inf" if count is None else count,
        )

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._rules.clear()
            else:
                self._rules.pop(point, None)

    def reset(self) -> None:
        """Drop every rule (test hygiene between drills)."""
        self.disarm()

    def configure(self, spec: str) -> None:
        """Parse and arm a drill spec (see module docstring). Invalid
        entries are logged and skipped — a bad conf push must not kill
        the scheduling loop (scheduler.py's conf-reload rule)."""
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            point = parts[0].strip()
            try:
                if len(parts) > 1 and parts[1].strip().lower() == "off":
                    if point not in POINTS:
                        raise ValueError(f"unknown fault point {point!r}")
                    self.disarm(point)
                    continue
                prob = float(parts[1]) if len(parts) > 1 and parts[1].strip() else 1.0
                count = int(parts[2]) if len(parts) > 2 and parts[2].strip() else None
                seed = int(parts[3]) if len(parts) > 3 and parts[3].strip() else None
                self.arm(point, probability=prob, count=count, seed=seed)
            except ValueError as e:
                log.errorf("ignoring invalid fault spec entry %r: %s", entry, e)

    def active(self) -> dict[str, tuple[float, Optional[int], int]]:
        """point -> (probability, count, fired) for introspection."""
        with self._lock:
            return {
                p: (r.probability, r.count, r.fired) for p, r in self._rules.items()
            }

    # -- firing --------------------------------------------------------------

    def should_fire(self, point: str) -> bool:
        """True when the named point is armed and its probability/count
        say this call fails. A True return is already metered and logged;
        the call site only has to take its degraded branch (or raise
        ``FaultInjected`` where an exception is the failure mode)."""
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return False
            if rule.count is not None and rule.fired >= rule.count:
                return False
            if rule.probability < 1.0 and rule.rng.random() >= rule.probability:
                return False
            rule.fired += 1
            fired = rule.fired
        metrics.register_fault_injection(point)
        log.warningf("fault injected: %s (fire #%d)", point, fired)
        # Snapshot the flight recorder at the moment of injection: the
        # spans leading up to the fault are exactly what a drill wants
        # to read post-mortem. Lazy import (obs imports faults' peers,
        # never the reverse at module level) and throttled so a
        # probability-armed point firing every cycle cannot turn the
        # dump dir into a firehose.
        from kube_batch_tpu import obs

        obs.recorder.dump(reason=f"fault:{point}", min_interval_s=5.0)
        return True


registry = FaultRegistry()


def should_fire(point: str) -> bool:
    return registry.should_fire(point)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        log.errorf("%s=%r is not an integer; using %d", name, os.environ.get(name), default)
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        log.errorf("%s=%r is not a number; using %g", name, os.environ.get(name), default)
        return default


# The process-wide solver ladder (blocked sharded-Pallas -> single-chip
# pallas -> XLA twin -> serial), shared by every xla_allocate execution
# so breaker state persists across cycles and conf reloads. mesh_pallas
# is the top rung when a mesh is resolved; on a single chip the ladder
# starts at pallas. Tests swap in a short-timeout instance.
solver_ladder = DegradationLadder(
    ("mesh_pallas", "pallas", "xla", "serial"),
    failure_threshold=_env_int("KBT_BREAKER_THRESHOLD", 3),
    reset_timeout=_env_float("KBT_BREAKER_RESET_S", 30.0),
)
