"""Cache-mutation detector (VERDICT r5 row 58 / "What's missing" #2).

The reference gates every unit run on ``KUBE_CACHE_MUTATION_DETECTOR=true``
(hack/make-rules/test.sh:27-28): the k8s mutation detector deep-copies
each informer-cache object and panics when shared state is mutated in
place. The equivalent risk here is real — the ClusterStore's objects are
shared by reference across the cache mirror, every session snapshot
(TaskInfo.pod, JobInfo.pod_group), the watch hub serializers, and the
async write pool — and correctness rests on the convention that every
legitimate write goes through ``dataclasses.replace`` + ``store.update_*``
(object REPLACEMENT, never in-place mutation).

Mechanics: ``snapshot()`` records (object identity, content digest) for
every stored object; ``verify()`` re-digests and fires for any object
whose identity is unchanged (no store update replaced it) but whose
content differs — that is precisely an in-place mutation of shared
cluster state. Records hold strong references, so id() reuse cannot
alias a freed object.

One deliberate mask: PodGroup ``status`` is excluded from the digest.
The scheduler itself owns status write-back (close_session ->
update_job_status), and ``JobInfo.clone`` shares the PodGroup object
with the mirror by design (api/job_info.py), so status mutation is the
sanctioned channel; spec/metadata mutations still fire.

Enabled via ``KBT_CACHE_MUTATION_DETECTOR`` (the tier-1 conftest turns
it on, mirroring the reference's test gate); the scheduler loop wires it
around each cycle when enabled.
"""

from __future__ import annotations

import hashlib
import os

from kube_batch_tpu import log, metrics
from kube_batch_tpu.cache.store import KINDS, POD_GROUPS, obj_key

ENV = "KBT_CACHE_MUTATION_DETECTOR"


def enabled() -> bool:
    return os.environ.get(ENV, "").strip().lower() in ("1", "true", "yes", "on")


class CacheMutationError(AssertionError):
    """Shared cluster state was mutated in place (the k8s mutation
    detector's panic, typed)."""


def _digest(kind: str, obj) -> str:
    if kind == POD_GROUPS:
        body = repr((obj.metadata, obj.spec))
    else:
        body = repr(obj)
    return hashlib.sha1(body.encode()).hexdigest()


class MutationDetector:
    """Digest-before / verify-after guard over one ClusterStore."""

    def __init__(self, store) -> None:
        self._store = store
        # (kind, key) -> (the object itself, digest). The strong ref both
        # pins identity semantics and keeps digesting race-free: objects
        # are only ever REPLACED under the store lock, never mutated by
        # legitimate writers.
        self._records: dict[tuple[str, str], tuple[object, str]] = {}

    def snapshot(self) -> None:
        self._records.clear()
        for kind in KINDS:
            for obj in self._store.list(kind):
                self._records[(kind, obj_key(kind, obj))] = (obj, _digest(kind, obj))

    def violations(self) -> list[str]:
        out: list[str] = []
        for kind in KINDS:
            for obj in self._store.list(kind):
                rec = self._records.get((kind, obj_key(kind, obj)))
                if rec is None or rec[0] is not obj:
                    # new since snapshot, or legitimately replaced via
                    # store.update_* — not ours to judge
                    continue
                if rec[1] != _digest(kind, obj):
                    out.append(f"{kind}/{obj_key(kind, obj)}")
        return out

    def verify(self) -> None:
        """Raise CacheMutationError (after metering + logging) if any
        cached object was mutated in place since snapshot()."""
        bad = self.violations()
        if not bad:
            return
        for name in bad:
            metrics.register_cache_mutation(name.split("/", 1)[0])
            log.errorf("cache mutation detected: %s was mutated in place", name)
        raise CacheMutationError(
            "cached cluster objects mutated in place (writes must go through "
            f"dataclasses.replace + store.update_*): {', '.join(bad)}"
        )
