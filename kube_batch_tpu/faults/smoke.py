"""Fast chaos smoke: one injected fault per subsystem, each driven
through a real scheduling path, asserting binds still land and the
degraded path engaged. Wired into ``hack/verify.py`` (gate 5) so the
static gate also proves the failure drills work in this image; the full
chaos suite lives in ``tests/test_faults.py``.

Usage:  python -m kube_batch_tpu.faults.smoke
Exit 0 iff every drill passes.
"""

from __future__ import annotations

import os
import sys
import threading
import time


def _session_binds(expect_timing: str) -> None:
    """One xla_allocate session over a 12-pod/3-gang cluster; asserts all
    12 binds land and the action reports the expected path marker."""
    import kube_batch_tpu.actions.xla_allocate as XA
    from kube_batch_tpu.conf import parse_scheduler_conf
    from kube_batch_tpu.framework import close_session, open_session
    from kube_batch_tpu.testing import (
        FakeCache,
        build_cluster,
        build_node,
        build_pod,
        build_pod_group,
        build_queue,
        build_resource_list,
    )

    conf = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""
    pods = [
        build_pod(
            name=f"p{i}", group_name=f"g{i % 3}",
            req=build_resource_list(cpu=1, memory="512Mi"),
        )
        for i in range(12)
    ]
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=16))
        for i in range(4)
    ]
    cluster = build_cluster(
        pods, nodes,
        [build_pod_group(f"g{j}", min_member=4) for j in range(3)],
        [build_queue("default")],
    )
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(conf).tiers)
    action = XA.XlaAllocateAction()
    action.execute(ssn)
    close_session(ssn)
    assert len(cache.binder.binds) == 12, f"only {len(cache.binder.binds)}/12 binds"
    assert expect_timing in action.last_timings, action.last_timings


def drill_solver() -> None:
    from kube_batch_tpu import faults

    faults.registry.arm("solve.xla", count=1)
    _session_binds("serial_degraded_s")


def drill_native() -> None:
    from kube_batch_tpu import faults

    faults.registry.arm("native.load")
    _session_binds("solve_s")


def drill_bind() -> None:
    from kube_batch_tpu import faults
    from kube_batch_tpu.cache import ClusterStore, SchedulerCache
    from kube_batch_tpu.scheduler import Scheduler
    from kube_batch_tpu.testing import build_node, build_pod, build_queue, build_resource_list

    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=8, memory="8Gi", pods=16)))
    store.create_queue(build_queue("default"))
    store.create_pod(build_pod(name="p0", req=build_resource_list(cpu=1, memory="1Gi")))
    faults.registry.arm("bind.write", count=1)
    Scheduler(SchedulerCache(store), schedule_period=0.05).run_once()
    pod = store.get_pod("default", "p0")
    assert pod is not None and pod.node_name, "bind did not land after retry"


def drill_watch() -> None:
    from kube_batch_tpu import faults
    from kube_batch_tpu.cache import ClusterStore
    from kube_batch_tpu.server import WatchHub

    store = ClusterStore()
    hub = WatchHub(store)
    faults.registry.arm("watch.drop", count=1)
    status, events, _rv = hub.poll("queues", 0, 0.1, threading.Event())
    assert status == "gone", "injected drop did not surface as 410-Gone"
    status, _, _ = hub.poll("queues", 0, 0.05, threading.Event())
    assert status == "ok", "poll did not recover after the drop"


def drill_lease() -> None:
    from kube_batch_tpu import faults
    from kube_batch_tpu.cache import ClusterStore
    from kube_batch_tpu.server import StoreLeaseElector

    store = ClusterStore()
    elector = StoreLeaseElector(
        store, "smoke", "a", lease_duration=30.0,
        renew_deadline=0.3, retry_period=0.1,
    )
    assert elector.acquire(blocking=False)
    faults.registry.arm("lease.renew")
    lost = threading.Event()
    elector.start_renewing(lost.set)
    assert lost.wait(2.0), "partitioned leader never fired on_lost"
    faults.registry.reset()
    # the loss path released: a standby gets the 30s lease immediately
    lease = store.try_acquire_lease("smoke", "b", 15.0)
    assert lease.holder_identity == "b", "lease not released on loss"


def drill_mutation_detector() -> None:
    from kube_batch_tpu.cache import ClusterStore
    from kube_batch_tpu.faults.mutation_detector import CacheMutationError, MutationDetector
    from kube_batch_tpu.testing import build_node, build_resource_list

    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=1, memory="1Gi")))
    det = MutationDetector(store)
    det.snapshot()
    store.list("nodes")[0].metadata.labels["mutated"] = "1"
    try:
        det.verify()
    except CacheMutationError:
        return
    raise AssertionError("seeded cache mutation was not detected")


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("KBT_MIN_DEVICE_PAIRS", "0")
    from kube_batch_tpu import faults

    drills = (
        ("solver (solve.xla -> serial degradation)", drill_solver),
        ("native boundary (native.load -> Python twins)", drill_native),
        ("cache write (bind.write -> retry w/ jitter)", drill_bind),
        ("watch hub (watch.drop -> 410-Gone)", drill_watch),
        ("lease elector (lease.renew -> on_lost + release)", drill_lease),
        ("cache-mutation detector (seeded violation fires)", drill_mutation_detector),
    )
    failed = 0
    for name, drill in drills:
        faults.registry.reset()
        faults.solver_ladder.reset()
        t0 = time.perf_counter()
        try:
            drill()
        except Exception as e:  # noqa: BLE001 - report every drill
            failed += 1
            print(f"chaos smoke: {name}: FAILED ({e})")
        else:
            print(f"chaos smoke: {name}: ok ({time.perf_counter() - t0:.2f}s)")
        finally:
            faults.registry.reset()
            faults.solver_ladder.reset()
    print("chaos smoke:", "FAILED" if failed else "ok", f"({len(drills)} drills)")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
