"""Chaos suite: the fault-injection registry and degradation ladder
(ISSUE 1 tentpole) driven end-to-end through real scheduling sessions.

Each test arms one (or more) of the named injection points —
solver, cache write side, watch hub, lease elector, native extension
boundary — runs a full scheduling session, and asserts bind-for-bind
correctness against an un-faulted twin: under injected failure the
pipeline may get *slower* (retries, serial degradation), never *wrong*.
Plus: a breaker open -> probe -> close cycle at both unit and session
level, and the fault/ladder metric families visible on /metrics.

Runs by default in the tier-1 suite (the `chaos` marker exists so soak
variants can be split out as `slow`).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu import faults, metrics
from kube_batch_tpu.cache import ClusterStore, SchedulerCache
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.faults.ladder import CLOSED, HALF_OPEN, OPEN, DegradationLadder
from kube_batch_tpu.faults.mutation_detector import (
    CacheMutationError,
    MutationDetector,
)
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.scheduler import Scheduler
from kube_batch_tpu.server import SchedulerServer, StoreLeaseElector
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

from test_xla_allocate import DEFAULT_TIERS_YAML

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_faults():
    """No drill outlives its test: registry and breaker state reset."""
    faults.registry.reset()
    faults.solver_ladder.reset()
    yield
    faults.registry.reset()
    faults.solver_ladder.reset()


def wait_until(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# -- session helpers ---------------------------------------------------------


def make_cluster():
    """3 gangs x 4 pods on 4 nodes — enough structure that a wrong
    degradation path produces visibly different placements."""
    pods = [
        build_pod(
            name=f"p{i}", group_name=f"g{i % 3}",
            req=build_resource_list(cpu=1, memory="512Mi"),
        )
        for i in range(12)
    ]
    nodes = [
        build_node(f"n{i}", build_resource_list(cpu=8, memory="8Gi", pods=16))
        for i in range(4)
    ]
    pgs = [build_pod_group(f"g{j}", min_member=4) for j in range(3)]
    return build_cluster(pods, nodes, pgs, [build_queue("default")])


def run_xla_session():
    """One xla_allocate session over a fresh cluster; returns (binds,
    action) — binds as {ns/name: node}."""
    import kube_batch_tpu.actions.xla_allocate as XA

    cache = FakeCache(make_cluster())
    ssn = open_session(cache, parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers)
    action = XA.XlaAllocateAction()
    action.execute(ssn)
    close_session(ssn)
    return dict(cache.binder.binds), action


# -- 1. solver entry ---------------------------------------------------------


def test_solver_fault_degrades_to_serial_with_identical_binds():
    """solve.xla: the XLA twin raises mid-cycle -> the ladder's bottom
    rung (serial) finishes the cycle with bind-for-bind identical output,
    and the injection + degradation are metered."""
    clean, a_clean = run_xla_session()
    assert "solve_s" in a_clean.last_timings  # device path engaged

    before = metrics.degraded_cycles.value({"tier": "serial", "reason": "solve_failed"})
    faults.registry.arm("solve.xla", count=1)
    faulted, a_fault = run_xla_session()
    assert "serial_degraded_s" in a_fault.last_timings
    assert faulted == clean and len(faulted) == 12
    assert metrics.fault_injections.value({"point": "solve.xla"}) >= 1
    assert (
        metrics.degraded_cycles.value({"tier": "serial", "reason": "solve_failed"})
        == before + 1
    )


def test_nan_poisoned_score_tensor_hits_finite_guard():
    """solve.nan: a NaN in a score tensor must never reach the kernel —
    the finite guard degrades the cycle to serial, binds unchanged."""
    clean, _ = run_xla_session()
    before = metrics.degraded_cycles.value({"tier": "serial", "reason": "nonfinite"})
    faults.registry.arm("solve.nan", count=1)
    faulted, a = run_xla_session()
    assert "serial_degraded_s" in a.last_timings
    assert faulted == clean
    assert (
        metrics.degraded_cycles.value({"tier": "serial", "reason": "nonfinite"})
        == before + 1
    )


# -- 2. native extension boundary -------------------------------------------


def test_native_boundary_faults_fall_back_to_python_twins():
    """native.load / native.prepass / native.dispatch: with every native
    fast path failing, the Python twins produce identical binds through
    the device solve (the prepass contract: failures are pre-mutation)."""
    clean, _ = run_xla_session()
    for point in ("native.load", "native.prepass", "native.dispatch"):
        faults.registry.reset()
        faults.registry.arm(point)
        faulted, a = run_xla_session()
        assert "solve_s" in a.last_timings, (point, a.last_timings)
        assert faulted == clean, point


# -- 3. cache write side -----------------------------------------------------


def test_bind_rejection_retries_with_jitter_then_lands():
    """bind.write: the first two write attempts are rejected; the
    retry-with-jitter ladder lands the bind within the same cycle and
    the retries are metered."""
    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=8, memory="8Gi", pods=16)))
    store.create_queue(build_queue("default"))
    for i in range(3):
        store.create_pod(
            build_pod(name=f"p{i}", req=build_resource_list(cpu=1, memory="1Gi"))
        )
    cache = SchedulerCache(store)
    sched = Scheduler(cache, schedule_period=0.05)

    before = metrics.write_retries.value({"op": "bind"})
    faults.registry.arm("bind.write", count=2)
    sched.run_once()
    pods = store.list("pods")
    assert all(p.node_name == "n0" for p in pods), [p.node_name for p in pods]
    assert metrics.write_retries.value({"op": "bind"}) >= before + 2


def test_bind_rejection_beyond_retries_requeues_and_recovers():
    """bind.write with more failures than the retry budget: the bind
    falls to the errTasks resync queue, and once the fault clears the
    live loop still lands every bind — slower, never lost."""
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=0.05)
    srv.store.create_node(
        build_node("n0", build_resource_list(cpu=8, memory="8Gi", pods=16))
    )
    faults.registry.arm("bind.write", count=8)  # > retry budget of one cycle
    try:
        srv.start()
        for i in range(2):
            srv.store.create_pod(
                build_pod(name=f"p{i}", req=build_resource_list(cpu=1, memory="1Gi"))
            )
        wait_until(
            lambda: all(p.node_name for p in srv.store.list("pods")),
            what="binds land after injected write rejections",
        )
    finally:
        srv.stop()


# -- 4. watch hub ------------------------------------------------------------


def test_watch_drop_client_recovers_via_relist():
    """watch.drop: an injected stream drop surfaces as 410-Gone; a
    client following the k8s contract (re-list, resume from the returned
    resourceVersion) converges on the store's true state."""
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    base = f"http://127.0.0.1:{srv.listen_port}/apis/v1alpha1"
    try:
        def get(path):
            with urllib.request.urlopen(f"{base}/{path}", timeout=5) as r:
                return r.getcode(), json.loads(r.read())

        code, listing = get("queues")
        rv = listing["resourceVersion"]
        faults.registry.arm("watch.drop", count=1)
        try:
            code, _ = get(f"watch/queues?since={rv}&timeout=0.2")
            assert False, "expected 410 Gone from the injected drop"
        except urllib.error.HTTPError as e:
            assert e.code == 410

        # the contract: re-list, then resume watching from the fresh rv
        srv.store.create_queue(build_queue("tenant-a", weight=3))
        code, listing = get("queues")
        assert code == 200
        names = {q["name"] for q in listing["items"]}
        assert names == {"default", "tenant-a"}
        rv = listing["resourceVersion"]
        srv.store.create_queue(build_queue("tenant-b", weight=2))
        code, watch = get(f"watch/queues?since={rv}&timeout=5")
        assert code == 200
        assert [e["object"]["name"] for e in watch["events"]] == ["tenant-b"]
    finally:
        srv.stop()


def test_410_relist_storm_converges_and_staleness_gauge_recovers():
    """Satellite (ISSUE 3): repeated watch.drop firings under churn — a
    ResilientWatcher rides the storm via coalesced re-lists; once the
    drops stop, the mirror converges to store truth and the snapshot-age
    gauge returns to ~0."""
    from kube_batch_tpu.recovery import ResilientWatcher

    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    w = ResilientWatcher(
        f"http://127.0.0.1:{srv.listen_port}", ("queues",),
        poll_timeout=0.3, min_backoff=0.01, relist_min_interval=0.05,
    )
    try:
        w.start()
        wait_until(
            lambda: "default" in w.mirror["queues"], what="initial list lands"
        )
        relists_before = metrics.watch_relists.value({"kind": "queues"})
        # the storm: half of all watch polls drop while queues churn
        faults.registry.arm("watch.drop", probability=0.5, seed=11)
        for i in range(12):
            srv.store.create_queue(build_queue(f"storm{i}", weight=1 + i % 3))
            time.sleep(0.02)
        for i in range(0, 12, 2):
            srv.store.delete_queue(f"storm{i}")
        time.sleep(0.3)  # let several drops fire mid-churn
        faults.registry.reset()
        truth = {q.name for q in srv.store.list("queues")}
        wait_until(
            lambda: set(w.mirror["queues"]) == truth,
            what="mirror converges to store truth after the storm",
        )
        wait_until(
            lambda: w.snapshot_age() < 1.0,
            what="staleness gauge returns to ~0",
        )
        assert metrics.fault_injections.value({"point": "watch.drop"}) >= 1
        # recovery went through the re-list path, and the gauge metric
        # reflects the healthy age
        assert metrics.watch_relists.value({"kind": "queues"}) >= relists_before + 1
        assert metrics.watch_snapshot_age.value() < 1.0
    finally:
        w.stop()
        srv.stop()


# -- 5. lease elector --------------------------------------------------------


def test_lease_partition_fires_on_lost_within_deadline_and_releases():
    """lease.renew: every renewal round-trip fails (arbiter partition).
    on_lost must fire within the renew deadline — before the lease could
    expire under a standby — and the loss path's best-effort release lets
    the standby take over immediately instead of waiting out the lease."""
    store = ClusterStore()
    a = StoreLeaseElector(
        store, "kb-chaos", "a", lease_duration=30.0,
        renew_deadline=0.4, retry_period=0.1,
    )
    assert a.acquire(blocking=False)
    faults.registry.arm("lease.renew")
    lost = threading.Event()
    t0 = time.monotonic()
    a.start_renewing(lost.set)
    assert lost.wait(2.0), "partitioned leader never noticed"
    assert time.monotonic() - t0 < 2.0
    assert not a.is_leader
    # release landed despite the (renewal-only) fault: the 30s lease is
    # free NOW, not after expiry
    faults.registry.reset()
    b = StoreLeaseElector(
        store, "kb-chaos", "b", lease_duration=5.0,
        renew_deadline=4.0, retry_period=0.1,
    )
    assert b.acquire(blocking=False), "lease not released on loss"
    b.release()


# -- degradation ladder ------------------------------------------------------


def test_breaker_open_probe_close_cycle_unit():
    """The breaker automaton: threshold failures -> OPEN (allow False),
    backoff elapses -> HALF_OPEN probe, probe success -> CLOSED; a failed
    probe re-opens with doubled backoff. Transitions are metered."""
    ladder = DegradationLadder(
        ("pallas", "xla", "serial"), failure_threshold=2, reset_timeout=0.05
    )
    before = metrics.breaker_transitions.value(
        {"tier": "xla", "from": "closed", "to": "open"}
    )
    assert ladder.allow("xla") and ladder.allow("serial")
    ladder.record_failure("xla")
    assert ladder.state("xla") == CLOSED  # below threshold
    ladder.record_failure("xla")
    assert ladder.state("xla") == OPEN
    assert not ladder.allow("xla")
    assert ladder.allow("serial")  # the floor never opens
    time.sleep(0.06)
    assert ladder.allow("xla")  # admitted as the recovery probe
    assert ladder.state("xla") == HALF_OPEN
    ladder.record_failure("xla")  # failed probe: reopen, backoff doubled
    assert ladder.state("xla") == OPEN
    b = ladder.breakers["xla"]
    assert b._backoff == pytest.approx(0.1)
    time.sleep(0.11)
    assert ladder.allow("xla")
    ladder.record_success("xla")
    assert ladder.state("xla") == CLOSED
    assert b._backoff == pytest.approx(0.05)  # backoff reset on close
    assert (
        metrics.breaker_transitions.value({"tier": "xla", "from": "closed", "to": "open"})
        == before + 1
    )
    assert metrics.breaker_state.value({"tier": "xla"}) == 0.0


def test_breaker_open_probe_close_cycle_through_sessions(monkeypatch):
    """The same cycle driven by real scheduling sessions: repeated solve
    failures open the xla breaker (cycle degrades to serial *before*
    encoding), the backoff elapses, the next session is the probe and
    closes the breaker — binds identical throughout."""
    ladder = DegradationLadder(
        ("pallas", "xla", "serial"), failure_threshold=1, reset_timeout=0.1
    )
    monkeypatch.setattr(faults, "solver_ladder", ladder)
    clean, _ = run_xla_session()

    # cycle 1: injected solve failure -> serial degradation + breaker opens
    faults.registry.arm("solve.xla", count=1)
    b1, a1 = run_xla_session()
    assert "serial_degraded_s" in a1.last_timings
    assert ladder.state("xla") == OPEN
    assert b1 == clean

    # cycle 2: breaker open -> serial routed without touching the device
    before = metrics.degraded_cycles.value({"tier": "serial", "reason": "breaker_open"})
    b2, a2 = run_xla_session()
    assert "serial_degraded_s" in a2.last_timings
    assert (
        metrics.degraded_cycles.value({"tier": "serial", "reason": "breaker_open"})
        == before + 1
    )
    assert b2 == clean

    # cycle 3 (after backoff): the probe runs the device path and closes
    time.sleep(0.11)
    b3, a3 = run_xla_session()
    assert "solve_s" in a3.last_timings
    assert ladder.state("xla") == CLOSED
    assert b3 == clean


# -- metrics surface ---------------------------------------------------------


def test_fault_and_ladder_metrics_visible_on_metrics_endpoint():
    """Acceptance: fault and ladder-transition metrics are served on
    /metrics in Prometheus exposition format."""
    faults.registry.arm("watch.drop", count=1)
    srv = SchedulerServer(listen_address="127.0.0.1:0", schedule_period=5.0)
    srv.start()
    try:
        # fire the armed point through the real watch surface
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.listen_port}/apis/v1alpha1/watch/queues"
                "?since=0&timeout=0.1",
                timeout=5,
            )
        except urllib.error.HTTPError as e:
            assert e.code == 410
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.listen_port}/metrics", timeout=5
        ) as r:
            text = r.read().decode()
    finally:
        srv.stop()
    assert 'kube_batch_tpu_fault_injections_total{point="watch.drop"}' in text
    assert "kube_batch_tpu_breaker_state" in text
    assert 'tier="xla"' in text
    assert "kube_batch_tpu_breaker_transitions_total" in text
    assert "kube_batch_tpu_degraded_cycles_total" in text
    assert "kube_batch_tpu_write_retries_total" in text
    assert "kube_batch_tpu_cache_mutation_violations_total" in text


# -- cache-mutation detector (VERDICT row 58) --------------------------------


def test_mutation_detector_fires_on_seeded_violation():
    """The detector's contract: an object mutated in place (identity
    unchanged, content changed) fires; replaced objects don't."""
    import dataclasses

    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=4, memory="4Gi")))
    pod = build_pod(name="victim", req=build_resource_list(cpu=1, memory="1Gi"))
    store.create_pod(pod)

    det = MutationDetector(store)
    det.snapshot()
    # a legitimate write: replace through the store -> no violation
    store.update_pod(dataclasses.replace(pod, node_name="n0"))
    assert det.violations() == []
    # the seeded violation: in-place mutation of shared state
    store.list("nodes")[0].metadata.labels["mutated"] = "yes"
    before = metrics.cache_mutation_violations.value({"kind": "nodes"})
    with pytest.raises(CacheMutationError, match="nodes/n0"):
        det.verify()
    assert metrics.cache_mutation_violations.value({"kind": "nodes"}) == before + 1


def test_mutation_detector_catches_evil_action_through_run_once(monkeypatch):
    """Wired end-to-end: an action that mutates a cached Node in place
    (through the shared NodeInfo.node reference — session clones share
    the store's objects) is caught by the detector around run_once — the
    reference's KUBE_CACHE_MUTATION_DETECTOR role. A Pod would not do as
    the victim: binding legitimately REPLACES the store's pod object the
    same cycle, which correctly exempts it from the identity check."""
    monkeypatch.setenv("KBT_CACHE_MUTATION_DETECTOR", "1")
    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=8, memory="8Gi", pods=16)))
    store.create_queue(build_queue("default"))
    store.create_pod(build_pod(name="p0", req=build_resource_list(cpu=1, memory="1Gi")))
    cache = SchedulerCache(store)
    sched = Scheduler(cache, schedule_period=0.05)

    class EvilAction:
        name = "evil"

        def execute(self, ssn):
            for ni in ssn.nodes.values():
                ni.node.metadata.labels["evil"] = "1"

    sched.actions = list(sched.actions) + [EvilAction()]
    with pytest.raises(CacheMutationError, match="nodes/n0"):
        sched.run_once()


def test_mutation_detector_clean_cycle_passes(monkeypatch):
    """No false positive: a normal scheduling cycle (binds, status
    write-back, podgroup status) is clean under the detector."""
    monkeypatch.setenv("KBT_CACHE_MUTATION_DETECTOR", "1")
    store = ClusterStore()
    store.create_node(build_node("n0", build_resource_list(cpu=8, memory="8Gi", pods=16)))
    store.create_queue(build_queue("default"))
    store.create_pod_group(build_pod_group("g", min_member=2))
    for i in range(2):
        store.create_pod(
            build_pod(
                name=f"p{i}", group_name="g",
                req=build_resource_list(cpu=1, memory="1Gi"),
            )
        )
    cache = SchedulerCache(store)
    sched = Scheduler(cache, schedule_period=0.05)
    sched.run_once()
    sched.run_once()  # second cycle sees the bound pods round-tripped
    assert all(p.node_name for p in store.list("pods"))


# -- registry semantics ------------------------------------------------------


def test_registry_probability_and_seed_are_deterministic():
    """p<1 draws come from a per-point seeded RNG: the same spec replays
    the same fire pattern."""
    def pattern():
        reg = faults.FaultRegistry(spec="", seed=7)
        reg.arm("watch.drop", probability=0.5)
        return [reg.should_fire("watch.drop") for _ in range(32)]

    p1, p2 = pattern(), pattern()
    assert p1 == p2
    assert any(p1) and not all(p1)  # actually probabilistic


def test_registry_count_and_spec_grammar():
    reg = faults.FaultRegistry(spec="bind.write:1:2,watch.drop:0.5,bogus:1")
    active = reg.active()
    assert set(active) == {"bind.write", "watch.drop"}  # bogus rejected
    assert active["bind.write"] == (1.0, 2, 0)
    assert reg.should_fire("bind.write") and reg.should_fire("bind.write")
    assert not reg.should_fire("bind.write")  # count exhausted
    reg.configure("bind.write:off")
    assert "bind.write" not in reg.active()
    with pytest.raises(ValueError, match="unknown fault point"):
        reg.arm("no.such.point")
