"""xla_preempt ≡ preempt: the vectorized candidate-scan's oracle.

The serial preempt action is the reference implementation (pinned against
preempt_test.go semantics in test_actions.py); these tests assert the
vectorized scan (actions/xla_preempt.py) produces the same evictions and
pipelines in the same order — scenarios plus a randomized contention
sweep with running victims, exactly the preempt_mix shape (VERDICT r2
item 6's done-criterion).
"""

import random

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.apis.types import Affinity, PodAffinityTerm, PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.models import preempt_mix
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

PREEMPT_TIERS = """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def run_and_capture(action_name, cluster):
    cache = FakeCache(cluster)
    ssn = open_session(cache, parse_scheduler_conf(PREEMPT_TIERS).tiers)
    get_action(action_name).execute(ssn)
    state = {}
    for job in ssn.jobs.values():
        for tasks in job.task_status_index.values():
            for t in tasks.values():
                state[t.uid] = (t.status, t.node_name)
    close_session(ssn)
    return state, list(cache.evictor.evicts)


def assert_equivalent(make_cluster):
    s_state, s_evicts = run_and_capture("preempt", make_cluster())
    x_state, x_evicts = run_and_capture("xla_preempt", make_cluster())
    assert x_evicts == s_evicts
    assert x_state == s_state


def gen_contended_cluster(seed: int):
    """Random preemption scene: low-priority gang jobs running on full
    nodes, starved higher-priority jobs pending in the same queues."""
    rng = random.Random(seed)
    n_queues = rng.randint(1, 2)
    queues = [build_queue(f"q{i}", weight=rng.randint(1, 3)) for i in range(n_queues)]
    for i, q in enumerate(queues):
        q.metadata.creation_timestamp = float(i)

    nodes, pods, pgs = [], [], []
    n_nodes = rng.randint(2, 8)
    for i in range(n_nodes):
        labels = {"zone": rng.choice(["a", "b"])} if rng.random() < 0.3 else {}
        nodes.append(
            build_node(
                f"n{i:02d}",
                build_resource_list(cpu=2, memory="2Gi", pods=rng.randint(3, 8)),
                labels=labels,
            )
        )

    # running low-priority victims (grouped => preemptable via job filter);
    # each node fits two 1cpu/1Gi runners
    free = [2] * n_nodes
    slot = 0
    for j in range(rng.randint(1, 3)):
        name = f"low{j}"
        n_tasks = rng.randint(1, 4)
        pgs.append(
            build_pod_group(
                name, queue=rng.choice(queues).name, min_member=rng.randint(0, 1)
            )
        )
        for t in range(n_tasks):
            while slot < 2 * n_nodes and free[slot % n_nodes] == 0:
                slot += 1
            if slot >= 2 * n_nodes:
                break
            node = nodes[slot % n_nodes]
            free[slot % n_nodes] -= 1
            slot += 1
            pods.append(
                build_pod(
                    name=f"{name}-t{t}",
                    group_name=name,
                    node_name=node.name,
                    phase=PodPhase.RUNNING,
                    req=build_resource_list(cpu=1, memory="1Gi"),
                    priority=1,
                )
            )

    # pending high-priority preemptors
    for j in range(rng.randint(1, 3)):
        name = f"high{j}"
        n_tasks = rng.randint(1, 3)
        pgs.append(
            build_pod_group(
                name, queue=rng.choice(queues).name, min_member=rng.randint(1, n_tasks)
            )
        )
        for t in range(n_tasks):
            pod = build_pod(
                name=f"{name}-t{t}",
                group_name=name,
                req=build_resource_list(
                    cpu=rng.choice([1, 2]), memory=rng.choice(["512Mi", "1Gi"])
                ),
                priority=rng.choice([5, 9]),
            )
            if rng.random() < 0.2:
                pod.node_selector = {"zone": rng.choice(["a", "b"])}
            pods.append(pod)

    return build_cluster(pods, nodes, pgs, queues)


def test_simple_preemption_parity():
    def mk():
        victims = [
            build_pod(
                name=f"low-p{i}",
                group_name="low",
                req=build_resource_list(cpu=1, memory="512Mi"),
                node_name=f"n{i}",
                phase=PodPhase.RUNNING,
                priority=1,
            )
            for i in range(2)
        ]
        preemptor = build_pod(
            name="high-p0",
            group_name="high",
            req=build_resource_list(cpu=1, memory="512Mi"),
            priority=9,
        )
        nodes = [
            build_node(f"n{i}", build_resource_list(cpu=1, memory="1Gi", pods=5))
            for i in range(2)
        ]
        return build_cluster(
            victims + [preemptor],
            nodes,
            [build_pod_group("low", min_member=1), build_pod_group("high", min_member=1)],
            [build_queue("default")],
        )

    s_state, s_evicts = run_and_capture("preempt", mk())
    x_state, x_evicts = run_and_capture("xla_preempt", mk())
    assert len(x_evicts) == 1
    assert x_evicts == s_evicts
    assert x_state == s_state


def test_property_contended_parity():
    for seed in range(24):
        s_state, s_evicts = run_and_capture("preempt", gen_contended_cluster(seed))
        x_state, x_evicts = run_and_capture("xla_preempt", gen_contended_cluster(seed))
        assert x_evicts == s_evicts, f"seed {seed}: evict order diverged"
        assert x_state == s_state, f"seed {seed}: state diverged"


def test_preempt_mix_residents_parity():
    """The north-star config's shape at test scale: priority bands over
    nodes partially occupied by (some terminating) residents."""
    assert_equivalent(lambda: preempt_mix(400, 40, tasks_per_job=10))


def test_pod_affinity_preemptor_takes_serial_path():
    """A preemptor with required pod-affinity is host-only: the scan
    returns None and the serial predicate walk must produce the same
    outcome as the serial action."""

    def mk():
        cluster = gen_contended_cluster(3)
        # attach required pod-affinity to one pending task
        for job in cluster.jobs.values():
            for task in job.tasks.values():
                if task.pod.node_name == "" and task.pod.affinity is None:
                    task.pod.affinity = Affinity(
                        pod_affinity_required=[
                            PodAffinityTerm(
                                label_selector={"app": "web"},
                                topology_key="kubernetes.io/hostname",
                            )
                        ]
                    )
                    return cluster
        return cluster

    assert_equivalent(mk)


def test_out_of_envelope_conf_falls_back_serial():
    """A conf whose plugin set the scan does not model — here one without
    the predicates plugin (the serial chain would treat every node as
    feasible while the scan still applies its hardwired masks) — must
    route xla_preempt/xla_reclaim through the serial actions."""
    no_predicates = """
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: nodeorder
"""
    tiers = parse_scheduler_conf(no_predicates).tiers

    def run(action_name):
        cache = FakeCache(gen_contended_cluster(5))
        ssn = open_session(cache, tiers)
        get_action(action_name).execute(ssn)
        close_session(ssn)
        return dict(cache.binder.binds), list(cache.evictor.evicts)

    assert run("xla_preempt") == run("preempt")
    assert run("xla_reclaim") == run("reclaim")
