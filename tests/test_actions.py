"""Action-level integration tests in the reference's pattern
(actions/allocate/allocate_test.go:38-212, preempt_test.go:37,
reclaim_test.go:37): real model + real event handlers + fake write-side,
one action.Execute, assert on FakeBinder.binds."""

from kube_batch_tpu import actions  # noqa: F401  (registers actions)
from kube_batch_tpu import plugins  # noqa: F401  (registers plugins)
from kube_batch_tpu.apis.types import PodPhase
from kube_batch_tpu.conf import parse_scheduler_conf
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.testing import (
    FakeCache,
    build_cluster,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
    build_resource_list,
)

DEFAULT_TIERS_YAML = """
actions: "allocate"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""


def default_tiers():
    return parse_scheduler_conf(DEFAULT_TIERS_YAML).tiers


def run_action(name, cache, tiers=None):
    ssn = open_session(cache, tiers if tiers is not None else default_tiers())
    get_action(name).execute(ssn)
    close_session(ssn)
    return ssn


def one_slot_nodes(n):
    return [
        build_node(f"n{i}", build_resource_list(cpu=1, memory="1Gi", pods=10))
        for i in range(n)
    ]


def gang_pods(job, count, cpu=1):
    return [
        build_pod(
            name=f"{job}-p{i}",
            group_name=job,
            req=build_resource_list(cpu=cpu, memory="512Mi"),
        )
        for i in range(count)
    ]


class TestAllocate:
    def test_gang_min_member_3_binds_atomically(self):
        """minMember=3 on 3 one-slot nodes: all 3 binds land
        (allocate_test.go case 'prepredicate').'"""
        cache = FakeCache(
            build_cluster(
                gang_pods("pg1", 3),
                one_slot_nodes(3),
                [build_pod_group("pg1", min_member=3)],
                [build_queue("default")],
            )
        )
        run_action("allocate", cache)
        assert len(cache.binder.binds) == 3
        assert sorted(cache.binder.binds) == ["default/pg1-p0", "default/pg1-p1", "default/pg1-p2"]
        # Each pod on a distinct node (1-cpu slots).
        assert len(set(cache.binder.binds.values())) == 3

    def test_gang_min_member_4_with_3_slots_binds_nothing(self):
        """Gang barrier: not enough capacity for minMember -> zero binds."""
        cache = FakeCache(
            build_cluster(
                gang_pods("pg1", 4),
                one_slot_nodes(3),
                [build_pod_group("pg1", min_member=4)],
                [build_queue("default")],
            )
        )
        run_action("allocate", cache)
        assert cache.binder.binds == {}

    def test_gang_min_member_4_with_3_pods_rejected_at_open(self):
        """JobValid gate: 3 valid tasks < minMember 4 -> job never enters
        the session; the PodGroup gets an Unschedulable condition."""
        pg = build_pod_group("pg1", min_member=4)
        cache = FakeCache(
            build_cluster(gang_pods("pg1", 3), one_slot_nodes(5), [pg], [build_queue("default")])
        )
        ssn = open_session(cache, default_tiers())
        assert ssn.jobs == {}
        conds = pg.status.conditions
        assert conds and conds[0].type == "Unschedulable"
        assert conds[0].reason == "NotEnoughTasks"

    def test_min_member_1_partial_binds(self):
        """minMember=1: every task binds as soon as it is allocated."""
        cache = FakeCache(
            build_cluster(
                gang_pods("pg1", 5),
                one_slot_nodes(3),
                [build_pod_group("pg1", min_member=1)],
                [build_queue("default")],
            )
        )
        run_action("allocate", cache)
        assert len(cache.binder.binds) == 3  # capacity-bound

    def test_best_effort_tasks_skipped(self):
        pods = [build_pod(name="be", group_name="pg1", req={})]
        cache = FakeCache(
            build_cluster(
                pods, one_slot_nodes(1), [build_pod_group("pg1", min_member=1)], [build_queue("default")]
            )
        )
        run_action("allocate", cache)
        assert cache.binder.binds == {}

    def test_node_selector_respected(self):
        pod = build_pod(
            name="gpu-pod",
            group_name="pg1",
            req=build_resource_list(cpu=1),
            node_selector={"accel": "tpu"},
        )
        nodes = [
            build_node("plain", build_resource_list(cpu=4, memory="4Gi", pods=10)),
            build_node(
                "tpu-node",
                build_resource_list(cpu=4, memory="4Gi", pods=10),
                labels={"accel": "tpu"},
            ),
        ]
        cache = FakeCache(
            build_cluster([pod], nodes, [build_pod_group("pg1", min_member=1)], [build_queue("default")])
        )
        run_action("allocate", cache)
        assert cache.binder.binds == {"default/gpu-pod": "tpu-node"}

    def test_least_requested_spreads_load(self):
        """nodeorder least-requested: second pod lands on the emptier node."""
        busy = build_pod(
            name="resident",
            req=build_resource_list(cpu=3),
            node_name="n0",
            phase=PodPhase.RUNNING,
        )
        incoming = build_pod(name="new", group_name="pg1", req=build_resource_list(cpu=1))
        nodes = [
            build_node("n0", build_resource_list(cpu=4, memory="4Gi", pods=10)),
            build_node("n1", build_resource_list(cpu=4, memory="4Gi", pods=10)),
        ]
        cache = FakeCache(
            build_cluster(
                [busy, incoming],
                nodes,
                [build_pod_group("pg1", min_member=1)],
                [build_queue("default")],
            )
        )
        run_action("allocate", cache)
        assert cache.binder.binds == {"default/new": "n1"}

    def test_two_queues_share_cluster(self):
        """proportion: two weight-1 queues with competing jobs both make
        progress."""
        pods = gang_pods("qa-job", 2) + [
            build_pod(
                name=f"qb-job-p{i}",
                group_name="qb-job",
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
            for i in range(2)
        ]
        groups = [
            build_pod_group("qa-job", queue="qa", min_member=1),
            build_pod_group("qb-job", queue="qb", min_member=1),
        ]
        cache = FakeCache(
            build_cluster(
                pods, one_slot_nodes(2), groups, [build_queue("qa"), build_queue("qb")]
            )
        )
        run_action("allocate", cache)
        assert len(cache.binder.binds) == 2
        owners = {k.split("/")[1].rsplit("-", 1)[0] for k in cache.binder.binds}
        assert owners == {"qa-job", "qb-job"}


class TestBackfill:
    def test_best_effort_pod_backfilled(self):
        pods = [build_pod(name="be", group_name="pg1", req={})]
        cache = FakeCache(
            build_cluster(
                pods, one_slot_nodes(1), [build_pod_group("pg1", min_member=1)], [build_queue("default")]
            )
        )
        run_action("backfill", cache)
        assert list(cache.binder.binds) == ["default/be"]


class TestPreempt:
    def _contended_cluster(self, preemptor_prio=10, victim_prio=1):
        victims = [
            build_pod(
                name=f"low-p{i}",
                group_name="low",
                req=build_resource_list(cpu=1, memory="512Mi"),
                node_name=f"n{i}",
                phase=PodPhase.RUNNING,
                priority=victim_prio,
            )
            for i in range(2)
        ]
        preemptors = [
            build_pod(
                name="high-p0",
                group_name="high",
                req=build_resource_list(cpu=1, memory="512Mi"),
                priority=preemptor_prio,
            )
        ]
        groups = [
            build_pod_group("low", min_member=1),
            build_pod_group("high", min_member=1),
        ]
        return build_cluster(
            victims + preemptors, one_slot_nodes(2), groups, [build_queue("default")]
        )

    def test_high_priority_preempts_running_low(self):
        cache = FakeCache(self._contended_cluster())
        tiers = parse_scheduler_conf(
            """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: drf
  - name: predicates
  - name: nodeorder
"""
        ).tiers
        run_action("preempt", cache, tiers)
        assert len(cache.evictor.evicts) == 1
        assert cache.evictor.evicts[0].startswith("default/low-p")

    def test_gang_protects_min_available(self):
        """Victim job with minMember=2 and exactly 2 running tasks: gang
        vetoes eviction (ready would drop below min)."""
        cluster = self._contended_cluster()
        low_job = next(j for j in cluster.jobs.values() if j.name == "low")
        low_job.min_available = 2
        low_job.pod_group.spec.min_member = 2
        cache = FakeCache(cluster)
        tiers = parse_scheduler_conf(
            """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""
        ).tiers
        run_action("preempt", cache, tiers)
        assert cache.evictor.evicts == []

    def test_conformance_protects_critical_pods(self):
        cluster = self._contended_cluster()
        for job in cluster.jobs.values():
            if job.name == "low":
                for task in job.tasks.values():
                    task.pod.priority_class_name = "system-cluster-critical"
        cache = FakeCache(cluster)
        tiers = parse_scheduler_conf(
            """
actions: "preempt"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: predicates
  - name: nodeorder
"""
        ).tiers
        run_action("preempt", cache, tiers)
        assert cache.evictor.evicts == []


class TestReclaim:
    def test_underserved_queue_reclaims_from_overused(self):
        """qa hogs both nodes; qb's pending task reclaims one via
        proportion's deserved share."""
        running = [
            build_pod(
                name=f"qa-p{i}",
                group_name="qa-job",
                req=build_resource_list(cpu=1, memory="512Mi"),
                node_name=f"n{i}",
                phase=PodPhase.RUNNING,
            )
            for i in range(2)
        ]
        pending = [
            build_pod(
                name="qb-p0",
                group_name="qb-job",
                req=build_resource_list(cpu=1, memory="512Mi"),
            )
        ]
        groups = [
            build_pod_group("qa-job", queue="qa", min_member=1),
            build_pod_group("qb-job", queue="qb", min_member=1),
        ]
        cache = FakeCache(
            build_cluster(
                running + pending,
                one_slot_nodes(2),
                groups,
                [build_queue("qa"), build_queue("qb")],
            )
        )
        tiers = parse_scheduler_conf(
            """
actions: "reclaim"
tiers:
- plugins:
  - name: priority
  - name: gang
  - name: conformance
- plugins:
  - name: proportion
  - name: predicates
  - name: nodeorder
"""
        ).tiers
        run_action("reclaim", cache, tiers)
        assert len(cache.evictor.evicts) == 1
        assert cache.evictor.evicts[0].startswith("default/qa-p")


class TestEnqueue:
    def test_pending_group_with_fitting_min_resources_inqueued(self):
        pg = build_pod_group(
            "pg1", min_member=1, min_resources=build_resource_list(cpu=1, memory="512Mi")
        )
        from kube_batch_tpu.apis.types import PodGroupPhase

        cluster = build_cluster([], one_slot_nodes(1), [pg], [build_queue("default")])
        # build_cluster promotes Pending->Inqueue; force back to Pending to
        # exercise the enqueue gate itself.
        pg.status.phase = PodGroupPhase.PENDING
        cache = FakeCache(cluster)
        run_action("enqueue", cache)
        assert pg.status.phase == PodGroupPhase.INQUEUE

    def test_oversized_group_stays_pending(self):
        pg = build_pod_group(
            "pg1", min_member=1, min_resources=build_resource_list(cpu=100)
        )
        from kube_batch_tpu.apis.types import PodGroupPhase

        cluster = build_cluster([], one_slot_nodes(1), [pg], [build_queue("default")])
        pg.status.phase = PodGroupPhase.PENDING
        cache = FakeCache(cluster)
        run_action("enqueue", cache)
        assert pg.status.phase == PodGroupPhase.PENDING

    def test_overcommit_factor_admits_1_2x(self):
        """Idle headroom is 1.2x allocatable (enqueue.go:80)."""
        pg = build_pod_group(
            "pg1", min_member=1, min_resources=build_resource_list(cpu="1100m")
        )
        from kube_batch_tpu.apis.types import PodGroupPhase

        cluster = build_cluster([], one_slot_nodes(1), [pg], [build_queue("default")])
        pg.status.phase = PodGroupPhase.PENDING
        cache = FakeCache(cluster)
        run_action("enqueue", cache)
        # 1.1 cpu fits under 1.2 * 1 cpu.
        assert pg.status.phase == PodGroupPhase.INQUEUE


class TestSessionClose:
    def test_pod_group_status_written_back(self):
        pg = build_pod_group("pg1", min_member=1)
        cache = FakeCache(
            build_cluster(gang_pods("pg1", 2), one_slot_nodes(2), [pg], [build_queue("default")])
        )
        run_action("allocate", cache)
        # 2 allocated > minMember 1 -> Running (session.go:176, strict >).
        from kube_batch_tpu.apis.types import PodGroupPhase

        assert pg.status.phase == PodGroupPhase.RUNNING
